"""Multi-head attention.

Reference: src/ops/attention.cc (926 LoC) + attention.cu — one monolithic
cudnnMultiHeadAttnForward call (attention.cu:35) with packed qkv/out
weights; head-partition parallelism comes from the
create_partition_attention_combine / create_replicate_attention_reduce
substitutions (substitution.cc:1762-1770).

TPU-first re-design: explicit per-projection weights shaped
[embed, heads, head_dim] so the **heads dim is a first-class shardable
dim** (ShardConfig.channel = head degree, the TP axis); the score/value
matmuls are dot_generals on the MXU in bf16; output-projection
contraction over heads yields a partial-sum output (replica degree =
head degree) exactly like the reference's Reduction-consumed attention
output.  Sequence parallelism for long context is handled by ring
attention over the mesh's "seq" axis (flexflow_tpu/parallel/
ring_attention.py) — a capability the reference lacks (SURVEY §5).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.shard_map_compat import shard_map as _shard_map

from ..fftype import DataType, OperatorType
from ..initializer import DEFAULT_WEIGHT_INIT, GlorotUniform
from ..tensor import ParallelDim, ParallelTensorShape
from .op import Op, ShapeError, WeightSpec


# force the flash kernel when the per-device [b, h, q, k] score tensor
# would exceed this, regardless of flash_min_seq — OOM insurance for the
# non-flash branch, which counts on XLA fusing the scores away
_FLASH_FORCE_SCORE_BYTES = 2 << 30


@dataclasses.dataclass(frozen=True)
class MultiHeadAttentionParams:
    embed_dim: int
    num_heads: int
    kdim: int = 0  # 0 -> embed_dim
    vdim: int = 0
    dropout: float = 0.0
    use_bias: bool = False
    add_bias_kv: bool = False
    add_zero_attn: bool = False
    causal: bool = False

    @property
    def k_channels(self) -> int:
        return (self.kdim or self.embed_dim) // self.num_heads

    @property
    def v_channels(self) -> int:
        return (self.vdim or self.embed_dim) // self.num_heads


class MultiHeadAttention(Op):
    op_type = OperatorType.MULTIHEAD_ATTENTION

    def __init__(self, params, inputs, name="", shard=None,
                 decode_max_seq: int = 0, kv_page_size: int = 0,
                 kv_num_blocks: int = 0, kv_kernel: str = "gather"):
        from .op import ShardConfig

        # must exist before Op.__init__ runs make_weight_specs
        self._decode_max_seq = int(decode_max_seq)
        self._kv_page_size = int(kv_page_size)
        self._kv_num_blocks = int(kv_num_blocks)
        # paged READ formulation: "gather" materializes the dense
        # [b, N, h, d] view (the bit-identity oracle); "pallas" streams
        # blocks in place through the fused kernel
        # (ops/pallas/paged_attention.py).  Callers validate the value
        # and Pallas availability BEFORE building the graph
        # (config.resolve_paged_kernel).
        self._kv_kernel = str(kv_kernel or "gather")
        super().__init__(params, inputs, name=name,
                         shard=shard or ShardConfig())

    def infer_output_shapes(self, input_shapes):
        q, k, v = input_shapes
        p: MultiHeadAttentionParams = self.params
        qd = [d for d in q.dims if not d.is_replica_dim]
        kd = [d for d in k.dims if not d.is_replica_dim]
        vd = [d for d in v.dims if not d.is_replica_dim]
        if len(qd) != 3:
            raise ShapeError(f"{self.name}: expect [batch, seq, embed] inputs")
        if p.num_heads % self.shard.channel != 0:
            raise ShapeError(f"{self.name}: heads {p.num_heads} not divisible by "
                             f"degree {self.shard.channel}")
        if qd[1].degree != 1 or kd[1].degree != 1 or vd[1].degree != 1:
            # Seq partitioning lowers to ring attention — legal only
            # when q/k/v share one seq sharding (self-attention SP).
            # (Runtime dispatch keys on q's seq degree, so q-only
            # sharding must be validated here too.)
            if not (qd[1].degree == kd[1].degree == vd[1].degree):
                raise ShapeError(
                    f"{self.name}: ring attention needs equal q/k/v seq "
                    f"degrees, got {qd[1].degree}/{kd[1].degree}/{vd[1].degree}"
                )
            if self.params.add_bias_kv or self.params.add_zero_attn:
                raise ShapeError(
                    f"{self.name}: kv-append options unsupported with "
                    f"sequence sharding"
                )
            if self.params.dropout > 0.0:
                raise ShapeError(
                    f"{self.name}: attention dropout unsupported with "
                    f"sequence sharding (ring attention)"
                )
        ri = q.replica_degree
        c = self.shard.channel
        if c > 1 and ri % c == 0:
            ri //= c
        dims = (
            ParallelDim(qd[0].size, qd[0].degree),
            ParallelDim(qd[1].size, qd[1].degree),
            ParallelDim(p.embed_dim, 1),
            ParallelDim(1, ri * c, is_replica_dim=True),  # head-contraction partials
        )
        return [ParallelTensorShape(dims, q.dtype)]

    # -- KV-cache decode mode -------------------------------------------
    # Set op._decode_max_seq = N (before compile) to run this attention
    # as an incremental decoder: per-step q/k/v of seq length 1, k/v
    # appended into fixed-shape [b, N, h, d] cache state carried through
    # the op-state pytree (the BatchNorm running-stats convention), so
    # generation is O(T) instead of re-running the full forward per
    # token.  The reference has no incremental decoding at all (its
    # legacy nmt/ re-runs the graph; triton/ is an incomplete
    # prototype) — this is TPU-native serving machinery.
    def _decode_n(self) -> int:
        return int(getattr(self, "_decode_max_seq", 0) or 0)

    # Paged decode mode (serving/kv_pool.py, the vLLM PagedAttention
    # design, SOSP'23): instead of one dense [b, N, h, d] cache per
    # sequence slot, k/v live in a POOL of fixed-size blocks
    # [num_blocks, page, h, d] shared by all slots; a per-slot block
    # table [b, N/page] maps logical block -> physical block and a
    # per-slot seq_lens [b] carries each row's own position (continuous
    # batching runs rows at different positions in one step).  The
    # block table and seq_lens are HOST-owned (the scheduler allocates
    # on extend / frees on retire and rewrites them between steps);
    # in-graph they are read-only and returned unchanged.
    def _paged(self) -> bool:
        return self._decode_n() > 0 and \
            int(getattr(self, "_kv_page_size", 0) or 0) > 0

    def ctor_kwargs(self) -> dict:
        n = self._decode_n()
        if not n:
            return {}
        kw = {"decode_max_seq": n}
        if self._paged():
            kw["kv_page_size"] = self._kv_page_size
            kw["kv_num_blocks"] = self._kv_num_blocks
            if getattr(self, "_kv_kernel", "gather") != "gather":
                kw["kv_kernel"] = self._kv_kernel
        return kw

    def num_trainable_weights(self) -> int:
        n = 4
        p: MultiHeadAttentionParams = self.params
        if p.use_bias:
            n += 4
        if p.add_bias_kv:
            n += 2
        return n

    def make_weight_specs(self, input_shapes):
        q, k, v = input_shapes
        p: MultiHeadAttentionParams = self.params
        qd = [d for d in q.dims if not d.is_replica_dim]
        batch_degree = qd[0].degree * qd[1].degree
        c = self.shard.channel
        dt = q.dtype

        def w(shape_sizes, head_axis):
            dims = []
            for i, s in enumerate(shape_sizes):
                dims.append(ParallelDim(s, c if i == head_axis else 1))
            extra = batch_degree if head_axis is not None else batch_degree * c
            dims.append(ParallelDim(1, extra, is_replica_dim=True))
            return ParallelTensorShape(tuple(dims), dt)

        embed = p.embed_dim
        init = GlorotUniform(fan_in=embed, fan_out=embed)
        specs = [
            WeightSpec("wq", w((embed, p.num_heads, p.k_channels), 1), init),
            WeightSpec("wk", w((k.logical_shape[-1], p.num_heads, p.k_channels), 1), init),
            WeightSpec("wv", w((v.logical_shape[-1], p.num_heads, p.v_channels), 1), init),
            WeightSpec("wo", w((p.num_heads, p.v_channels, embed), 0), init),
        ]
        from ..initializer import ZeroInitializer

        zero = ZeroInitializer()
        if p.use_bias:
            specs += [
                WeightSpec("bq", w((p.num_heads, p.k_channels), 0), zero),
                WeightSpec("bk", w((p.num_heads, p.k_channels), 0), zero),
                WeightSpec("bv", w((p.num_heads, p.v_channels), 0), zero),
                WeightSpec("bo", w((embed,), None), zero),
            ]
        if p.add_bias_kv:
            # one learnable bias token appended to the k/v sequences
            specs += [
                WeightSpec("bias_k", w((1, p.num_heads, p.k_channels), 1), init),
                WeightSpec("bias_v", w((1, p.num_heads, p.v_channels), 1), init),
            ]
        n = self._decode_n()
        if n > 0:
            if p.add_bias_kv or p.add_zero_attn:
                raise ShapeError(
                    f"{self.name}: kv-append options unsupported in "
                    "decode mode"
                )
            if qd[1].degree != 1:
                raise ShapeError(
                    f"{self.name}: decode mode needs an unsharded seq dim"
                )

            if self._paged():
                return specs + self._paged_state_specs(qd, dt)

            def cache(d_head):
                dims = (
                    ParallelDim(qd[0].size, qd[0].degree),
                    ParallelDim(n),
                    ParallelDim(p.num_heads, c),
                    ParallelDim(d_head),
                    ParallelDim(1, q.replica_degree, is_replica_dim=True),
                )
                return ParallelTensorShape(dims, dt)

            pos_shape = ParallelTensorShape(
                (ParallelDim(1),
                 ParallelDim(1, q.total_degree, is_replica_dim=True)),
                DataType.INT32,
            )
            specs += [
                WeightSpec("k_cache", cache(p.k_channels), zero),
                WeightSpec("v_cache", cache(p.v_channels), zero),
                WeightSpec("cache_pos", pos_shape, zero),
            ]
        return specs

    def _paged_state_specs(self, qd, dt):
        """State specs for paged decode: block-pool k/v caches plus the
        host-owned per-slot block table and sequence lengths."""
        from ..initializer import ZeroInitializer

        p: MultiHeadAttentionParams = self.params
        n, page, nb = self._decode_n(), self._kv_page_size, \
            self._kv_num_blocks
        if not 1 <= qd[1].size <= n:
            # seq length C > 1 is the CHUNKED-PREFILL twin
            # (decoding.build_paged_chunk_step): C tokens scattered at
            # each row's own positions per step, causal within the
            # chunk.  seq 1 remains the decode twin.
            raise ShapeError(
                f"{self.name}: paged decode chunk must be within [1, "
                f"decode_max_seq={n}], got {qd[1].size}"
            )
        if qd[0].degree != 1:
            # head (channel) sharding IS supported — the pool shards
            # its head dim over the 'model' axis below, the block
            # scatter/gather index only the block/page dims, and the
            # Pallas dispatch shard_maps over heads.  Batch sharding is
            # not: slots are scheduler-owned host state, and splitting
            # them would split the block table.
            raise ShapeError(
                f"{self.name}: paged decode mode needs an unsharded "
                "batch dim (slots are host-owned; use head "
                "tensor-parallelism via ShardConfig.channel instead)"
            )
        if page < 1 or n % page:
            raise ShapeError(
                f"{self.name}: kv_page_size {page} must divide "
                f"decode_max_seq {n} (the gathered view must equal the "
                "dense cache shape for bit-identical attention)"
            )
        if nb < 2:
            raise ShapeError(
                f"{self.name}: kv_num_blocks {nb} < 2 (block 0 is the "
                "scratch block idle slots write into)"
            )
        zero = ZeroInitializer()

        def pool(d_head):
            # head dim carries the channel (tp) degree: the pool shards
            # [nb, page, h/tp, d] per chip — per-chip KV bytes are 1/tp
            # — while the block scatter/gather address only the
            # unsharded block/page dims, so the host-owned block
            # table / COW / prefix-sharing plumbing never sees the
            # sharding.
            dims = (
                ParallelDim(nb), ParallelDim(page),
                ParallelDim(p.num_heads, self.shard.channel),
                ParallelDim(d_head),
                ParallelDim(1, 1, is_replica_dim=True),
            )
            return ParallelTensorShape(dims, dt)

        def ints(*sizes):
            dims = tuple(ParallelDim(s) for s in sizes) + (
                ParallelDim(1, 1, is_replica_dim=True),)
            return ParallelTensorShape(dims, DataType.INT32)

        return [
            WeightSpec("k_cache", pool(p.k_channels), zero),
            WeightSpec("v_cache", pool(p.v_channels), zero),
            WeightSpec("block_table", ints(qd[0].size, n // page), zero),
            WeightSpec("seq_lens", ints(qd[0].size), zero),
        ]

    def forward(self, inputs, weights, *, training=False, rng=None):
        q, k, v = inputs
        p: MultiHeadAttentionParams = self.params
        wq, wk, wv, wo = weights[:4]
        wi = 4
        # [b, s, e] x [e, h, d] -> [b, s, h, d]
        qh = jnp.einsum("bse,ehd->bshd", q, wq)
        kh = jnp.einsum("bse,ehd->bshd", k, wk)
        vh = jnp.einsum("bse,ehd->bshd", v, wv)
        bo = None
        if p.use_bias:
            bq, bk, bv, bo = weights[wi : wi + 4]
            wi += 4
            qh = qh + bq[None, None]
            kh = kh + bk[None, None]
            vh = vh + bv[None, None]
        if p.add_bias_kv:
            bias_k, bias_v = weights[wi : wi + 2]
            wi += 2
            bsz = kh.shape[0]
            kh = jnp.concatenate([kh, jnp.broadcast_to(bias_k[None], (bsz,) + bias_k.shape)], axis=1)
            vh = jnp.concatenate([vh, jnp.broadcast_to(bias_v[None], (bsz,) + bias_v.shape)], axis=1)
        if p.add_zero_attn:
            bsz, _, h, dk = kh.shape
            dv = vh.shape[-1]
            kh = jnp.concatenate([kh, jnp.zeros((bsz, 1, h, dk), kh.dtype)], axis=1)
            vh = jnp.concatenate([vh, jnp.zeros((bsz, 1, h, dv), vh.dtype)], axis=1)
        scale = 1.0 / np.sqrt(p.k_channels)
        if self._paged():
            k_cache, v_cache, btab, slen = weights[-4:]
            ctx, k_cache, v_cache = self._attend_decode_paged(
                qh, kh, vh, k_cache, v_cache, btab, slen, scale
            )
            out = jnp.einsum("bqhd,hde->bqe", ctx, wo)
            if bo is not None:
                out = out + bo[None, None]
            return [out.astype(q.dtype), k_cache, v_cache, btab, slen]
        if self._decode_n() > 0:
            k_cache, v_cache, pos = weights[-3], weights[-2], weights[-1]
            ctx, k_cache, v_cache, pos = self._attend_decode(
                qh, kh, vh, k_cache, v_cache, pos, scale
            )
            out = jnp.einsum("bqhd,hde->bqe", ctx, wo)
            if bo is not None:
                out = out + bo[None, None]
            return [out.astype(q.dtype), k_cache, v_cache, pos]
        ctx = self._attend(qh, kh, vh, scale, training=training, rng=rng)
        out = jnp.einsum("bqhd,hde->bqe", ctx, wo)
        if bo is not None:
            out = out + bo[None, None]
        return [out.astype(q.dtype)]

    def _attend_decode(self, qh, kh, vh, k_cache, v_cache, pos, scale):
        """Incremental attention: append this step's k/v at position
        `pos` (a [1] int32 carried in op state), attend the new queries
        over the cache prefix.  q/k/v seq length is the step size
        (usually 1); causality across steps comes from masking cache
        positions beyond pos, within-step causality from the usual
        triangular mask."""
        p: MultiHeadAttentionParams = self.params
        s = qh.shape[1]
        pos0 = pos.reshape(())  # scalar current length
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, kh.astype(k_cache.dtype), (0, pos0, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, vh.astype(v_cache.dtype), (0, pos0, 0, 0)
        )
        n = k_cache.shape[1]
        key_pos = jnp.arange(n, dtype=jnp.int32)  # absolute cache slots
        q_pos = pos0 + jnp.arange(s, dtype=jnp.int32)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", qh, k_cache.astype(qh.dtype)
        ) * scale
        mask = key_pos[None, :] <= q_pos[:, None]  # [s, n]
        if not p.causal:
            # bidirectional within the visible prefix (encoder-style
            # caches): every written slot is attendable
            mask = jnp.broadcast_to(
                key_pos[None, :] < pos0 + s, (s, n)
            )
        scores = jnp.where(
            mask[None, None], scores, jnp.finfo(scores.dtype).min
        )
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache.astype(qh.dtype))
        return ctx, k_cache, v_cache, (pos0 + s).reshape(1)

    def _attend_decode_paged(self, qh, kh, vh, k_cache, v_cache, btab,
                             slen, scale):
        """Paged incremental attention: write this step's k/v into the
        block pool at each row's OWN position (slot = block_table[i,
        pos_i // page], offset = pos_i % page), then attend over the
        row's gathered block view.  The gather materializes a dense
        [b, N, h, d] view (N = table_len * page == decode_max_seq), so
        the score/softmax/context math is shape-identical to the dense
        `_attend_decode` path — greedy decoding is bit-identical by
        construction, while the RESIDENT cache is the shared pool
        (sum-of-live-lengths HBM instead of b * max_seq).  Gathered
        slots past a row's length hold other sequences' bytes; the
        per-row position mask zeroes them out of the softmax exactly
        (exp underflow of the finfo.min fill), so cross-sequence leaks
        are structurally impossible, not just unlikely.

        A step of s > 1 tokens (the chunked-prefill twin,
        decoding.build_paged_chunk_step) scatters row i's token j at
        position slen[i] + j and attends each chunk token over the
        prefix INCLUDING its own chunk predecessors — the math runs
        per position (scatter j, gather, attend q=1) so every op keeps
        the decode twin's shapes: the per-token k/v bytes match the
        one-token program's wherever XLA lowers same-shape ops
        identically.  (The one-gather/full-matrix formulation is NOT
        rowwise-bitwise-stable — its [s, n] x [n, d] context matmul
        accumulates differently per s — so it is deliberately not
        used.)

        Rows always step the full chunk; idle scheduler slots point
        their table at scratch block 0 with seq_len 0, so their
        (garbage) writes land in scratch and their logits are ignored
        host-side.

        kv_kernel="pallas" keeps the scatter writes (so the POOL bytes
        stay byte-identical to this oracle) but replaces the dense
        gather + attend with one fused kernel dispatch that streams
        each row's own blocks in place
        (ops/pallas/paged_attention.py) — per-step HBM reads scale
        with live tokens instead of decode_max_seq, outputs match this
        path to fp32 tolerance (tests/test_paged_kernel.py)."""
        p: MultiHeadAttentionParams = self.params
        b, s = qh.shape[0], qh.shape[1]
        page = self._kv_page_size
        pos = slen.reshape(b).astype(jnp.int32)  # [b] incoming position
        if getattr(self, "_kv_kernel", "gather") == "pallas":
            return self._attend_decode_paged_kernel(
                qh, kh, vh, k_cache, v_cache, btab, pos, scale)
        n = btab.shape[1] * page
        key_pos = jnp.arange(n, dtype=jnp.int32)
        ctxs = []
        for j in range(s):
            # j == 0 keeps the exact seq-1 trace (no +0 constant node)
            pj = pos if j == 0 else pos + jnp.int32(j)
            blk = jnp.take_along_axis(
                btab, (pj // page)[:, None], axis=1
            )[:, 0]
            off = pj % page
            k_cache = k_cache.at[blk, off].set(
                kh[:, j].astype(k_cache.dtype))
            v_cache = v_cache.at[blk, off].set(
                vh[:, j].astype(v_cache.dtype))
            kv_k = jnp.take(k_cache, btab, axis=0).reshape(
                b, n, p.num_heads, -1)
            kv_v = jnp.take(v_cache, btab, axis=0).reshape(
                b, n, p.num_heads, -1)
            qj = qh if s == 1 else qh[:, j:j + 1]
            scores = jnp.einsum(
                "bqhd,bkhd->bhqk", qj, kv_k.astype(qh.dtype)
            ) * scale
            # one-token attends: causal and visible-prefix masks
            # coincide at key_pos <= pos_i + j (the just-written slot
            # is attendable; later chunk slots are not yet)
            mask = key_pos[None, :] <= pj[:, None]  # [b, n]
            scores = jnp.where(
                mask[:, None, None, :], scores,
                jnp.finfo(scores.dtype).min
            )
            probs = jax.nn.softmax(scores, axis=-1)
            ctxs.append(jnp.einsum(
                "bhqk,bkhd->bqhd", probs, kv_v.astype(qh.dtype)))
        ctx = ctxs[0] if s == 1 else jnp.concatenate(ctxs, axis=1)
        return ctx, k_cache, v_cache

    def _attend_decode_paged_kernel(self, qh, kh, vh, k_cache, v_cache,
                                    btab, pos, scale):
        """Fused-kernel paged attention: scatter this step's k/v at
        each row's own positions (the SAME writes, in the same order,
        as the gather oracle — pool state stays byte-identical between
        formulations), then one paged_attention dispatch reads each
        row's blocks in place.  Scattering the whole chunk before
        attending is equivalent to the oracle's interleaved loop: a
        later chunk position's write lands at a key position the
        earlier queries' masks exclude."""
        from .pallas.paged_attention import paged_attention

        s, page = qh.shape[1], self._kv_page_size
        n = btab.shape[1] * page
        for j in range(s):
            pj = pos if j == 0 else pos + jnp.int32(j)
            if j > 0:
                # a chunk's trailing PAD positions can run past the
                # position table; route those writes to scratch (zeroed
                # table row) and clamp in-range EXPLICITLY — the same
                # guard build_paged_prefill_step carries, because jax's
                # fill-mode OOB-scatter drop is a mode default, not a
                # contract (decoding.py's v18 hardening note)
                safe = (pj < n)[:, None]
                bt_j = jnp.where(safe, btab, 0)
                pj = jnp.minimum(pj, n - 1)
            else:
                bt_j = btab  # decode positions are in-range by contract
            blk = jnp.take_along_axis(
                bt_j, (pj // page)[:, None], axis=1
            )[:, 0]
            off = pj % page
            k_cache = k_cache.at[blk, off].set(
                kh[:, j].astype(k_cache.dtype))
            v_cache = v_cache.at[blk, off].set(
                vh[:, j].astype(v_cache.dtype))
        mesh = getattr(self, "_mesh", None)
        if self.shard.channel > 1 and mesh is not None \
                and mesh.devices.size > 1:
            # GSPMD cannot partition a pallas_call: shard the kernel
            # grid over the head axis explicitly (the _flash_sharded
            # pattern).  Per shard the kernel sees [b, s, h/tp, d]
            # queries against the local [nb, page, h/tp, d] pool slice;
            # the block table and positions are replicated host state.
            # No TPU gate — CPU meshes run the kernel in interpret mode
            # so tests exercise this exact dispatch.
            from jax.sharding import PartitionSpec

            batch_spec, _, head_spec = self._view_specs()
            qspec = PartitionSpec(batch_spec, None, head_spec, None)
            pool_spec = PartitionSpec(None, None, head_spec, None)
            ctx = _shard_map(
                lambda q_, k_, v_, bt_, ps_: paged_attention(
                    q_, k_, v_, bt_, ps_, scale),
                mesh=mesh,
                in_specs=(qspec, pool_spec, pool_spec,
                          PartitionSpec(None, None), PartitionSpec(None)),
                out_specs=qspec,
                check_vma=False,
            )(qh, k_cache, v_cache, btab, pos)
        else:
            ctx = paged_attention(qh, k_cache, v_cache, btab, pos, scale)
        return ctx, k_cache, v_cache

    # -- attention core dispatch ----------------------------------------
    def _seq_degree(self) -> int:
        qdims = [d for d in self.inputs[0].shape.dims if not d.is_replica_dim]
        return qdims[1].degree

    def _view_specs(self):
        """(batch_spec, seq_axes, head_spec) from the compiled machine
        views — shared by the ring and flash shard_map paths."""
        view = self.inputs[0].machine_view
        qdims_axes = [
            a for d, a in zip(self.inputs[0].shape.dims, view.axes)
            if not d.is_replica_dim
        ] if view is not None else [(), (), ()]
        head_view = self.weights[0].machine_view
        head_axes = head_view.axes[1] if head_view is not None else ()

        def spec_of(axes):
            if not axes:
                return None
            return axes[0] if len(axes) == 1 else tuple(axes)

        return spec_of(qdims_axes[0]), qdims_axes[1], spec_of(head_axes)

    def _attend(self, qh, kh, vh, scale, *, training, rng):
        p: MultiHeadAttentionParams = self.params
        sp = self._seq_degree()
        if sp > 1:
            # sequence parallelism: ring attention over the seq mesh axis
            from ..parallel.ring_attention import ring_attention

            mesh = getattr(self, "_mesh", None)
            assert mesh is not None and self.inputs[0].machine_view is not None, (
                f"{self.name}: ring attention needs a compiled mesh/view"
            )
            batch_spec, seq_axes, head_spec = self._view_specs()
            assert len(seq_axes) == 1, f"{self.name}: seq dim needs one mesh axis"
            return ring_attention(
                qh, kh, vh, mesh, seq_axes[0],
                batch_spec=batch_spec,
                head_spec=head_spec,
                scale=scale, causal=p.causal,
                training=training,
            )
        kv_appended = kh.shape[1] - self.inputs[1].shape.logical_shape[1]
        use_dropout = training and p.dropout > 0.0 and rng is not None
        # FFConfig.flash_min_seq (--flash-min-seq), set on ops at compile
        from ..config import DEFAULT_FLASH_MIN_SEQ

        flash_min = getattr(self, "_flash_min_seq", DEFAULT_FLASH_MIN_SEQ)
        # HBM guard: when the PER-DEVICE [b, h, q, k] score matrix would
        # be enormous, never trust the non-flash branch's reliance on XLA
        # fusing it away.  Shapes here are global (GSPMD traces the full
        # array), so divide by the partition degrees (batch/seq from the
        # input view, heads from the channel shard).
        # Only the batch and seq partition degrees shrink the [b,h,q,k]
        # score tensor — a hidden-dim partition does not (heads are
        # counted once via shard.channel, replication never shrinks
        # per-device data).
        deg = self.inputs[0].shape.degrees
        data_deg = int(np.prod(deg[:2])) if len(deg) >= 2 else int(deg[0])
        part = max(1, data_deg) * max(1, self.shard.channel)
        scores_bytes = (
            qh.shape[0] * qh.shape[2] * qh.shape[1] * kh.shape[1]
            * jnp.dtype(qh.dtype).itemsize
        ) // part
        force_flash = scores_bytes > _FLASH_FORCE_SCORE_BYTES
        if force_flash and (use_dropout or (p.causal and kv_appended)):
            import warnings

            warnings.warn(
                f"{self.name}: ~{scores_bytes >> 30} GiB of attention "
                "scores will materialize per device — the flash path "
                "cannot take over because of "
                + ("attention dropout" if use_dropout
                   else "causal attention with appended kv "
                        "(add_bias_kv/add_zero_attn)")
            )
        if (
            not use_dropout
            and not (p.causal and kv_appended)
            and (kh.shape[1] >= flash_min or force_flash)
        ):
            # hot path: flash attention (Pallas on TPU, fused jnp off-TPU)
            from .pallas.flash_attention import mha_flash

            mesh = getattr(self, "_mesh", None)
            if (
                mesh is not None
                and mesh.devices.size > 1
                and jax.default_backend() == "tpu"
            ):
                # GSPMD cannot partition a pallas_call: shard over the
                # batch/head mesh axes explicitly (both embarrassingly
                # parallel for attention)
                return self._flash_sharded(qh, kh, vh, scale, mesh)
            return mha_flash(qh, kh, vh, scale, p.causal)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
        if p.causal:
            qlen, klen = scores.shape[-2], scores.shape[-1]
            # appended bias_kv/zero_attn keys are always attendable;
            # real keys follow absolute-position causality
            mask = jnp.tril(jnp.ones((qlen, klen), bool))
            if kv_appended:
                mask = mask.at[:, klen - kv_appended:].set(True)
            scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores, axis=-1)
        if use_dropout:
            keep = 1.0 - p.dropout
            probs = probs * jax.random.bernoulli(rng, keep, probs.shape) / keep
        return jnp.einsum("bhqk,bkhd->bqhd", probs, vh)

    def _flash_sharded(self, qh, kh, vh, scale, mesh):
        """shard_map-wrapped flash attention over batch/head axes."""
        import functools

        from jax.sharding import PartitionSpec

        from .pallas.flash_attention import mha_flash

        p: MultiHeadAttentionParams = self.params
        batch_spec, _, head_spec = self._view_specs()
        spec = PartitionSpec(batch_spec, None, head_spec, None)
        fn = functools.partial(mha_flash, scale=scale, causal=p.causal)
        return _shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(qh, kh, vh)

    def flops(self):
        p: MultiHeadAttentionParams = self.params
        b, s, e = self.inputs[0].shape.logical_shape
        ks = self.inputs[1].shape.logical_shape[1]
        proj = 2.0 * b * s * e * p.num_heads * p.k_channels * 3
        proj += 2.0 * b * s * e * p.num_heads * p.v_channels
        attn = 2.0 * b * p.num_heads * s * ks * (p.k_channels + p.v_channels)
        return proj + attn
