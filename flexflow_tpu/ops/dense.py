"""Dense compute ops: Linear, Conv2D, Pool2D, Embedding, BatchMatmul.

Reference counterparts: src/ops/linear.cc (cublasGemmEx kernels,
kernels/linear_kernels.cu:213), src/ops/conv_2d.cc (cuDNN conv),
src/ops/pool_2d.cc, src/ops/embedding.cc (custom CUDA lookup,
attribute-parallel over vocab at embedding.cc:132-196),
src/ops/batch_matmul.cc (strided-batched GEMM, seq-length-dim support at
batch_matmul.cc:70-77).

TPU-first: all map onto `lax.dot_general` / `lax.conv_general_dilated` /
`lax.reduce_window` so XLA tiles them straight onto the MXU; backward is
autodiff.  Parallelism via ShardConfig:
  - Linear.channel  = out-channel partition (the reference's
    create_partition_linear_combine substitution);
  - Linear via partitioned in-dim = partial-sum output with replica
    degree = in-degree (the reference's Reduction-consumed output);
  - Embedding.attribute = vocab partition (attribute parallelism) —
    out-of-shard ids contribute zero and the partial outputs sum.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..fftype import ActiMode, AggrMode, DataType, OperatorType
from ..initializer import DEFAULT_BIAS_INIT, DEFAULT_WEIGHT_INIT
from ..tensor import ParallelDim, ParallelTensorShape
from .op import Op, ShapeError, WeightSpec


def apply_activation(x: jax.Array, act: ActiMode) -> jax.Array:
    if act == ActiMode.NONE:
        return x
    if act == ActiMode.RELU:
        return jax.nn.relu(x)
    if act == ActiMode.SIGMOID:
        return jax.nn.sigmoid(x)
    if act == ActiMode.TANH:
        return jnp.tanh(x)
    if act == ActiMode.GELU:
        return jax.nn.gelu(x)
    raise ValueError(act)


@dataclasses.dataclass(frozen=True)
class LinearParams:
    out_channels: int
    use_bias: bool = True
    activation: ActiMode = ActiMode.NONE
    dtype: DataType = DataType.FLOAT


class Linear(Op):
    op_type = OperatorType.LINEAR

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: LinearParams = self.params
        dims = list(ishape.dims)
        data_dims = [d for d in dims if not d.is_replica_dim]
        in_dim = data_dims[-1]
        ri = ishape.replica_degree
        c = self.shard.channel
        if c > 1 and ri % c == 0:
            ri //= c  # replicated input consumed by channel shards
        out_replica = ri * in_dim.degree  # in-degree partials
        out_dims = tuple(
            d for d in data_dims[:-1]
        ) + (
            ParallelDim(p.out_channels, c),
            ParallelDim(1, out_replica, is_replica_dim=True),
        )
        return [ParallelTensorShape(out_dims, p.dtype)]

    def make_weight_specs(self, input_shapes):
        (ishape,) = input_shapes
        p: LinearParams = self.params
        data_dims = [d for d in ishape.dims if not d.is_replica_dim]
        in_dim = data_dims[-1]
        batch_degree = 1
        for d in data_dims[:-1]:
            batch_degree *= d.degree
        kernel = ParallelTensorShape(
            (
                ParallelDim(in_dim.size, in_dim.degree),
                ParallelDim(p.out_channels, self.shard.channel),
                ParallelDim(1, batch_degree, is_replica_dim=True),
            ),
            p.dtype,
        )
        specs = [WeightSpec("kernel", kernel, DEFAULT_WEIGHT_INIT)]
        if p.use_bias:
            bias = ParallelTensorShape(
                (
                    ParallelDim(p.out_channels, self.shard.channel),
                    ParallelDim(1, batch_degree * in_dim.degree, is_replica_dim=True),
                ),
                p.dtype,
            )
            specs.append(WeightSpec("bias", bias, DEFAULT_BIAS_INIT))
        return specs

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        p: LinearParams = self.params
        kernel = weights[0]
        y = jnp.matmul(x, kernel)
        if p.use_bias:
            y = y + weights[1]
        return [apply_activation(y, p.activation)]

    def flops(self):
        ishape = self.inputs[0].shape
        return 2.0 * ishape.num_elements() * self.params.out_channels


@dataclasses.dataclass(frozen=True)
class Conv2DParams:
    out_channels: int
    kernel: Tuple[int, int]
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    groups: int = 1
    use_bias: bool = True
    activation: ActiMode = ActiMode.NONE
    dtype: DataType = DataType.FLOAT


class Conv2D(Op):
    """NCHW conv (reference convention, conv_2d.cc)."""

    op_type = OperatorType.CONV2D

    def _out_hw(self, h, w):
        p: Conv2DParams = self.params
        oh = (h + 2 * p.padding[0] - p.kernel[0]) // p.stride[0] + 1
        ow = (w + 2 * p.padding[1] - p.kernel[1]) // p.stride[1] + 1
        return oh, ow

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: Conv2DParams = self.params
        n, cin, h, w = [d for d in ishape.dims if not d.is_replica_dim]
        if cin.size % p.groups != 0 or p.out_channels % p.groups != 0:
            raise ShapeError(f"{self.name}: groups {p.groups} mismatch")
        oh, ow = self._out_hw(h.size, w.size)
        if oh <= 0 or ow <= 0:
            raise ShapeError(f"{self.name}: non-positive output spatial dims")
        out_replica = ishape.replica_degree * cin.degree
        dims = (
            ParallelDim(n.size, n.degree),
            ParallelDim(p.out_channels, self.shard.channel),
            ParallelDim(oh, h.degree),
            ParallelDim(ow, w.degree),
            ParallelDim(1, out_replica, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, p.dtype)]

    def make_weight_specs(self, input_shapes):
        (ishape,) = input_shapes
        p: Conv2DParams = self.params
        n, cin, h, w = [d for d in ishape.dims if not d.is_replica_dim]
        # OIHW filter layout
        kernel = ParallelTensorShape(
            (
                ParallelDim(p.out_channels, self.shard.channel),
                ParallelDim(cin.size // p.groups, cin.degree),
                ParallelDim(p.kernel[0]),
                ParallelDim(p.kernel[1]),
                ParallelDim(1, n.degree * h.degree * w.degree, is_replica_dim=True),
            ),
            p.dtype,
        )
        specs = [WeightSpec("kernel", kernel, DEFAULT_WEIGHT_INIT)]
        if p.use_bias:
            bias = ParallelTensorShape(
                (
                    ParallelDim(p.out_channels, self.shard.channel),
                    ParallelDim(1, n.degree * h.degree * w.degree * cin.degree,
                                is_replica_dim=True),
                ),
                p.dtype,
            )
            specs.append(WeightSpec("bias", bias, DEFAULT_BIAS_INIT))
        return specs

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        p: Conv2DParams = self.params
        # physical layout assigned by pcg/layout.py: NHWC puts channels
        # on the MXU lanes (weights stay OIHW in the pytree; XLA folds
        # the kernel relayout, which is tiny next to the activations)
        nhwc = getattr(self, "_data_layout", "nchw") == "nhwc"
        dn = ("NHWC", "OIHW", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
        y = lax.conv_general_dilated(
            x,
            weights[0],
            window_strides=p.stride,
            padding=[(p.padding[0], p.padding[0]), (p.padding[1], p.padding[1])],
            dimension_numbers=dn,
            feature_group_count=p.groups,
        )
        if p.use_bias:
            bias = weights[1]
            y = y + (bias[None, None, None, :] if nhwc
                     else bias[None, :, None, None])
        return [apply_activation(y, p.activation)]

    def flops(self):
        oshape = self.outputs[0].shape
        p: Conv2DParams = self.params
        cin = self.inputs[0].shape.logical_shape[1]
        return (
            2.0
            * oshape.num_elements()
            * (cin // p.groups)
            * p.kernel[0]
            * p.kernel[1]
        )


@dataclasses.dataclass(frozen=True)
class Pool2DParams:
    kernel: Tuple[int, int]
    stride: Tuple[int, int]
    padding: Tuple[int, int] = (0, 0)
    pool_type: str = "max"  # "max" | "avg"
    activation: ActiMode = ActiMode.NONE


class Pool2D(Op):
    op_type = OperatorType.POOL2D

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: Pool2DParams = self.params
        n, c, h, w = [d for d in ishape.dims if not d.is_replica_dim]
        oh = (h.size + 2 * p.padding[0] - p.kernel[0]) // p.stride[0] + 1
        ow = (w.size + 2 * p.padding[1] - p.kernel[1]) // p.stride[1] + 1
        if oh <= 0 or ow <= 0:
            raise ShapeError(f"{self.name}: non-positive output spatial dims")
        dims = (
            ParallelDim(n.size, n.degree),
            ParallelDim(c.size, c.degree),
            ParallelDim(oh, h.degree),
            ParallelDim(ow, w.degree),
            ParallelDim(1, ishape.replica_degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, ishape.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        (x,) = inputs
        p: Pool2DParams = self.params
        hw_pads = [(p.padding[0], p.padding[0]), (p.padding[1], p.padding[1])]
        if getattr(self, "_data_layout", "nchw") == "nhwc":
            pads = [(0, 0)] + hw_pads + [(0, 0)]
            dims = (1,) + p.kernel + (1,)
            strides = (1,) + p.stride + (1,)
        else:
            pads = [(0, 0), (0, 0)] + hw_pads
            dims = (1, 1) + p.kernel
            strides = (1, 1) + p.stride
        if p.pool_type == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            y = lax.reduce_window(x, init, lax.max, dims, strides, pads)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
            y = s / (p.kernel[0] * p.kernel[1])
        return [apply_activation(y, p.activation)]


@dataclasses.dataclass(frozen=True)
class EmbeddingParams:
    num_entries: int
    out_dim: int
    aggr: AggrMode = AggrMode.NONE
    dtype: DataType = DataType.FLOAT


class Embedding(Op):
    """Token embedding; attribute-parallel over the vocab dim.

    Reference: embedding.cc:132-196 — the weight's vocab dim carries the
    attribute-parallel degree; each shard looks up only ids in its range
    and the partial outputs sum (output replica degree = vocab degree).
    Here the masked lookup is one gather + where; XLA SPMD turns the
    partial sum into a psum over the vocab axis.
    """

    op_type = OperatorType.EMBEDDING

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: EmbeddingParams = self.params
        data_dims = [d for d in ishape.dims if not d.is_replica_dim]
        out_replica = ishape.replica_degree * self.shard.attribute
        if p.aggr == AggrMode.NONE:
            kept = data_dims
        else:
            kept = data_dims[:-1]  # aggregate over the last (bag) dim
        dims = tuple(ParallelDim(d.size, d.degree) for d in kept) + (
            ParallelDim(p.out_dim, self.shard.channel),
            ParallelDim(1, out_replica, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, p.dtype)]

    def make_weight_specs(self, input_shapes):
        (ishape,) = input_shapes
        p: EmbeddingParams = self.params
        batch_degree = 1
        for d in ishape.dims:
            if not d.is_replica_dim:
                batch_degree *= d.degree
        weight = ParallelTensorShape(
            (
                ParallelDim(p.num_entries, self.shard.attribute),
                ParallelDim(p.out_dim, self.shard.channel),
                ParallelDim(1, batch_degree, is_replica_dim=True),
            ),
            p.dtype,
        )
        return [WeightSpec("weight", weight, DEFAULT_WEIGHT_INIT)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        (ids,) = inputs
        p: EmbeddingParams = self.params
        table = weights[0]
        emb = jnp.take(table, ids, axis=0)
        if p.aggr == AggrMode.SUM:
            emb = jnp.sum(emb, axis=-2)
        elif p.aggr == AggrMode.AVG:
            emb = jnp.mean(emb, axis=-2)
        return [emb]


@dataclasses.dataclass(frozen=True)
class BatchMatmulParams:
    a_seq_length_dim: int = -1
    b_seq_length_dim: int = -1


class BatchMatmul(Op):
    """[b..., m, k] @ [b..., k, n] -> [b..., m, n].

    Reference: batch_matmul.cc (cublas strided-batched GEMM); the
    seq-length-dim fields mirror its FFIterationConfig truncation support
    (batch_matmul.cc:70-77).
    """

    op_type = OperatorType.BATCH_MATMUL

    def infer_output_shapes(self, input_shapes):
        a, b = input_shapes
        ad = [d for d in a.dims if not d.is_replica_dim]
        bd = [d for d in b.dims if not d.is_replica_dim]
        if len(ad) != len(bd):
            raise ShapeError(f"{self.name}: rank mismatch {len(ad)} vs {len(bd)}")
        if ad[-1].size != bd[-2].size:
            raise ShapeError(f"{self.name}: contraction mismatch")
        for da, db in zip(ad[:-2], bd[:-2]):
            if da.size != db.size or da.degree != db.degree:
                raise ShapeError(f"{self.name}: batch dims mismatch")
        if ad[-1].degree != bd[-2].degree:
            raise ShapeError(f"{self.name}: contraction degrees differ")
        out_replica = max(a.replica_degree, b.replica_degree) * ad[-1].degree
        dims = tuple(ParallelDim(d.size, d.degree) for d in ad[:-2]) + (
            ParallelDim(ad[-2].size, ad[-2].degree),
            ParallelDim(bd[-1].size, bd[-1].degree),
            ParallelDim(1, out_replica, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, a.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        a, b = inputs
        # FFIterationConfig.seq_length early truncation
        # (batch_matmul.cc:70-77): positions past seq_length on the
        # declared seq dims are masked out — static shapes for XLA, the
        # masked work is dead and fuses away.
        seq_len = getattr(self, "_iter_seq_length", -1)
        p: BatchMatmulParams = self.params
        if seq_len > 0:
            a = self._mask_seq(a, p.a_seq_length_dim, seq_len)
            b = self._mask_seq(b, p.b_seq_length_dim, seq_len)
        return [jnp.matmul(a, b)]

    @staticmethod
    def _mask_seq(x, dim: int, seq_len: int):
        if dim < 0 or dim >= x.ndim:
            return x
        idx = jnp.arange(x.shape[dim])
        shape = [1] * x.ndim
        shape[dim] = x.shape[dim]
        return x * (idx < seq_len).reshape(shape).astype(x.dtype)

    def flops(self):
        a = self.inputs[0].shape.logical_shape
        n = self.outputs[0].shape.logical_shape[-1]
        import numpy as np

        return 2.0 * float(np.prod(a)) * n
