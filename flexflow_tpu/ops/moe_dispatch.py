"""Cumsum-based MoE dispatch (the standard TPU trick; SURVEY hard-part 3).

The reference's group_by/aggregate are data-dependent CUDA
scatter/gather kernels (group_by.cu:1-206, aggregate.cu).  The dense
one-hot formulation (`_dispatch_mask` in moe.py) is numerically
identical but costs O(b·k·n·cap·d) MXU work.  This module computes the
same capacity-bounded assignment with a one-hot cumsum rank — the
GShard/Switch position-in-expert scan, O(bk·n) on integers only — and
moves rows with one scatter-add (dispatch) / gather (combine), each
O(bk·d).

Priority semantics match `_dispatch_mask` exactly: tokens are served in
flattened (sample-major, slot-minor) order; ranks past `capacity` are
dropped.  Integer rank indices carry no gradient, matching the one-hot
path (gradients flow through the moved rows only).

Why cumsum and not sort: an earlier revision ranked tokens with a
stable argsort + segment scan + unscatter.  That chain is numerically
identical per device, but under GSPMD with the batch dim sharded
(data-parallel serving/training meshes) XLA's partitioner produced
wrong ranks for the fused sort->scan->scatter pattern on jax 0.4.x —
the expert-parallel parity test caught live routing corruption.  The
cumsum formulation partitions correctly (verified sharded-vs-single
bit-parity in tests/test_parallelism.py::test_moe_expert_parallel) and
lowers to the same O(bk·n) integer work XLA emits for the GShard
dispatch einsum's position computation.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def dispatch_indices(
    assign: jax.Array, capacity: int, n: int
) -> Tuple[jax.Array, jax.Array]:
    """[b, k] int expert ids -> (slot [bk], keep [bk]).

    slot[i] = expert_id[i] * capacity + rank-of-i-within-its-expert
    (clamped); keep[i] = rank < capacity.  Flat index i = b*k + slot,
    i.e. the same priority order as the reference's cumsum scatter.
    """
    flat = assign.reshape(-1).astype(jnp.int32)
    onehot = jax.nn.one_hot(flat, n, dtype=jnp.int32)  # [bk, n]
    pos = jnp.cumsum(onehot, axis=0) - 1  # running count per expert
    rank = jnp.sum(pos * onehot, axis=-1)  # [bk] rank within expert
    keep = rank < capacity
    slot = flat * capacity + jnp.minimum(rank, capacity - 1)
    return slot, keep


def sort_group_by(
    data: jax.Array, assign: jax.Array, n: int, capacity: int
) -> jax.Array:
    """[b, d] tokens + [b, k] assignments -> [n, capacity, d] expert
    inputs (dropped tokens contribute zero rows)."""
    b, k = assign.shape
    d = data.shape[1]
    slot, keep = dispatch_indices(assign, capacity, n)
    rows = jnp.repeat(data, k, axis=0)  # row i serves flat token i
    contrib = rows * keep[:, None].astype(data.dtype)
    out = jnp.zeros((n * capacity, d), data.dtype).at[slot].add(contrib)
    return out.reshape(n, capacity, d)


def sort_combine(
    expert_out: jax.Array, assign: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array]:
    """[n, cap, e] expert outputs -> per-(token, slot) rows [bk, e]
    (zero for dropped tokens), plus keep [bk]."""
    n = expert_out.shape[0]
    slot, keep = dispatch_indices(assign, capacity, n)
    flat_out = expert_out.reshape(-1, expert_out.shape[-1])
    return flat_out[slot] * keep[:, None].astype(expert_out.dtype), keep
