"""PCG source nodes: Input, Weight, NoOp.

Reference: src/ops/noop.cc (255 LoC) — OP_INPUT/OP_WEIGHT/OP_NOOP nodes
created by get_or_create_input_node (model.h:707).
"""
from __future__ import annotations

import dataclasses

from ..fftype import OperatorType
from ..tensor import ParallelTensorShape
from .op import Op


@dataclasses.dataclass(frozen=True)
class SourceParams:
    shape: ParallelTensorShape
    kind: str = "input"  # "input" | "weight" | "noop"


class InputOp(Op):
    op_type = OperatorType.INPUT

    def infer_output_shapes(self, input_shapes):
        return [self.params.shape]

    def forward(self, inputs, weights, *, training=False, rng=None):
        raise RuntimeError("source ops are fed by the executor, not executed")


class NoOp(Op):
    op_type = OperatorType.NOOP

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [inputs[0]]
