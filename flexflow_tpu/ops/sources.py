"""PCG source nodes: Input, Weight, NoOp.

Reference: src/ops/noop.cc (255 LoC) — OP_INPUT/OP_WEIGHT/OP_NOOP nodes
created by get_or_create_input_node (model.h:707).
"""
from __future__ import annotations

import dataclasses

from ..fftype import OperatorType
from ..tensor import ParallelTensorShape
from .op import Op


@dataclasses.dataclass(frozen=True)
class SourceParams:
    shape: ParallelTensorShape
    kind: str = "input"  # "input" | "weight" | "noop"
    # weight sources only: False freezes the value (torch buffers)
    trainable: bool = True


class InputOp(Op):
    op_type = OperatorType.INPUT

    def infer_output_shapes(self, input_shapes):
        return [self.params.shape]

    def forward(self, inputs, weights, *, training=False, rng=None):
        raise RuntimeError("source ops are fed by the executor, not executed")


class WeightOp(Op):
    """A standalone (trainable) parameter surfaced as a tensor — the
    reference's OP_WEIGHT node / torch-frontend AttributeNode
    (python/flexflow/torch/model.py:2294): a bare nn.Parameter consumed
    by elementwise ops."""

    op_type = OperatorType.WEIGHT

    def infer_output_shapes(self, input_shapes):
        return [self.params.shape]

    def make_weight_specs(self, input_shapes):
        from ..initializer import DEFAULT_WEIGHT_INIT
        from .op import WeightSpec

        return [WeightSpec("value", self.params.shape, DEFAULT_WEIGHT_INIT)]

    def num_trainable_weights(self) -> int:
        # frozen buffers (torch requires_grad=False) live in the state
        # pytree: no gradients, no optimizer updates, no weight decay
        return 1 if self.params.trainable else 0

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [weights[0]]


class NoOp(Op):
    op_type = OperatorType.NOOP

    def infer_output_shapes(self, input_shapes):
        return [input_shapes[0]]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [inputs[0]]
