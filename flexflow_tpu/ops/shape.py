"""Shape/manipulation ops: Reshape, Flat, Transpose, Reverse, Concat,
Split, Gather, ReduceSum, Mean.

Reference: src/ops/{reshape,flat,transpose,reverse,concat,split,gather,
reduce,mean}.cc — all custom-copy or cuDNN-reduce CUDA kernels.  TPU-first
these are pure metadata ops or single XLA HLOs (reshape/transpose/rev/
concatenate/slice/gather/reduce) that fuse with neighbours.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp
import numpy as np

from ..fftype import OperatorType
from ..tensor import ParallelDim, ParallelTensorShape
from .op import Op, ShapeError


def _is_prefix_merge(ddims, target0: int) -> bool:
    """True if target0 is the product of a leading run of input dims."""
    prod = 1
    for d in ddims:
        prod *= d.size
        if prod == target0:
            return True
        if prod > target0:
            return False
    return False


def _is_prefix_split(lead_size: int, target) -> bool:
    """True if a leading run of target dims multiplies to lead_size."""
    prod = 1
    for s in target:
        prod *= s
        if prod == lead_size:
            return True
        if prod > lead_size:
            return False
    return False


def _data_dims(shape: ParallelTensorShape):
    return [d for d in shape.dims if not d.is_replica_dim]


@dataclasses.dataclass(frozen=True)
class ReshapeParams:
    shape: Tuple[int, ...]


class Reshape(Op):
    """Logical reshape.  Partitioned input dims must survive the reshape
    (dim 0 degree is carried if sizes allow); otherwise the search must
    insert a Combine first."""

    op_type = OperatorType.RESHAPE

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        target = list(self.params.shape)
        neg = [i for i, s in enumerate(target) if s == -1]
        if len(neg) > 1:
            raise ShapeError(f"{self.name}: multiple -1 in reshape")
        numel = ishape.num_elements()
        if neg:
            rest = -int(np.prod(target))
            target[neg[0]] = numel // rest
        if int(np.prod(target)) != numel:
            raise ShapeError(f"{self.name}: cannot reshape {ishape} to {target}")
        ddims = _data_dims(ishape)
        degrees = [1] * len(target)
        # The leading (sample) dim's degree survives three SPMD-safe cases:
        #   * size preserved;
        #   * merge: leading partitioned dim folded with following
        #     UNpartitioned dims ([b(deg),s,h] -> [b*s,h] — each shard
        #     stays contiguous, no data movement);
        #   * split: leading partitioned dim split into a prefix of the
        #     target ([b*s(deg),h] -> [b,s,h] with deg | b).
        if (
            ddims
            and target
            and ddims[0].size == target[0]
            and all(d.degree == 1 for d in ddims[1:])
        ):
            degrees[0] = ddims[0].degree
        elif (
            ddims
            and target
            and all(d.degree == 1 for d in ddims[1:])
            and _is_prefix_merge(ddims, target[0])
            and target[0] % max(ddims[0].degree, 1) == 0
        ):
            degrees[0] = ddims[0].degree
        elif (
            ddims
            and target
            and all(d.degree == 1 for d in ddims[1:])
            and _is_prefix_split(ddims[0].size, target)
            and target[0] % max(ddims[0].degree, 1) == 0
        ):
            degrees[0] = ddims[0].degree
        elif any(d.degree > 1 for d in ddims):
            raise ShapeError(f"{self.name}: reshape of partitioned dims unsupported")
        dims = tuple(ParallelDim(s, g) for s, g in zip(target, degrees)) + (
            ParallelDim(1, ishape.replica_degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, ishape.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        out_shape = self.outputs[0].shape.logical_shape
        return [jnp.reshape(inputs[0], out_shape)]


@dataclasses.dataclass(frozen=True)
class ExpandParams:
    sizes: Tuple[int, ...]


class Expand(Op):
    """Broadcast size-1 dims up to `sizes` (torch Tensor.expand; the
    reference's ExpandNode, python/flexflow/torch/model.py:1736).
    Backward is the summing transpose of broadcast via autodiff."""

    op_type = OperatorType.RESHAPE  # same family: metadata-only HLO

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        ddims = _data_dims(ishape)
        target = list(self.params.sizes)
        if len(target) != len(ddims):
            raise ShapeError(
                f"{self.name}: expand rank {len(target)} != input rank "
                f"{len(ddims)}"
            )
        dims = []
        for d, s in zip(ddims, target):
            s = d.size if s == -1 else s
            if d.size != s and d.size != 1:
                raise ShapeError(
                    f"{self.name}: cannot expand dim of size {d.size} to {s}"
                )
            if d.size == 1 and s != 1 and d.degree != 1:
                raise ShapeError(f"{self.name}: cannot expand partitioned dim")
            dims.append(ParallelDim(s, d.degree))
        dims = tuple(dims) + (
            ParallelDim(1, ishape.replica_degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, ishape.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [jnp.broadcast_to(inputs[0], self.outputs[0].shape.logical_shape)]


class Flat(Op):
    """Flatten all but the sample dim (reference src/ops/flat.cc)."""

    op_type = OperatorType.FLAT

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        ddims = _data_dims(ishape)
        if any(d.degree > 1 for d in ddims[1:]):
            raise ShapeError(f"{self.name}: flattened dims are partitioned")
        rest = int(np.prod([d.size for d in ddims[1:]])) if len(ddims) > 1 else 1
        dims = (
            ParallelDim(ddims[0].size, ddims[0].degree),
            ParallelDim(rest),
            ParallelDim(1, ishape.replica_degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, ishape.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        x = inputs[0]
        return [jnp.reshape(x, (x.shape[0], -1))]


@dataclasses.dataclass(frozen=True)
class TransposeParams:
    perm: Tuple[int, ...]


class Transpose(Op):
    op_type = OperatorType.TRANSPOSE

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        ddims = _data_dims(ishape)
        perm = self.params.perm
        if sorted(perm) != list(range(len(ddims))):
            raise ShapeError(f"{self.name}: bad perm {perm}")
        dims = tuple(ParallelDim(ddims[p].size, ddims[p].degree) for p in perm) + (
            ParallelDim(1, ishape.replica_degree, is_replica_dim=True),
        )
        return [ParallelTensorShape(dims, ishape.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [jnp.transpose(inputs[0], self.params.perm)]


@dataclasses.dataclass(frozen=True)
class ReverseParams:
    axis: int


class Reverse(Op):
    op_type = OperatorType.REVERSE

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        ax = self.params.axis % ishape.logical_rank
        if _data_dims(ishape)[ax].degree != 1:
            raise ShapeError(f"{self.name}: reversed axis is partitioned")
        return [ishape]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [jnp.flip(inputs[0], self.params.axis)]


@dataclasses.dataclass(frozen=True)
class PadParams:
    pads: Tuple[Tuple[int, int], ...]  # (before, after) per logical dim
    value: float = 0.0


class Pad(Op):
    """Constant-pad along logical dims (ONNX Pad / torch F.pad).  The
    reference's onnx handler is a warned pass-through
    (python/flexflow/onnx/model.py:229-233); here it is a real op:
    jnp.pad lowers to one XLA pad HLO that fuses with its neighbors."""

    op_type = OperatorType.PAD

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        pads = self.params.pads
        if len(pads) != ishape.logical_rank:
            raise ShapeError(
                f"{self.name}: {len(pads)} pad pairs for rank "
                f"{ishape.logical_rank}"
            )
        dims = []
        for d, (b, a) in zip(_data_dims(ishape), pads):
            if (b or a) and d.degree != 1:
                raise ShapeError(f"{self.name}: padded axis is partitioned")
            dims.append(ParallelDim(d.size + b + a, d.degree))
        dims.append(ParallelDim(1, ishape.replica_degree, is_replica_dim=True))
        return [ParallelTensorShape(tuple(dims), ishape.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        return [jnp.pad(inputs[0], self.params.pads,
                        constant_values=self.params.value)]


@dataclasses.dataclass(frozen=True)
class ConcatParams:
    axis: int


class Concat(Op):
    op_type = OperatorType.CONCAT

    def infer_output_shapes(self, input_shapes):
        first = input_shapes[0]
        rank = first.logical_rank
        ax = self.params.axis % rank
        total = 0
        for s in input_shapes:
            dd = _data_dims(s)
            if s.logical_rank != rank:
                raise ShapeError(f"{self.name}: rank mismatch")
            if dd[ax].degree != 1:
                raise ShapeError(f"{self.name}: concat axis partitioned")
            for i in range(rank):
                if i != ax and (
                    dd[i].size != _data_dims(first)[i].size
                    or dd[i].degree != _data_dims(first)[i].degree
                ):
                    raise ShapeError(f"{self.name}: dim {i} mismatch")
            total += dd[ax].size
        dims = []
        for i, d in enumerate(_data_dims(first)):
            dims.append(ParallelDim(total if i == ax else d.size, d.degree if i != ax else 1))
        dims.append(ParallelDim(1, first.replica_degree, is_replica_dim=True))
        return [ParallelTensorShape(tuple(dims), first.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        ax = self.params.axis
        if getattr(self, "_data_layout", "nchw") == "nhwc":
            from ..pcg.layout import NCHW_TO_NHWC_AXIS

            ax = NCHW_TO_NHWC_AXIS[ax % 4]
        return [jnp.concatenate(list(inputs), axis=ax)]


@dataclasses.dataclass(frozen=True)
class SplitParams:
    sizes: Tuple[int, ...]
    axis: int


class Split(Op):
    op_type = OperatorType.SPLIT

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        ddims = _data_dims(ishape)
        ax = self.params.axis % len(ddims)
        if ddims[ax].degree != 1:
            raise ShapeError(f"{self.name}: split axis partitioned")
        if sum(self.params.sizes) != ddims[ax].size:
            raise ShapeError(f"{self.name}: split sizes {self.params.sizes} != {ddims[ax].size}")
        outs = []
        for sz in self.params.sizes:
            dims = tuple(
                ParallelDim(sz if i == ax else d.size, d.degree)
                for i, d in enumerate(ddims)
            ) + (ParallelDim(1, ishape.replica_degree, is_replica_dim=True),)
            outs.append(ParallelTensorShape(dims, ishape.dtype))
        return outs

    def forward(self, inputs, weights, *, training=False, rng=None):
        x = inputs[0]
        idx = np.cumsum(self.params.sizes)[:-1]
        ax = self.params.axis
        if getattr(self, "_data_layout", "nchw") == "nhwc":
            from ..pcg.layout import NCHW_TO_NHWC_AXIS

            ax = NCHW_TO_NHWC_AXIS[ax % len(x.shape)]
        return list(jnp.split(x, idx, axis=ax))


@dataclasses.dataclass(frozen=True)
class GatherParams:
    axis: int


class Gather(Op):
    """Gather along axis with an index tensor of the same rank
    (torch.gather semantics, reference src/ops/gather.cc)."""

    op_type = OperatorType.GATHER

    def infer_output_shapes(self, input_shapes):
        data, index = input_shapes
        ax = self.params.axis % data.logical_rank
        if _data_dims(data)[ax].degree != 1:
            raise ShapeError(f"{self.name}: gather axis partitioned")
        dims = tuple(
            ParallelDim(d.size, d.degree) for d in _data_dims(index)
        ) + (ParallelDim(1, data.replica_degree, is_replica_dim=True),)
        return [ParallelTensorShape(dims, data.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        data, index = inputs
        return [jnp.take_along_axis(data, index, axis=self.params.axis)]


@dataclasses.dataclass(frozen=True)
class ReduceParams:
    axes: Tuple[int, ...]
    keepdims: bool = False
    op: str = "sum"  # "sum" | "mean"


class Reduce(Op):
    op_type = OperatorType.REDUCE_SUM

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        ddims = _data_dims(ishape)
        rank = len(ddims)
        axes = {a % rank for a in self.params.axes}
        dims = []
        reduced_degree = 1
        for i, d in enumerate(ddims):
            if i in axes:
                # Reducing a partitioned axis is legal under SPMD: XLA
                # emits the cross-shard psum; the result is replicated
                # over that axis (replica degree absorbs the degree).
                reduced_degree *= d.degree
                if self.params.keepdims:
                    dims.append(ParallelDim(1))
            else:
                dims.append(ParallelDim(d.size, d.degree))
        dims.append(
            ParallelDim(1, ishape.replica_degree * reduced_degree,
                        is_replica_dim=True)
        )
        return [ParallelTensorShape(tuple(dims), ishape.dtype)]

    def forward(self, inputs, weights, *, training=False, rng=None):
        p: ReduceParams = self.params
        fn = jnp.sum if p.op == "sum" else jnp.mean
        return [fn(inputs[0], axis=p.axes, keepdims=p.keepdims)]


class Mean(Reduce):
    op_type = OperatorType.MEAN
