"""PyTorch frontend: torch.fx symbolic trace -> FFModel lowering.

Reference: python/flexflow/torch/model.py (2656 LoC) — ~60 fx Node
classes each with a `to_ff` lowering (:248-2441) plus a string-IR file
format (:2442+).  Here the trace lowers directly to FFModel layer calls
(no intermediate file), and module weights can be copied into the
compiled model for exact numerical parity with the torch original.
"""
from .model import PyTorchModel, torch_to_flexflow

__all__ = ["PyTorchModel", "torch_to_flexflow"]
