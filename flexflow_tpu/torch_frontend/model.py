"""torch.fx -> FFModel importer (+ serialized-IR file exchange).

Reference: python/flexflow/torch/model.py — `PyTorchModel` traces an
nn.Module with a customed fx tracer and lowers every fx node through a
per-op Node subclass's `to_ff` (LinearNode.to_ff at model.py:285, ~60
node kinds), with a string-IR file format for out-of-process exchange
(torch_to_file/`PyTorchModel.apply`, model.py:2442+).

TPU-native redesign: lowering dispatches on SERIALIZABLE descriptions —
a module-config dict for call_module nodes and a canonical function
name for call_function/call_method — so the live fx path and the
file-replay path (`torch_to_file` -> `file_to_ff`, which needs no torch
at all) share one implementation.  Weights transfer via `copy_weights`
after compile (torch Linear stores [out, in] — ours is [in, out],
transposed on the way in); functional F.linear/F.conv2d weights arrive
as arrays and become ArrayInitializers (exact parity by construction).
"""
from __future__ import annotations

import json
import operator
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..fftype import ActiMode, DataType
from ..initializer import ArrayInitializer
from ..model import FFModel
from ..tensor import ParallelTensor

try:
    import torch
    import torch.fx
    import torch.nn as nn
    import torch.nn.functional as F

    HAS_TORCH = True
except ImportError:  # pragma: no cover - torch is baked into this image
    HAS_TORCH = False


# ---------------------------------------------------------------------------
# module -> serializable config
# ---------------------------------------------------------------------------

def module_config(m) -> Dict:
    """Extract a JSON-serializable lowering config from an nn.Module
    (the file format's call_module payload)."""
    if isinstance(m, nn.Linear):
        return {"kind": "linear", "out": m.out_features,
                "bias": m.bias is not None}
    if isinstance(m, nn.Conv2d):
        assert m.padding_mode == "zeros"
        pad = m.padding if isinstance(m.padding, tuple) else (m.padding,) * 2
        return {"kind": "conv2d", "out": m.out_channels,
                "kernel": list(m.kernel_size), "stride": list(m.stride),
                "padding": [pad[0], pad[1]], "groups": m.groups,
                "bias": m.bias is not None}
    if isinstance(m, (nn.MaxPool2d, nn.AvgPool2d)):
        k = m.kernel_size if isinstance(m.kernel_size, tuple) else (m.kernel_size,) * 2
        s = m.stride if isinstance(m.stride, tuple) else (m.stride or m.kernel_size,) * 2
        p = m.padding if isinstance(m.padding, tuple) else (m.padding,) * 2
        return {"kind": "pool2d", "k": list(k), "s": list(s), "p": list(p),
                "type": "max" if isinstance(m, nn.MaxPool2d) else "avg"}
    if isinstance(m, nn.AdaptiveAvgPool2d):
        o = m.output_size if isinstance(m.output_size, tuple) else (
            m.output_size, m.output_size)
        return {"kind": "adaptive_avg_pool2d", "out": [o[0], o[1]]}
    if isinstance(m, nn.BatchNorm2d):
        return {"kind": "batch_norm"}
    if isinstance(m, nn.LayerNorm):
        return {"kind": "layer_norm", "ndims": len(m.normalized_shape),
                "affine": m.elementwise_affine, "eps": m.eps}
    if isinstance(m, nn.Embedding):
        return {"kind": "embedding", "num": m.num_embeddings,
                "dim": m.embedding_dim}
    if isinstance(m, nn.ReLU):
        return {"kind": "unary", "fn": "relu"}
    if isinstance(m, nn.GELU):
        return {"kind": "unary", "fn": "gelu"}
    if isinstance(m, nn.Sigmoid):
        return {"kind": "unary", "fn": "sigmoid"}
    if isinstance(m, nn.Tanh):
        return {"kind": "unary", "fn": "tanh"}
    if isinstance(m, nn.ELU):
        return {"kind": "unary", "fn": "elu"}
    if isinstance(m, nn.Softmax):
        return {"kind": "softmax", "dim": m.dim if m.dim is not None else -1}
    if isinstance(m, nn.Dropout):
        return {"kind": "dropout", "p": m.p}
    if isinstance(m, nn.Flatten):
        return {"kind": "flatten", "start": m.start_dim, "end": m.end_dim}
    if isinstance(m, nn.Identity):
        return {"kind": "identity"}
    if isinstance(m, nn.MultiheadAttention):
        assert m.batch_first, "set batch_first=True for MHA import"
        return {"kind": "mha", "embed": m.embed_dim, "heads": m.num_heads,
                "dropout": m.dropout, "bias": m.in_proj_bias is not None,
                "add_bias_kv": m.bias_k is not None}
    raise ValueError(f"unsupported torch module in trace: {m}")


_UNARY_FNS = {"relu": "relu", "gelu": "gelu", "sigmoid": "sigmoid",
              "tanh": "tanh", "elu": "elu", "exp": "exp", "log": "log",
              "sin": "sin", "cos": "cos", "sqrt": "sqrt", "rsqrt": "rsqrt",
              "erf": "erf", "floor": "floor"}

#: module-config kinds that own trainable weights (copy_weights targets)
_WEIGHTED_KINDS = {"linear", "conv2d", "batch_norm", "layer_norm",
                   "embedding", "mha"}


def lower_module(ff: FFModel, cfg: Dict, a: List, name: str):
    """Lower one call_module node from its serializable config — shared
    by the live fx path and file replay."""
    kind = cfg["kind"]
    if kind == "linear":
        return ff.dense(a[0], cfg["out"], use_bias=cfg["bias"], name=name)
    if kind == "conv2d":
        return ff.conv2d(
            a[0], cfg["out"], cfg["kernel"][0], cfg["kernel"][1],
            cfg["stride"][0], cfg["stride"][1], cfg["padding"][0],
            cfg["padding"][1], groups=cfg["groups"], use_bias=cfg["bias"],
            name=name,
        )
    if kind == "pool2d":
        k, s, p = cfg["k"], cfg["s"], cfg["p"]
        return ff.pool2d(a[0], k[0], k[1], s[0], s[1], p[0], p[1],
                         pool_type=cfg["type"], name=name)
    if kind == "adaptive_avg_pool2d":
        h, w = a[0].shape.logical_shape[2:4]
        kh, kw = h // cfg["out"][0], w // cfg["out"][1]
        return ff.pool2d(a[0], kh, kw, kh, kw, 0, 0, pool_type="avg",
                         name=name)
    if kind == "batch_norm":
        return ff.batch_norm(a[0], relu=False, name=name)
    if kind == "layer_norm":
        rank = a[0].shape.logical_rank
        axes = tuple(range(rank - cfg["ndims"], rank))
        return ff.layer_norm(a[0], axes, cfg["affine"], cfg["eps"], name=name)
    if kind == "embedding":
        return ff.embedding(a[0], cfg["num"], cfg["dim"], name=name)
    if kind == "unary":
        return getattr(ff, _UNARY_FNS[cfg["fn"]])(a[0], name=name)
    if kind == "softmax":
        return ff.softmax(a[0], axis=cfg["dim"], name=name)
    if kind == "dropout":
        return ff.dropout(a[0], cfg["p"], name=name)
    if kind == "flatten":
        assert cfg["start"] == 1 and cfg["end"] == -1, (
            "only full flatten supported"
        )
        return ff.flat(a[0], name=name)
    if kind == "identity":
        return a[0]
    if kind == "mha":
        out = ff.multihead_attention(
            a[0], a[1], a[2], cfg["embed"], cfg["heads"],
            dropout=cfg["dropout"], bias=cfg["bias"],
            add_bias_kv=cfg["add_bias_kv"], name=name,
        )
        # torch MHA returns (output, attn_weights): hand back a tuple so
        # the traced 'out, _ = attn(...)' unpack resolves via getitem(0)
        # instead of slicing the batch dim
        return (out, None)
    raise ValueError(f"unsupported module config kind: {kind}")


# ---------------------------------------------------------------------------
# function / method lowering by canonical name
# ---------------------------------------------------------------------------

def _fn_names() -> Dict:
    """Canonical name for every supported call_function target."""
    t: Dict = {
        operator.add: "add", operator.sub: "sub", operator.mul: "mul",
        operator.truediv: "div", operator.floordiv: "floordiv",
        operator.neg: "neg", operator.pow: "pow",
        operator.getitem: "getitem",
        # fx records `x.shape` as call_function(builtins.getattr):
        # shapes are static here, so it folds to a tuple of ints
        # (HF transformers' `hidden_states.shape[...]` idiom)
        getattr: "getattr_",
    }
    if HAS_TORCH:
        t.update({
            torch.add: "add", torch.sub: "sub", torch.mul: "mul",
            torch.div: "div", torch.pow: "pow", torch.neg: "neg",
            torch.relu: "relu", F.relu: "relu", F.gelu: "gelu",
            torch.sigmoid: "sigmoid", F.sigmoid: "sigmoid",
            torch.tanh: "tanh", F.tanh: "tanh", F.elu: "elu",
            torch.exp: "exp", torch.log: "log", torch.sin: "sin",
            torch.cos: "cos", torch.sqrt: "sqrt", torch.rsqrt: "rsqrt",
            torch.erf: "erf", torch.floor: "floor",
            torch.maximum: "maximum", torch.minimum: "minimum",
            torch.max: "maximum", torch.min: "minimum",
            F.softmax: "softmax", torch.flatten: "flatten",
            torch.cat: "cat", torch.split: "split",
            torch.chunk: "chunk",
            torch.matmul: "matmul", torch.bmm: "matmul",
            torch.reshape: "reshape", torch.transpose: "transpose",
            torch.permute: "permute", torch.mean: "mean",
            torch.sum: "sum", torch.unsqueeze: "unsqueeze",
            torch.squeeze: "squeeze", F.dropout: "dropout",
            F.linear: "f_linear", F.conv2d: "f_conv2d",
            F.adaptive_avg_pool2d: "adaptive_avg_pool2d",
            F.avg_pool2d: "avg_pool2d", F.max_pool2d: "max_pool2d",
        })
    return t


_FN_NAMES = _fn_names()

_METHOD_ALIASES = {
    "view": "reshape", "reshape": "reshape", "permute": "permute",
    "transpose": "transpose", "flatten": "flatten",
    "contiguous": "identity_m", "mean": "mean", "sum": "sum",
    "size": "size", "pow": "pow", "sqrt": "sqrt", "rsqrt": "rsqrt",
    "expand": "expand", "expand_as": "expand_as",
    "unsqueeze": "unsqueeze", "squeeze": "squeeze", "chunk": "chunk",
    "split": "split", "to": "to", "float": "to_float",
    "type_as": "type_as", "relu": "relu", "sigmoid": "sigmoid",
    "tanh": "tanh", "matmul": "matmul", "bmm": "matmul",
    "add": "add", "sub": "sub", "mul": "mul", "div": "div",
    "masked_fill": None, "detach": "identity_m",
}


class TracedArray(np.ndarray):
    """np view carrying torch-parameter provenance: whether the source
    tensor had requires_grad (buffers import as frozen weights)."""

    trainable: bool = True


def _traced_array(arr, trainable: bool) -> "TracedArray":
    t = np.asarray(arr).view(TracedArray)
    t.trainable = bool(trainable)
    return t


def _is_tensor(x) -> bool:
    return isinstance(x, ParallelTensor)


def _axis_arg(a, kw, pos, key="dim", default=None):
    if key in kw:
        return kw[key]
    return a[pos] if len(a) > pos else default


def _getitem(ff: FFModel, x, idx, name: str):
    """getitem on a tensor: int / slice / tuple-of-slices lowering via
    Split (+ reshape for int indexing) — reference GetItemNode
    (model.py:1393) covers the same shapes."""
    if isinstance(x, (tuple, list)):
        return x[idx]
    if not _is_tensor(x):
        raise ValueError(f"getitem on unsupported value {type(x)}")
    items = idx if isinstance(idx, tuple) else (idx,)
    out = x
    squeeze_axes = []
    for axis, it in enumerate(items):
        if isinstance(it, slice):
            if it == slice(None):
                continue
            size = out.shape.logical_shape[axis]
            start = it.start or 0
            if start < 0:
                start += size
            stop = size if it.stop is None else it.stop
            if stop < 0:
                stop += size
            # torch clamps out-of-range bounds; empty slices stay empty
            start = max(0, start)
            stop = max(start, min(stop, size))
            if stop == start:
                raise ValueError(
                    f"empty slice on axis {axis} is unsupported"
                )
            if (it.step or 1) != 1:
                raise ValueError("strided tensor slicing is unsupported")
            out = _slice_axis(ff, out, axis, start, stop, name)
        elif isinstance(it, int):
            size = out.shape.logical_shape[axis]
            it = it % size
            out = _slice_axis(ff, out, axis, it, it + 1, name)
            squeeze_axes.append(axis)
        else:
            raise ValueError(f"unsupported tensor index {it!r}")
    if squeeze_axes:
        shape = [
            s for ax, s in enumerate(out.shape.logical_shape)
            if ax not in squeeze_axes
        ]
        out = ff.reshape(out, shape, name=f"{name}_sq")
    return out


def _slice_axis(ff, x, axis, start, stop, name):
    size = x.shape.logical_shape[axis]
    sizes = [s for s in (start, stop - start, size - stop) if s > 0]
    if sizes == [size]:
        return x
    parts = ff.split(x, sizes, axis, name=f"{name}_ax{axis}")
    if not isinstance(parts, (tuple, list)):
        parts = [parts]
    return parts[1 if start > 0 else 0]


def _unsqueeze(ff, x, dim, name):
    shape = list(x.shape.logical_shape)
    dim = dim % (len(shape) + 1)
    shape.insert(dim, 1)
    return ff.reshape(x, shape, name=name)


def _squeeze(ff, x, dim, name):
    shape = list(x.shape.logical_shape)
    if dim is None:
        shape = [s for s in shape if s != 1]
    else:
        dim = dim % len(shape)
        if shape[dim] != 1:
            return x
        shape.pop(dim)
    return ff.reshape(x, shape, name=name)


def lower_function(ff: FFModel, fname: str, a: List, kw: Dict, name: str):
    """Lower one call_function node by canonical name — shared by the
    fx path and file replay (reference FunctionNode kinds,
    model.py:858-2293)."""
    if fname in ("add", "sub", "mul", "div"):
        # a bare nn.Parameter / buffer operand (reference AttributeNode,
        # model.py:2294) becomes a weight-backed tensor — frozen when the
        # source was a non-grad buffer
        a = [
            ff.weight_tensor(v, trainable=getattr(v, "trainable", True),
                             name=f"{name}_w{i}")
            if isinstance(v, np.ndarray) and v.ndim > 0 else v
            for i, v in enumerate(a)
        ]
        if _is_tensor(a[0]) and _is_tensor(a[1]):
            fn = {"add": ff.add, "sub": ff.subtract, "mul": ff.multiply,
                  "div": ff.divide}[fname]
            return fn(a[0], a[1], name=name)
        if not _is_tensor(a[0]) and not _is_tensor(a[1]):
            # static-shape arithmetic in the trace (e.g. HF's
            # `x.shape[:-1] + (heads, d)`) folds in Python
            return {"add": operator.add, "sub": operator.sub,
                    "mul": operator.mul,
                    "div": operator.truediv}[fname](a[0], a[1])
        tensor, scalar = (a[0], a[1]) if _is_tensor(a[0]) else (a[1], a[0])
        if fname == "sub" and not _is_tensor(a[0]):
            # scalar - x = -(x - scalar)
            t = ff.scalar_sub(tensor, float(scalar), name=f"{name}_s")
            return ff.scalar_multiply(t, -1.0, name=name)
        if fname == "div" and not _is_tensor(a[0]):
            # scalar / x = scalar * x^-1
            t = ff.pow(tensor, -1.0, name=f"{name}_r")
            return ff.scalar_multiply(t, float(scalar), name=name)
        fn = {"add": ff.scalar_add, "sub": ff.scalar_sub,
              "mul": ff.scalar_multiply, "div": ff.scalar_true_divide}[fname]
        return fn(tensor, float(scalar), name=name)
    if fname == "floordiv":
        if not _is_tensor(a[0]):  # folded shape arithmetic (shape // 2)
            return operator.floordiv(a[0], a[1])
        t = ff.scalar_true_divide(a[0], float(a[1]), name=f"{name}_d")
        return ff.floor(t, name=name)
    if fname == "neg":
        if not _is_tensor(a[0]):
            return -a[0]
        return ff.scalar_multiply(a[0], -1.0, name=name)
    if fname == "pow":
        if not _is_tensor(a[0]):
            return operator.pow(a[0], a[1])
        return ff.pow(a[0], float(a[1]), name=name)
    if fname in _UNARY_FNS:
        return getattr(ff, _UNARY_FNS[fname])(a[0], name=name)
    if fname in ("maximum", "minimum"):
        if len(a) == 1 or not _is_tensor(a[1] if len(a) > 1 else None):
            raise ValueError(f"{fname} reduction form is unsupported")
        return (ff.max if fname == "maximum" else ff.min)(
            a[0], a[1], name=name
        )
    if fname == "softmax":
        return ff.softmax(a[0], axis=_axis_arg(a, kw, 1, default=-1),
                          name=name)
    if fname == "flatten":
        start = kw.get("start_dim", a[1] if len(a) > 1 else 0)
        if start == 1:
            return ff.flat(a[0], name=name)
        shape = a[0].shape.logical_shape
        total = int(np.prod(shape[start:]))
        return ff.reshape(a[0], list(shape[:start]) + [total], name=name)
    if fname == "cat":
        axis = _axis_arg(a, kw, 1, default=0)
        return ff.concat(list(a[0]), axis, name=name)
    if fname == "split":
        axis = _axis_arg(a, kw, 2, default=0)
        spec = a[1]
        if isinstance(spec, int):  # torch semantics: CHUNK SIZE
            size = a[0].shape.logical_shape[axis]
            sizes = [spec] * (size // spec)
            if size % spec:
                sizes.append(size % spec)
        else:
            sizes = list(spec)
        return ff.split(a[0], sizes, axis, name=name)
    if fname == "chunk":
        axis = _axis_arg(a, kw, 2, default=0)
        n = int(a[1])
        size = a[0].shape.logical_shape[axis]
        base = size // n
        sizes = [base + (1 if i < size % n else 0) for i in range(n)]
        return ff.split(a[0], sizes, axis, name=name)
    if fname == "matmul":
        if _is_tensor(a[1]):
            return ff.batch_matmul(a[0], a[1], name=name)
        w = np.asarray(a[1])  # constant weight: x @ W == dense
        return _dense_from_array(ff, a[0], w, None, name, transpose=False)
    if fname == "reshape":
        return _reshape(ff, a[0], a[1], name)
    if fname == "transpose":
        return _transpose2(ff, a[0], a[1], a[2], name)
    if fname == "permute":
        return ff.transpose(a[0], list(a[1]), name=name)
    if fname in ("mean", "sum"):
        axes = _axis_arg(a, kw, 1)
        if axes is None:
            axes = list(range(a[0].shape.logical_rank))
        if isinstance(axes, int):
            axes = [axes]
        fn = ff.mean if fname == "mean" else ff.reduce_sum
        return fn(a[0], list(axes), keepdims=kw.get("keepdim", False),
                  name=name)
    if fname == "unsqueeze":
        return _unsqueeze(ff, a[0], _axis_arg(a, kw, 1, default=0), name)
    if fname == "squeeze":
        return _squeeze(ff, a[0], _axis_arg(a, kw, 1), name)
    if fname == "dropout":
        return ff.dropout(a[0], kw.get("p", a[1] if len(a) > 1 else 0.5),
                          name=name)
    if fname == "getitem":
        return _getitem(ff, a[0], a[1], name)
    if fname == "getattr_":
        x, attr = a[0], a[1]
        if _is_tensor(x) and attr == "shape":
            return tuple(x.shape.logical_shape)
        if not _is_tensor(x):
            return getattr(x, attr)
        raise ValueError(f"unsupported tensor attribute in trace: {attr}")
    if fname == "f_linear":
        w = np.asarray(a[1])
        b = np.asarray(a[2]) if len(a) > 2 and a[2] is not None else kw.get("bias")
        b = np.asarray(b) if b is not None else None
        return _dense_from_array(ff, a[0], w, b, name, transpose=True)
    if fname == "f_conv2d":
        w = np.asarray(a[1])
        b = a[2] if len(a) > 2 else kw.get("bias")
        b = np.asarray(b) if b is not None else None
        stride = kw.get("stride", a[3] if len(a) > 3 else 1)
        padding = kw.get("padding", a[4] if len(a) > 4 else 0)
        groups = kw.get("groups", a[6] if len(a) > 6 else 1)
        stride = stride if isinstance(stride, (tuple, list)) else (stride,) * 2
        padding = padding if isinstance(padding, (tuple, list)) else (padding,) * 2
        out = ff.conv2d(
            a[0], w.shape[0], w.shape[2], w.shape[3], stride[0], stride[1],
            padding[0], padding[1], groups=int(groups),
            use_bias=b is not None, name=name,
        )
        _pin_weights(out.owner_op, kernel=w, bias=b)
        return out
    if fname == "adaptive_avg_pool2d":
        o = a[1] if isinstance(a[1], (tuple, list)) else (a[1], a[1])
        h, w = a[0].shape.logical_shape[2:4]
        return ff.pool2d(a[0], h // o[0], w // o[1], h // o[0], w // o[1],
                         0, 0, pool_type="avg", name=name)
    if fname in ("avg_pool2d", "max_pool2d"):
        k = a[1] if isinstance(a[1], (tuple, list)) else (a[1],) * 2
        s = kw.get("stride", a[2] if len(a) > 2 else None) or k
        s = s if isinstance(s, (tuple, list)) else (s,) * 2
        p = kw.get("padding", a[3] if len(a) > 3 else 0)
        p = p if isinstance(p, (tuple, list)) else (p,) * 2
        return ff.pool2d(a[0], k[0], k[1], s[0], s[1], p[0], p[1],
                         pool_type="avg" if fname == "avg_pool2d" else "max",
                         name=name)
    if fname == "to":
        return _cast_like(ff, a, kw, name)
    raise ValueError(f"unsupported torch function in trace: {fname}")


def lower_method(ff: FFModel, mname: str, a: List, kw: Dict, name: str):
    """Lower one call_method node (reference tensor-method nodes)."""
    canon = _METHOD_ALIASES.get(mname)
    if canon is None:
        raise ValueError(f"unsupported tensor method in trace: {mname}")
    x = a[0]
    if canon == "identity_m":
        return x
    if canon == "size":
        return (x.shape.logical_shape[a[1]] if len(a) > 1
                else x.shape.logical_shape)
    if canon == "reshape":
        shape = a[1] if isinstance(a[1], (tuple, list)) else a[1:]
        return _reshape(ff, x, shape, name)
    if canon == "permute":
        perm = a[1] if isinstance(a[1], (tuple, list)) else a[1:]
        return ff.transpose(x, list(perm), name=name)
    if canon == "transpose":
        return _transpose2(ff, x, a[1], a[2], name)
    if canon == "flatten":
        start = a[1] if len(a) > 1 else 0
        return lower_function(ff, "flatten", [x, start], {}, name)
    if canon == "expand":
        sizes = a[1] if isinstance(a[1], (tuple, list)) else a[1:]
        return ff.expand(x, [int(s) for s in sizes], name=name)
    if canon == "expand_as":
        return ff.expand(x, a[1].shape.logical_shape, name=name)
    if canon == "to":
        return _cast_like(ff, a, kw, name)
    if canon == "to_float":
        return ff.cast(x, DataType.FLOAT, name=name)
    if canon == "type_as":
        return ff.cast(x, a[1].shape.dtype, name=name)
    if canon in ("mean", "sum", "unsqueeze", "squeeze", "chunk", "split",
                 "matmul", "pow", "sqrt", "rsqrt", "relu", "sigmoid",
                 "tanh", "add", "sub", "mul", "div"):
        return lower_function(ff, canon, a, kw, name)
    raise ValueError(f"unsupported tensor method in trace: {mname}")


def _reshape(ff, x, shape, name):
    # Reshape's own shape rule resolves -1 dims (ops/shape.py:63-71)
    return ff.reshape(x, [int(s) for s in shape], name=name)


def _transpose2(ff, x, d0, d1, name):
    perm = list(range(x.shape.logical_rank))
    perm[d0], perm[d1] = perm[d1], perm[d0]
    return ff.transpose(x, perm, name=name)


def _cast_like(ff, a, kw, name):
    target = kw.get("dtype", a[1] if len(a) > 1 else None)
    if target is None:
        return a[0]
    if HAS_TORCH and isinstance(target, torch.dtype):
        target = {
            torch.float32: DataType.FLOAT, torch.float16: DataType.HALF,
            torch.bfloat16: DataType.BF16, torch.int32: DataType.INT32,
            torch.int64: DataType.INT64, torch.float64: DataType.DOUBLE,
        }.get(target)
        if target is None:
            raise ValueError("unsupported torch dtype in .to()")
    if isinstance(target, str):
        target = DataType.from_any(target)
    return ff.cast(a[0], target, name=name)


def _dense_from_array(ff, x, w, b, name, transpose: bool):
    """F.linear / matmul-with-constant: dense with pinned weights.
    torch F.linear weight is [out, in]; plain matmul constant is
    [in, out]."""
    kernel = w.T.copy() if transpose else np.asarray(w)
    out = ff.dense(x, kernel.shape[1], use_bias=b is not None, name=name)
    _pin_weights(out.owner_op, kernel=kernel, bias=b)
    return out


def _pin_weights(op, kernel=None, bias=None):
    by_name = {"kernel": kernel, "bias": bias}
    op.weight_specs = [
        s.__class__(s.name, s.shape, ArrayInitializer(by_name[s.name]))
        if by_name.get(s.name) is not None else s
        for s in op.weight_specs
    ]


# ---------------------------------------------------------------------------
# the importer
# ---------------------------------------------------------------------------

class PyTorchModel:
    """Wraps an nn.Module for lowering into an FFModel.

    Usage (mirrors the reference README.md:17-22 flow):
        pt = PyTorchModel(torch_module)
        out = pt.torch_to_ff(ffmodel, [input_tensor, ...])
        ffmodel.compile(...)
        pt.copy_weights(ffmodel)   # optional: exact torch parity
    or the file route (reference model.py:2442+):
        pt.torch_to_file("model.ir")
        ...elsewhere, no torch needed:
        outs = file_to_ff("model.ir", ffmodel, [input_tensor])
    """

    def __init__(self, module, seq_length: Optional[int] = None):
        assert HAS_TORCH, "torch is required for the PyTorch frontend"
        self.module = module
        self.seq_length = seq_length
        self.traced = torch.fx.symbolic_trace(module)
        # fx node name -> ff op name (for weight copy)
        self._op_of_node: Dict[str, str] = {}

    # ------------------------------------------------------------------
    def torch_to_ff(
        self, ff: FFModel, inputs: Sequence[ParallelTensor]
    ) -> List[ParallelTensor]:
        env: Dict[str, object] = {}
        input_iter = iter(inputs)
        outputs: List[ParallelTensor] = []
        modules = dict(self.traced.named_modules())

        def resolve(x):
            return env[x.name] if isinstance(x, torch.fx.Node) else x

        for node in self.traced.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = next(input_iter)
            elif node.op == "get_attr":
                v = _fetch_attr(self.module, node.target)
                if isinstance(v, torch.Tensor):
                    v = _traced_array(v.detach().numpy(), v.requires_grad)
                env[node.name] = v
            elif node.op == "call_module":
                if node.kwargs:
                    raise ValueError(
                        f"unsupported module kwargs {list(node.kwargs)} on "
                        f"{node.target} (e.g. MHA masks are not lowered)"
                    )
                m = modules[node.target]
                cfg = module_config(m)
                a = torch.fx.node.map_arg(list(node.args), resolve)
                env[node.name] = lower_module(ff, cfg, a, node.name)
                if cfg["kind"] in _WEIGHTED_KINDS:
                    self._op_of_node[node.name] = node.name
            elif node.op == "call_function":
                fname = _FN_NAMES.get(node.target)
                if fname is None:
                    raise ValueError(
                        f"unsupported torch function in trace: {node.target}"
                    )
                a = torch.fx.node.map_arg(list(node.args), resolve)
                kw = torch.fx.node.map_arg(dict(node.kwargs), resolve)
                env[node.name] = lower_function(ff, fname, a, kw, node.name)
            elif node.op == "call_method":
                a = torch.fx.node.map_arg(list(node.args), resolve)
                kw = torch.fx.node.map_arg(dict(node.kwargs), resolve)
                env[node.name] = lower_method(ff, node.target, a, kw,
                                              node.name)
            elif node.op == "output":
                args = node.args[0]
                if isinstance(args, (tuple, list)):
                    outputs.extend(env[a.name] for a in args)
                else:
                    outputs.append(env[args.name])
        return outputs

    # ------------------------------------------------------------------
    # serialized-IR exchange (reference PyTorchModel file format,
    # model.py:2442+: string IR out, replay in — here JSON lines + an
    # optional npz sidecar for get_attr constants)
    # ------------------------------------------------------------------
    def torch_to_file(self, path: str):
        modules = dict(self.traced.named_modules())
        consts: Dict[str, np.ndarray] = {}
        lines: List[str] = []

        def enc(x):
            if isinstance(x, torch.fx.Node):
                return {"__ref__": x.name}
            if isinstance(x, slice):
                return {"__slice__": [x.start, x.stop, x.step]}
            if isinstance(x, (list, tuple)):
                return {"__list__": [enc(v) for v in x]}
            if HAS_TORCH and isinstance(x, torch.dtype):
                return {"__dtype__": str(x).replace("torch.", "")}
            if x is None or isinstance(x, (bool, int, float, str)):
                return x
            raise ValueError(f"unserializable arg {x!r} in fx trace")

        for node in self.traced.graph.nodes:
            if node.op == "placeholder":
                lines.append(json.dumps({"op": "input", "name": node.name}))
            elif node.op == "get_attr":
                v = _fetch_attr(self.module, node.target)
                if isinstance(v, torch.Tensor):
                    consts[node.name] = v.detach().numpy()
                    lines.append(json.dumps(
                        {"op": "const", "name": node.name,
                         "trainable": bool(v.requires_grad)}))
                else:
                    lines.append(json.dumps(
                        {"op": "literal", "name": node.name, "value": v}))
            elif node.op in ("call_module", "call_function", "call_method"):
                if node.op == "call_module" and node.kwargs:
                    raise ValueError(
                        f"unsupported module kwargs {list(node.kwargs)} on "
                        f"{node.target}"
                    )
                rec = {
                    "op": node.op,
                    "name": node.name,
                    "args": [enc(x) for x in node.args],
                    "kwargs": {k: enc(v) for k, v in node.kwargs.items()},
                }
                if node.op == "call_module":
                    rec["config"] = module_config(modules[node.target])
                elif node.op == "call_function":
                    fname = _FN_NAMES.get(node.target)
                    if fname is None:
                        raise ValueError(
                            f"unsupported function {node.target} in trace"
                        )
                    rec["target"] = fname
                else:
                    rec["target"] = node.target
                lines.append(json.dumps(rec))
            elif node.op == "output":
                args = node.args[0]
                refs = ([a.name for a in args]
                        if isinstance(args, (tuple, list)) else [args.name])
                lines.append(json.dumps({"op": "output", "refs": refs}))
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        if consts:
            np.savez(path + ".npz", **consts)

    # ------------------------------------------------------------------
    # weight transfer (reference: file-format apply; here direct)
    # ------------------------------------------------------------------
    def copy_weights(self, ff: FFModel):
        """Copy the torch module's parameters into the compiled FFModel
        (torch Linear weight [out, in] -> ff kernel [in, out])."""
        weights = ff.get_weights()
        modules = dict(self.traced.named_modules())
        for fx_name, op_name in self._op_of_node.items():
            node = next(n for n in self.traced.graph.nodes if n.name == fx_name)
            m = modules[node.target]
            if op_name not in weights:
                continue
            entry = weights[op_name]
            if isinstance(m, nn.Linear):
                entry["kernel"] = m.weight.detach().numpy().T.copy()
                if m.bias is not None:
                    entry["bias"] = m.bias.detach().numpy().copy()
            elif isinstance(m, nn.Conv2d):
                # torch [out, in/g, kh, kw] -> ours matches lax HWIO? our
                # Conv2D stores torch-layout kernel (see ops/dense.py)
                entry["kernel"] = m.weight.detach().numpy().copy()
                if m.bias is not None:
                    entry["bias"] = m.bias.detach().numpy().copy()
            elif isinstance(m, nn.Embedding):
                entry["weight"] = m.weight.detach().numpy().copy()
            elif isinstance(m, nn.LayerNorm) and m.elementwise_affine:
                entry["gamma"] = m.weight.detach().numpy().copy()
                entry["beta"] = m.bias.detach().numpy().copy()
            elif isinstance(m, nn.BatchNorm2d):
                entry["gamma"] = m.weight.detach().numpy().copy()
                entry["beta"] = m.bias.detach().numpy().copy()
                # running stats live in the op-state pytree (weights[2:]
                # of a has_aux_state op), not in get_weights — pretrained
                # eval-mode parity needs them transferred too
                st = (ff._state or {}).get(op_name)
                if st is not None:
                    import jax as _jax

                    for sname, tv in (("running_mean", m.running_mean),
                                      ("running_var", m.running_var)):
                        if sname in st and tv is not None:
                            old = st[sname]
                            st[sname] = _jax.device_put(
                                np.asarray(tv.detach().numpy(),
                                           old.dtype),
                                old.sharding,
                            )
            elif isinstance(m, nn.MultiheadAttention):
                # packed in_proj [3E, E] (or separate q/k/v_proj_weight
                # when kdim/vdim differ) / out_proj [E, E] -> per-head
                # wq/wk/wv [E_in, H, C], wo [H, C, E] (ops/attention.py)
                E, H = m.embed_dim, m.num_heads
                C = E // H

                def per_head(w):  # [E_out=H*C, E_in] -> [E_in, H, C]
                    e_in = w.shape[1]
                    return w.reshape(H, C, e_in).transpose(2, 0, 1).copy()

                if m.in_proj_weight is not None:
                    ipw = m.in_proj_weight.detach().numpy()
                    wq, wk, wv = ipw[:E], ipw[E:2 * E], ipw[2 * E:]
                else:  # kdim/vdim != embed_dim: torch stores them split
                    wq = m.q_proj_weight.detach().numpy()
                    wk = m.k_proj_weight.detach().numpy()
                    wv = m.v_proj_weight.detach().numpy()
                entry["wq"] = per_head(wq)
                entry["wk"] = per_head(wk)
                entry["wv"] = per_head(wv)
                entry["wo"] = (m.out_proj.weight.detach().numpy()
                               .reshape(E, H, C).transpose(1, 2, 0).copy())
                if m.in_proj_bias is not None:
                    ipb = m.in_proj_bias.detach().numpy()
                    entry["bq"] = ipb[:E].reshape(H, C).copy()
                    entry["bk"] = ipb[E:2 * E].reshape(H, C).copy()
                    entry["bv"] = ipb[2 * E:].reshape(H, C).copy()
                    entry["bo"] = m.out_proj.bias.detach().numpy().copy()
                if m.bias_k is not None and "bias_k" in entry:
                    # appended bias token, torch [1, 1, E] -> [1, H, C]
                    entry["bias_k"] = (m.bias_k.detach().numpy()
                                       .reshape(1, H, C).copy())
                    entry["bias_v"] = (m.bias_v.detach().numpy()
                                       .reshape(1, H, C).copy())
        ff.set_weights(weights)


# ---------------------------------------------------------------------------
# file replay (torch-free)
# ---------------------------------------------------------------------------

def file_to_ff(path: str, ff: FFModel,
               inputs: Sequence[ParallelTensor]) -> List[ParallelTensor]:
    """Replay a serialized fx IR (torch_to_file) into an FFModel — no
    torch required (the reference's `PyTorchModel.apply` file route)."""
    import os

    consts = {}
    if os.path.exists(path + ".npz"):
        consts = dict(np.load(path + ".npz"))
    env: Dict[str, object] = {}
    input_iter = iter(inputs)
    outputs: List[ParallelTensor] = []

    def dec(x):
        if isinstance(x, dict):
            if "__ref__" in x:
                return env[x["__ref__"]]
            if "__slice__" in x:
                return slice(*x["__slice__"])
            if "__list__" in x:
                return [dec(v) for v in x["__list__"]]
            if "__dtype__" in x:
                return x["__dtype__"]
        return x

    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            op = rec["op"]
            if op == "input":
                env[rec["name"]] = next(input_iter)
            elif op == "const":
                env[rec["name"]] = _traced_array(
                    consts[rec["name"]], rec.get("trainable", True)
                )
            elif op == "literal":
                env[rec["name"]] = rec["value"]
            elif op == "output":
                outputs.extend(env[r] for r in rec["refs"])
            else:
                a = [dec(x) for x in rec["args"]]
                kw = {k: dec(v) for k, v in rec["kwargs"].items()}
                name = rec["name"]
                if op == "call_module":
                    env[name] = lower_module(ff, rec["config"], a, name)
                elif op == "call_function":
                    # getitem indices serialize tuples as __list__
                    if rec["target"] == "getitem" and isinstance(a[1], list):
                        a[1] = tuple(a[1])
                    env[name] = lower_function(ff, rec["target"], a, kw, name)
                else:
                    env[name] = lower_method(ff, rec["target"], a, kw, name)
    return outputs


def _fetch_attr(module, target: str):
    obj = module
    for part in target.split("."):
        obj = getattr(obj, part)
    return obj


def torch_to_flexflow(module, ff: FFModel,
                      inputs: Sequence[ParallelTensor]):
    """One-call convenience (reference fx.torch_to_flexflow)."""
    pt = PyTorchModel(module)
    return pt, pt.torch_to_ff(ff, inputs)
