"""torch.fx -> FFModel importer.

Reference: python/flexflow/torch/model.py — `PyTorchModel` traces an
nn.Module with a customed fx tracer and lowers every fx node through a
per-op Node subclass's `to_ff` (LinearNode.to_ff at model.py:285, ~60
node kinds).  TPU-native redesign: one dispatch table lowering fx nodes
straight to FFModel layer-API calls; weights transfer via
`copy_weights` after compile (torch Linear stores [out, in] — ours is
[in, out], transposed on the way in).
"""
from __future__ import annotations

import operator
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..fftype import ActiMode, DataType
from ..model import FFModel
from ..tensor import ParallelTensor

try:
    import torch
    import torch.fx
    import torch.nn as nn
    import torch.nn.functional as F

    HAS_TORCH = True
except ImportError:  # pragma: no cover - torch is baked into this image
    HAS_TORCH = False


def _act_of(module) -> ActiMode:
    import torch.nn as nn

    if isinstance(module, nn.ReLU):
        return ActiMode.RELU
    if isinstance(module, nn.GELU):
        return ActiMode.GELU
    if isinstance(module, nn.Sigmoid):
        return ActiMode.SIGMOID
    if isinstance(module, nn.Tanh):
        return ActiMode.TANH
    raise ValueError(f"unsupported activation module {module}")


class PyTorchModel:
    """Wraps an nn.Module for lowering into an FFModel.

    Usage (mirrors the reference README.md:17-22 flow):
        pt = PyTorchModel(torch_module)
        out = pt.torch_to_ff(ffmodel, [input_tensor, ...])
        ffmodel.compile(...)
        pt.copy_weights(ffmodel)   # optional: exact torch parity
    """

    def __init__(self, module, seq_length: Optional[int] = None):
        assert HAS_TORCH, "torch is required for the PyTorch frontend"
        self.module = module
        self.seq_length = seq_length
        self.traced = torch.fx.symbolic_trace(module)
        # fx node name -> ff op name (for weight copy)
        self._op_of_node: Dict[str, str] = {}

    # ------------------------------------------------------------------
    def torch_to_ff(
        self, ff: FFModel, inputs: Sequence[ParallelTensor]
    ) -> List[ParallelTensor]:
        env: Dict[str, object] = {}
        input_iter = iter(inputs)
        outputs: List[ParallelTensor] = []
        modules = dict(self.traced.named_modules())

        for node in self.traced.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = next(input_iter)
            elif node.op == "get_attr":
                env[node.name] = _fetch_attr(self.module, node.target)
            elif node.op == "call_module":
                env[node.name] = self._lower_module(
                    ff, node, modules[node.target], env
                )
            elif node.op == "call_function":
                env[node.name] = self._lower_function(ff, node, env)
            elif node.op == "call_method":
                env[node.name] = self._lower_method(ff, node, env)
            elif node.op == "output":
                args = node.args[0]
                if isinstance(args, (tuple, list)):
                    outputs.extend(env[a.name] for a in args)
                else:
                    outputs.append(env[args.name])
        return outputs

    # ------------------------------------------------------------------
    # call_module lowerings (reference model.py:248-1200 module nodes)
    # ------------------------------------------------------------------
    def _lower_module(self, ff: FFModel, node, m, env):
        a = [env[x.name] if isinstance(x, torch.fx.Node) else x
             for x in node.args]
        name = node.name
        if isinstance(m, nn.Linear):
            out = ff.dense(a[0], m.out_features, use_bias=m.bias is not None,
                           name=name)
            self._op_of_node[node.name] = name
            return out
        if isinstance(m, nn.Conv2d):
            assert m.padding_mode == "zeros"
            pad = m.padding if isinstance(m.padding, tuple) else (m.padding, m.padding)
            out = ff.conv2d(
                a[0], m.out_channels, m.kernel_size[0], m.kernel_size[1],
                m.stride[0], m.stride[1], pad[0], pad[1],
                groups=m.groups, use_bias=m.bias is not None, name=name,
            )
            self._op_of_node[node.name] = name
            return out
        if isinstance(m, nn.MaxPool2d):
            k = m.kernel_size if isinstance(m.kernel_size, tuple) else (m.kernel_size,) * 2
            s = m.stride if isinstance(m.stride, tuple) else (m.stride or m.kernel_size,) * 2
            p = m.padding if isinstance(m.padding, tuple) else (m.padding,) * 2
            return ff.pool2d(a[0], k[0], k[1], s[0], s[1], p[0], p[1],
                             pool_type="max", name=name)
        if isinstance(m, nn.AvgPool2d):
            k = m.kernel_size if isinstance(m.kernel_size, tuple) else (m.kernel_size,) * 2
            s = m.stride if isinstance(m.stride, tuple) else (m.stride or m.kernel_size,) * 2
            p = m.padding if isinstance(m.padding, tuple) else (m.padding,) * 2
            return ff.pool2d(a[0], k[0], k[1], s[0], s[1], p[0], p[1],
                             pool_type="avg", name=name)
        if isinstance(m, nn.AdaptiveAvgPool2d):
            osize = m.output_size if isinstance(m.output_size, tuple) else (
                m.output_size, m.output_size)
            h, w = a[0].shape.logical_shape[2:4]
            kh, kw = h // osize[0], w // osize[1]
            return ff.pool2d(a[0], kh, kw, kh, kw, 0, 0, pool_type="avg",
                             name=name)
        if isinstance(m, nn.BatchNorm2d):
            out = ff.batch_norm(a[0], relu=False, name=name)
            self._op_of_node[node.name] = name
            return out
        if isinstance(m, nn.LayerNorm):
            rank = a[0].shape.logical_rank
            ndims = len(m.normalized_shape)
            axes = tuple(range(rank - ndims, rank))
            out = ff.layer_norm(a[0], axes, m.elementwise_affine, m.eps,
                                name=name)
            self._op_of_node[node.name] = name
            return out
        if isinstance(m, nn.Embedding):
            out = ff.embedding(a[0], m.num_embeddings, m.embedding_dim,
                               name=name)
            self._op_of_node[node.name] = name
            return out
        if isinstance(m, (nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh)):
            act = _act_of(m)
            fn = {ActiMode.RELU: ff.relu, ActiMode.GELU: ff.gelu,
                  ActiMode.SIGMOID: ff.sigmoid, ActiMode.TANH: ff.tanh}[act]
            return fn(a[0], name=name)
        if isinstance(m, nn.Softmax):
            return ff.softmax(a[0], axis=m.dim if m.dim is not None else -1,
                              name=name)
        if isinstance(m, nn.Dropout):
            return ff.dropout(a[0], m.p, name=name)
        if isinstance(m, nn.Flatten):
            assert m.start_dim == 1 and m.end_dim == -1, (
                "only full flatten supported"
            )
            return ff.flat(a[0], name=name)
        if isinstance(m, nn.Identity):
            return a[0]
        if isinstance(m, nn.MultiheadAttention):
            assert m.batch_first, "set batch_first=True for MHA import"
            out = ff.multihead_attention(
                a[0], a[1], a[2], m.embed_dim, m.num_heads,
                dropout=m.dropout, bias=m.in_proj_bias is not None,
                add_bias_kv=m.bias_k is not None, name=name,
            )
            self._op_of_node[node.name] = name
            return out
        raise ValueError(f"unsupported torch module in trace: {m}")

    # ------------------------------------------------------------------
    # call_function lowerings (reference model.py FunctionNode kinds)
    # ------------------------------------------------------------------
    def _lower_function(self, ff: FFModel, node, env):
        # map_arg resolves Nodes nested inside lists/tuples (torch.cat)
        a = torch.fx.node.map_arg(list(node.args), lambda n: env[n.name])
        kw = torch.fx.node.map_arg(dict(node.kwargs), lambda n: env[n.name])
        t = node.target
        name = node.name

        def is_tensor(x):
            return isinstance(x, ParallelTensor)

        if t in (operator.add, torch.add):
            if is_tensor(a[0]) and is_tensor(a[1]):
                return ff.add(a[0], a[1], name=name)
            tensor, scalar = (a[0], a[1]) if is_tensor(a[0]) else (a[1], a[0])
            return ff.scalar_add(tensor, float(scalar), name=name)
        if t in (operator.sub, torch.sub):
            if is_tensor(a[0]) and is_tensor(a[1]):
                return ff.subtract(a[0], a[1], name=name)
            return ff.scalar_sub(a[0], float(a[1]), name=name)
        if t in (operator.mul, torch.mul):
            if is_tensor(a[0]) and is_tensor(a[1]):
                return ff.multiply(a[0], a[1], name=name)
            tensor, scalar = (a[0], a[1]) if is_tensor(a[0]) else (a[1], a[0])
            return ff.scalar_multiply(tensor, float(scalar), name=name)
        if t in (operator.truediv, torch.div):
            if is_tensor(a[0]) and is_tensor(a[1]):
                return ff.divide(a[0], a[1], name=name)
            return ff.scalar_true_divide(a[0], float(a[1]), name=name)
        if t in (torch.relu, F.relu):
            return ff.relu(a[0], name=name)
        if t is F.gelu:
            return ff.gelu(a[0], name=name)
        if t in (torch.sigmoid, F.sigmoid):
            return ff.sigmoid(a[0], name=name)
        if t in (torch.tanh, F.tanh):
            return ff.tanh(a[0], name=name)
        if t is F.softmax:
            return ff.softmax(a[0], axis=kw.get("dim", a[1] if len(a) > 1 else -1),
                              name=name)
        if t is torch.flatten:
            return ff.flat(a[0], name=name)
        if t is torch.cat:
            tensors = a[0]
            axis = kw.get("dim", a[1] if len(a) > 1 else 0)
            return ff.concat(list(tensors), axis, name=name)
        if t is torch.split:
            axis = kw.get("dim", a[2] if len(a) > 2 else 0)
            return ff.split(a[0], a[1], axis, name=name)
        if t in (torch.matmul, torch.bmm):
            return ff.batch_matmul(a[0], a[1], name=name)
        if t is torch.reshape:
            return ff.reshape(a[0], a[1], name=name)
        if t is torch.transpose:
            return self._transpose(ff, a[0], a[1], a[2], name)
        if t is torch.permute:
            return ff.transpose(a[0], a[1], name=name)
        if t is torch.mean:
            axes = kw.get("dim", a[1] if len(a) > 1 else None)
            if axes is None:
                axes = list(range(a[0].shape.logical_rank))
            if isinstance(axes, int):
                axes = [axes]
            return ff.mean(a[0], axes, keepdims=kw.get("keepdim", False),
                           name=name)
        if t is F.dropout:
            return ff.dropout(a[0], kw.get("p", a[1] if len(a) > 1 else 0.5),
                              name=name)
        if t is getattr(operator, "getitem"):
            return a[0][a[1]]
        raise ValueError(f"unsupported torch function in trace: {t}")

    def _transpose(self, ff, x, d0, d1, name):
        perm = list(range(x.shape.logical_rank))
        perm[d0], perm[d1] = perm[d1], perm[d0]
        return ff.transpose(x, perm, name=name)

    # ------------------------------------------------------------------
    # call_method lowerings
    # ------------------------------------------------------------------
    def _lower_method(self, ff: FFModel, node, env):
        a = [env[x.name] if isinstance(x, torch.fx.Node) else x
             for x in node.args]
        m = node.target
        name = node.name
        self_t = a[0]
        if m in ("view", "reshape"):
            shape = a[1] if isinstance(a[1], (tuple, list)) else a[1:]
            shape = [int(s) for s in shape]
            if any(s == -1 for s in shape):
                total = self_t.shape.num_elements() if hasattr(
                    self_t.shape, "num_elements") else int(
                        np.prod(self_t.shape.logical_shape))
                known = -int(np.prod([s for s in shape if s != -1]))
                shape = [total // known if s == -1 else s for s in shape]
            return ff.reshape(self_t, shape, name=name)
        if m == "permute":
            perm = a[1] if isinstance(a[1], (tuple, list)) else a[1:]
            return ff.transpose(self_t, list(perm), name=name)
        if m == "transpose":
            return self._transpose(ff, self_t, a[1], a[2], name)
        if m == "flatten":
            start = a[1] if len(a) > 1 else 0  # Tensor.flatten defaults to 0
            if start == 1:
                return ff.flat(self_t, name=name)
            shape = self_t.shape.logical_shape
            total = int(np.prod(shape[start:]))
            return ff.reshape(self_t, list(shape[:start]) + [total], name=name)
        if m == "contiguous":
            return self_t
        if m == "mean":
            axes = a[1] if len(a) > 1 else list(range(self_t.shape.logical_rank))
            if isinstance(axes, int):
                axes = [axes]
            return ff.mean(self_t, axes, name=name)
        if m == "size":
            return self_t.shape.logical_shape[a[1]] if len(a) > 1 else (
                self_t.shape.logical_shape)
        raise ValueError(f"unsupported tensor method in trace: {m}")

    # ------------------------------------------------------------------
    # weight transfer (reference: file-format apply; here direct)
    # ------------------------------------------------------------------
    def copy_weights(self, ff: FFModel):
        """Copy the torch module's parameters into the compiled FFModel
        (torch Linear weight [out, in] -> ff kernel [in, out])."""
        weights = ff.get_weights()
        modules = dict(self.traced.named_modules())
        for fx_name, op_name in self._op_of_node.items():
            node = next(n for n in self.traced.graph.nodes if n.name == fx_name)
            m = modules[node.target]
            if op_name not in weights:
                continue
            entry = weights[op_name]
            if isinstance(m, nn.Linear):
                entry["kernel"] = m.weight.detach().numpy().T.copy()
                if m.bias is not None:
                    entry["bias"] = m.bias.detach().numpy().copy()
            elif isinstance(m, nn.Conv2d):
                # torch [out, in/g, kh, kw] -> ours matches lax HWIO? our
                # Conv2D stores torch-layout kernel (see ops/dense.py)
                entry["kernel"] = m.weight.detach().numpy().copy()
                if m.bias is not None:
                    entry["bias"] = m.bias.detach().numpy().copy()
            elif isinstance(m, nn.Embedding):
                entry["weight"] = m.weight.detach().numpy().copy()
            elif isinstance(m, nn.LayerNorm) and m.elementwise_affine:
                entry["gamma"] = m.weight.detach().numpy().copy()
                entry["beta"] = m.bias.detach().numpy().copy()
            elif isinstance(m, nn.BatchNorm2d):
                entry["gamma"] = m.weight.detach().numpy().copy()
                entry["beta"] = m.bias.detach().numpy().copy()
        ff.set_weights(weights)


def _fetch_attr(module, target: str):
    obj = module
    for part in target.split("."):
        obj = getattr(obj, part)
    return obj


def torch_to_flexflow(module, ff: FFModel,
                      inputs: Sequence[ParallelTensor]):
    """One-call convenience (reference fx.torch_to_flexflow)."""
    pt = PyTorchModel(module)
    return pt, pt.torch_to_ff(ff, inputs)
