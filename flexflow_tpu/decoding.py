"""KV-cache incremental decoding (TPU-native serving machinery).

The reference has no incremental decoder at all — its legacy nmt/
re-runs the full graph per emitted token and triton/ is an incomplete
prototype.  Here decoding is a first-class graph mode: attention ops
built with decode_max_seq=N carry fixed-shape [b, N, h, d] k/v caches
plus a position counter in the op-state pytree (the same functional
state channel BatchNorm running stats use), so one decode step is a
seq-1 forward that appends to the caches — O(T) generation instead of
the O(T^2) re-forward loop of models.transformer.gpt_generate.

Two drivers:
  * gpt_generate_cached — host loop over FFModel.decode_step (one
    device round trip per token; simple, streams tokens);
  * gpt_generate_scan — the WHOLE generation (prefill + sample loop)
    as ONE jitted lax.scan program: zero host round trips until the
    final token buffer lands.  Through a high-latency link (the axon
    tunnel's ~80 ms RTT) this is the difference between RTT x T and
    RTT x 1.

`make_gpt_decoder` builds the seq-1 decode twin of a trained
models.transformer.build_gpt model by introspecting its graph and
copies the weights across (shapes are seq-independent; the position
table is shared via build_gpt's max_positions).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .fftype import LossType, OperatorType
from .model import FFModel
from .optimizer import SGDOptimizer


def _gpt_dims(ff: FFModel) -> Dict[str, int]:
    """Read the build_gpt hyperparameters back off a built graph."""
    by_name = {op.name: op for op in ff.layers.topo_order()}
    attn = [
        op for op in ff.layers.topo_order()
        if op.op_type == OperatorType.MULTIHEAD_ATTENTION
    ]
    if (not attn or "tok_embed" not in by_name or "pos_embed" not in by_name
            or "ffn1_0" not in by_name):
        raise ValueError(
            "make_gpt_decoder expects a models.transformer.build_gpt "
            "graph (tok_embed/pos_embed/attn_i/ffn1_i naming)"
        )
    p = attn[0].params
    tok = by_name["tok_embed"].params
    pos = by_name["pos_embed"].params
    ffn1 = by_name["ffn1_0"].params
    return {
        "num_layers": len(attn),
        "hidden_size": p.embed_dim,
        "num_heads": p.num_heads,
        "dropout": p.dropout,
        "vocab_size": tok.num_entries,
        "max_seq": pos.num_entries,
        "intermediate_size": ffn1.out_channels,
    }


def gpt_decode_tp_strategy(tp: int, num_layers: int):
    """Head-tensor-parallel strategy for a decode twin: one replica
    spans tp chips on a {"data": 1, "model": tp} mesh — attention
    heads and FFN out-channels column-parallel on the model axis
    (ffn2 row-parallel automatically), and every paged KV pool's head
    dim rides the same axis (ops/attention._paged_state_specs), so
    per-chip KV bytes are 1/tp.  The bert_tp_strategy shape with the
    data axis degenerate: decode batches are slot-owned, never
    repartitioned."""
    from .ops.op import ShardConfig
    from .strategy import Strategy

    s = Strategy(mesh_axes={"data": 1, "model": int(tp)})
    for i in range(num_layers):
        s.shard_configs[f"attn_{i}"] = ShardConfig(channel=tp)
        s.shard_configs[f"ffn1_{i}"] = ShardConfig(channel=tp)
    return s


def make_gpt_decoder(ff_train: FFModel, batch_size: Optional[int] = None,
                     devices=None, kv_page_size: int = 0,
                     kv_num_blocks: int = 0,
                     step_tokens: int = 1,
                     kv_kernel: str = "gather",
                     tp: int = 1) -> FFModel:
    """Build + compile the KV-cache decode twin of a trained GPT and
    transfer its weights.  The decode graph is seq-`step_tokens`
    (default 1) with decode_max_seq = the trained model's
    position-table size.

    kv_page_size > 0 builds the PAGED twin (serving/scheduler.py):
    every attention layer's k/v cache is a [kv_num_blocks,
    kv_page_size, h, d] block pool with a host-owned per-slot block
    table + seq_lens instead of a dense per-slot [b, max_seq, h, d]
    buffer — continuous batching's allocation substrate.

    step_tokens > 1 (paged mode only) builds the [b, C] CHUNKED twin:
    one step scatters C tokens at each row's own positions and attends
    causally within the chunk — the multi-token prefill shape
    (build_paged_chunk_step).  Its state pytree is congruent with the
    seq-1 twin's (pools, tables and seq_lens are all seq-independent),
    so both programs thread one shared state.

    kv_kernel selects the paged READ formulation (docs/SERVING.md
    "Fused paged attention"): "gather" (default) is the dense
    block-gather oracle; "pallas" streams blocks in place through the
    fused kernel.  Validated against the runtime HERE — a pallas-less
    jax fails with ConfigError before any graph is built.

    tp > 1 compiles the twin over a tp-chip {"data": 1, "model": tp}
    replica mesh under GSPMD (docs/SERVING.md "Tensor-parallel
    replicas"): heads, FFN channels and the KV pools' head dims shard
    over the model axis, per-chip KV bytes drop to 1/tp, and greedy
    decoding stays token-identical to the tp=1 twin.  The strategy is
    served through the strategy store keyed by the decode graph x the
    replica mesh fingerprint (store/key.py) — the same consult-then-
    publish path training compiles use at spin-up.  Validated against
    the head count and visible devices HERE (resolve_serving_tp) —
    never a mid-compile shape error."""
    from .config import FFConfig, resolve_paged_kernel, resolve_serving_tp
    from .models.transformer import build_gpt

    if step_tokens < 1:
        raise ValueError(f"step_tokens must be >= 1, got {step_tokens}")
    if step_tokens > 1 and not kv_page_size:
        raise ValueError(
            "step_tokens > 1 needs the paged twin (kv_page_size > 0): "
            "the dense cache's scalar position counter cannot express "
            "per-row chunk positions")
    # validate the NAME first so a typo gets the "must be one of"
    # diagnostic, not advice to turn on paging
    kv_kernel = resolve_paged_kernel(kv_kernel)
    if kv_kernel != "gather" and not kv_page_size:
        raise ValueError(
            f"kv_kernel={kv_kernel!r} needs the paged twin "
            "(kv_page_size > 0): the dense cache has no block table "
            "to stream through")
    dims = _gpt_dims(ff_train)
    tp = resolve_serving_tp(
        tp, num_heads=dims["num_heads"],
        visible_devices=len(devices) if devices is not None else None,
    )
    b = batch_size or ff_train.config.batch_size
    cfg = FFConfig(
        batch_size=b, num_devices=tp,
        compute_dtype=ff_train.config.compute_dtype,
        only_data_parallel=(tp == 1),
        # replica cold start (docs/STORE.md): the twin's compile keeps
        # the train model's artifact-store wiring, so its decode step
        # reloads from the XLA persistent cache on spin-up instead of
        # recompiling (tp=1 never searches — the compilation cache is
        # the piece that matters there; tp>1 additionally restores its
        # sharding strategy through the store below)
        strategy_store=ff_train.config.strategy_store,
        compilation_cache=ff_train.config.compilation_cache,
    )
    ffd = FFModel(cfg)
    build_gpt(
        ffd, batch_size=b, seq_length=step_tokens,
        hidden_size=dims["hidden_size"], num_layers=dims["num_layers"],
        num_heads=dims["num_heads"],
        intermediate_size=dims["intermediate_size"],
        vocab_size=dims["vocab_size"], dropout=0.0,
        max_positions=dims["max_seq"], decode_max_seq=dims["max_seq"],
        kv_page_size=kv_page_size, kv_num_blocks=kv_num_blocks,
        kv_kernel=kv_kernel,
    )
    strategy = None
    if tp > 1:
        # consult-then-publish through the strategy store, keyed by the
        # DECODE graph x the replica's tp-chip mesh fingerprint — a new
        # replica at the same tp restores the layout instead of
        # rebuilding it (FFModel.compile skips the store for explicit
        # strategies, so the decoder routes through it here)
        from .store import cached_search

        strategy = cached_search(
            ffd, tp,
            lambda: gpt_decode_tp_strategy(tp, dims["num_layers"]),
        )
    ffd.compile(
        optimizer=SGDOptimizer(lr=0.0),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        strategy=strategy,
        devices=devices,
    )
    # weight transfer by (op, spec) name — all shapes are
    # seq-independent, so the trained pytree drops straight in.
    # Each entry is device_put onto the DECODE twin's sharding (the
    # compile-initialized placeholder carries it): on a tp replica
    # mesh this shards the trained weights over the model axis; at
    # tp=1 it is the identity placement.
    import jax

    missing = []
    new_w = {}
    for op_name, entries in ffd._weights.items():
        src = ff_train._weights.get(op_name)
        new_entries = {}
        for k, v in entries.items():
            if src is None or k not in src:
                missing.append(f"{op_name}.{k}")
                new_entries[k] = v
                continue
            sv = src[k]
            if tuple(sv.shape) != tuple(v.shape):
                raise ValueError(
                    f"decode weight {op_name}.{k}: trained shape "
                    f"{tuple(sv.shape)} != decode shape {tuple(v.shape)}"
                )
            sv = sv if sv.dtype == v.dtype else sv.astype(v.dtype)
            if tp > 1:
                sv = jax.device_put(np.asarray(sv), v.sharding)
            new_entries[k] = sv
        new_w[op_name] = new_entries
    if missing:
        raise ValueError(f"decode graph weights missing in trained "
                         f"model: {missing}")
    ffd._weights = new_w
    return ffd


def gpt_generate_cached(ffd: FFModel, prompt_ids, max_new_tokens: int,
                        temperature: float = 0.0, seed: int = 0,
                        top_k: int = 0, top_p: float = 0.0) -> np.ndarray:
    """Host-loop KV-cache generation on a make_gpt_decoder model:
    prefill feeds prompt tokens one per step (caches fill as a side
    effect), then each sampled token feeds back.  Exactly matches
    gpt_generate's outputs at temperature 0 (same model, same math,
    one attention row at a time)."""
    from .models.transformer import sample_next, validate_sampling

    validate_sampling(top_k, top_p)
    prompt_ids = np.asarray(prompt_ids, np.int32)
    dims = _gpt_dims(ffd)
    max_seq = dims["max_seq"]
    batch, plen = prompt_ids.shape
    if plen < 1:
        raise ValueError("gpt_generate_cached needs a non-empty prompt")
    if batch != ffd.config.batch_size:
        raise ValueError(
            f"prompt batch {batch} != decoder batch {ffd.config.batch_size}"
        )
    total = min(max_seq, plen + max_new_tokens)
    ffd.reset_decode_state()
    buf = np.zeros((batch, total), np.int32)
    buf[:, :plen] = prompt_ids[:, :total]
    rng = np.random.RandomState(seed)
    # the token at total-1 is the last ever written, so its decode step
    # (whose logits nothing consumes) is never run
    for t in range(total - 1):
        logits = np.asarray(
            ffd.decode_step({
                "input": buf[:, t:t + 1],
                "positions": np.full((batch, 1), t, np.int32),
            }),
            np.float32,
        )
        if t + 1 < plen:
            continue  # prefill: the next token is given
        buf[:, t + 1] = sample_next(logits[:, 0], temperature, rng,
                                    top_k, top_p)
    return buf


def _reorder_cache_rows(ffd: FFModel, perm: np.ndarray):
    """Gather KV-cache batch rows by `perm` (beam-hop bookkeeping: row
    i's history becomes row perm[i]'s).  cache_pos is identical across
    rows and untouched; placement is preserved per entry."""
    import jax
    import jax.numpy as jnp

    if np.array_equal(perm, np.arange(len(perm))):
        return
    idx = jnp.asarray(perm)
    new_state = {}
    for op, entries in ffd._state.items():
        ne = {}
        for k, v in entries.items():
            if k in ("k_cache", "v_cache"):
                ne[k] = jax.device_put(jnp.take(v, idx, axis=0), v.sharding)
            else:
                ne[k] = v
        new_state[op] = ne
    ffd._state = new_state


def gpt_beam_search_cached(ffd: FFModel, prompt_ids, max_new_tokens: int,
                           beam_size: int = 4, length_penalty: float = 0.0,
                           eos_id: int = -1):
    """KV-cached, batched beam search on a make_gpt_decoder model
    (VERDICT r4 #3: the O(T) replacement for
    models.transformer.gpt_beam_search, which re-runs the full forward
    per token and takes a single prompt).

    Beams ride the decoder's batch dimension: `num_prompts * beam_size`
    must equal the compiled decode batch.  Each selection step gathers
    the KV-cache rows by source beam (_reorder_cache_rows) so every
    row's cache always matches its hypothesis history.  Scoring is
    identical to the full-forward path: summed token log-probs, GNMT
    ((5+len)/6)^lp length normalization, eos freezing with frozen
    beams competing at their final score.

    prompt_ids: [num_prompts, prompt_len] ints.
    Returns (tokens [num_prompts, total_len], scores [num_prompts]).
    """
    prompt_ids = np.asarray(prompt_ids, np.int32)
    if prompt_ids.ndim == 1:
        prompt_ids = prompt_ids[None]
    dims = _gpt_dims(ffd)
    max_seq = dims["max_seq"]
    P, plen = prompt_ids.shape
    K = beam_size
    if plen < 1:
        raise ValueError("gpt_beam_search_cached needs a non-empty prompt")
    if P * K != ffd.config.batch_size:
        raise ValueError(
            f"num_prompts*beam_size = {P}*{K} != decoder batch "
            f"{ffd.config.batch_size}"
        )
    total = min(max_seq, plen + max_new_tokens)
    B = P * K

    ffd.reset_decode_state()
    buf = np.zeros((B, total), np.int32)
    buf[:, :plen] = np.repeat(prompt_ids[:, :total], K, axis=0)
    scores = np.full((P, K), -np.inf, np.float64)
    scores[:, 0] = 0.0  # one distinct hypothesis per prompt at step 1
    alive = np.ones((P, K), bool)
    gen_len = np.zeros((P, K), np.int64)

    for t in range(total - 1):
        logits = np.asarray(
            ffd.decode_step({
                "input": buf[:, t:t + 1],
                "positions": np.full((B, 1), t, np.int32),
            }),
            np.float32,
        )
        if t + 1 < plen:
            continue  # prefill: every row follows its prompt
        step = logits[:, 0].reshape(P, K, -1)
        z = step - step.max(-1, keepdims=True)
        lp = z - np.log(np.exp(z).sum(-1, keepdims=True))  # [P, K, vocab]
        vocab = lp.shape[-1]
        cand = scores[..., None] + np.where(alive[..., None], lp, -np.inf)
        for p in range(P):
            if eos_id >= 0 and not alive[p].all():
                cand[p, ~alive[p], :] = -np.inf
                cand[p, ~alive[p], 0] = scores[p, ~alive[p]]
        flat = cand.reshape(P, -1)
        top = np.argsort(-flat, axis=-1)[:, :K]  # [P, K]
        src_beam, tok = top // vocab, (top % vocab).astype(np.int32)
        perm = (np.arange(P)[:, None] * K + src_beam).reshape(-1)
        _reorder_cache_rows(ffd, perm)
        new_buf = buf[perm].copy()
        new_alive = np.take_along_axis(alive, src_beam, -1)
        write = new_alive.reshape(-1)
        new_buf[write, t + 1] = tok.reshape(-1)[write]
        gen_len = np.take_along_axis(gen_len, src_beam, -1) + new_alive
        if eos_id >= 0:
            new_alive &= tok != eos_id
        buf = new_buf
        scores = np.take_along_axis(flat, top, -1)
        alive = new_alive
        if eos_id >= 0 and not alive.any():
            break
    if length_penalty > 0.0:
        norm = ((5.0 + np.maximum(gen_len, 1).astype(np.float64)) / 6.0) \
            ** length_penalty
        best = np.argmax(scores / norm, axis=-1)
    else:
        best = np.argmax(scores, axis=-1)
    rows = np.arange(P) * K + best
    return buf[rows].copy(), scores[np.arange(P), best].astype(float)


def gpt_generate_scan(ffd: FFModel, prompt_ids, max_new_tokens: int,
                      temperature: float = 0.0, seed: int = 0) -> np.ndarray:
    """Whole-generation-as-one-XLA-program: a jitted lax.scan over the
    decode step with on-device greedy/temperature sampling.  No host
    round trips between tokens — the natural TPU serving shape (and
    through the axon tunnel, ~RTT x T faster than any host loop)."""
    import jax
    import jax.numpy as jnp

    prompt_ids = np.asarray(prompt_ids, np.int32)
    dims = _gpt_dims(ffd)
    max_seq = dims["max_seq"]
    batch, plen = prompt_ids.shape
    if plen < 1:
        raise ValueError("gpt_generate_scan needs a non-empty prompt")
    if batch != ffd.config.batch_size:
        raise ValueError(
            f"prompt batch {batch} != decoder batch {ffd.config.batch_size}"
        )
    total = int(min(max_seq, plen + max_new_tokens))
    prompt_pad = np.zeros((batch, total), np.int32)
    prompt_pad[:, :plen] = prompt_ids[:, :total]
    out = run_generate_scan(ffd, prompt_pad,
                            np.full(batch, plen, np.int32), temperature,
                            seed)
    out[:, :plen] = prompt_ids[:, :total]  # prompt verbatim
    return out


def run_generate_scan(ffd: FFModel, prompt_pad: np.ndarray,
                      plens: np.ndarray, temperature: float = 0.0,
                      seed: int = 0) -> np.ndarray:
    """Core scan generator over a row-padded prompt buffer.

    prompt_pad: [batch, total] int32, row i's prompt in [:plens[i]].
    Per-row prompt lengths are a traced [batch] operand, so ONE
    compiled program serves any mix of prompt lengths at a given total
    — the shape contract generation serving needs (each row prefills to
    its own boundary, then samples to `total`).  The compile cache is
    keyed by (total, temperature) and FIFO-bounded as a backstop
    against many totals."""
    import jax
    import jax.numpy as jnp

    batch, total = prompt_pad.shape
    if batch != ffd.config.batch_size:
        raise ValueError(
            f"prompt batch {batch} != decoder batch {ffd.config.batch_size}"
        )
    ffd.reset_decode_state()
    ex = ffd.executor

    cache_key = (total, float(temperature))
    fns = getattr(ffd, "_scan_gen_cache", None)
    if fns is None:
        fns = ffd._scan_gen_cache = {}
    if cache_key not in fns:

        def generate(weights, state, prompt, plen_t, key):
            def body(carry, t):
                state, tok = carry
                logits, new_state, _, _ = ex.run_forward(
                    weights, state,
                    {"input": tok[:, None],
                     "positions": jnp.full((batch, 1), t, jnp.int32)},
                    training=False, rng=None,
                )
                step = logits[:, 0]
                if temperature > 0.0:
                    nxt = jax.random.categorical(
                        jax.random.fold_in(key, t), step / temperature
                    ).astype(jnp.int32)
                else:
                    nxt = jnp.argmax(step, axis=-1).astype(jnp.int32)
                # during each row's prefill the next token is its given
                # prompt id (plen_t is per-row)
                nxt = jnp.where(t + 1 < plen_t,
                                prompt[:, (t + 1) % total], nxt)
                return (new_state, nxt), nxt

            (state, _), toks = jax.lax.scan(
                body, (state, prompt[:, 0]), jnp.arange(total - 1)
            )
            # final state is dropped: one generate call = one sequence
            return jnp.swapaxes(toks, 0, 1)  # [batch, total-1]

        while len(fns) >= 8:
            fns.pop(next(iter(fns)))
        with ex.mesh:
            fns[cache_key] = jax.jit(generate)

    key = jax.random.key(seed)
    toks = np.asarray(fns[cache_key](
        ffd._weights, ffd._state, jnp.asarray(prompt_pad),
        jnp.asarray(plens, np.int32), key))
    out = np.zeros((batch, total), np.int32)
    out[:, 0] = prompt_pad[:, 0]
    out[:, 1:] = toks
    return out


def build_paged_decode_step(ffd: FFModel):
    """ONE compiled step function for continuous batching on a paged
    decode twin (make_gpt_decoder with kv_page_size > 0):

        step(weights, state, tokens[b], positions[b], block_table)
            -> (logits [b, vocab], new_state)

    Unlike the full-generation scan (whose program is keyed by total
    length), the continuous scheduler steps every in-flight sequence by
    one token per call with per-row positions — the shapes never change,
    so this single program serves the engine's entire lifetime with
    zero recompiles.  The scheduler owns the state pytree and threads
    it through explicitly; nothing here touches ffd._state.

    Hot-path discipline (this runs once per generated token):
      * block_table/seq_lens are jit ARGUMENTS substituted into the
        attention op states inside the trace — the per-step override
        costs nothing at run time and the host never rebuilds the
        state dict;
      * the state pytree is DONATED, so each step's k/v pool scatter
        updates the buffers in place instead of copying every layer's
        pool per token (XLA honors this on TPU; on CPU it degrades to
        a copy, harmlessly)."""
    import jax
    import jax.numpy as jnp

    ex = ffd.executor

    def step(weights, state, tokens, positions, block_table):
        state = {
            op: {
                k: (block_table if k == "block_table"
                    else positions if k == "seq_lens" else v)
                for k, v in entries.items()
            }
            for op, entries in state.items()
        }
        logits, new_state, _, _ = ex.run_forward(
            weights, state,
            {"input": tokens[:, None],
             "positions": positions[:, None].astype(jnp.int32)},
            training=False, rng=None,
        )
        return logits[:, 0], new_state

    with ex.mesh:
        return jax.jit(step, donate_argnums=(1,))


def build_paged_prefill_step(ffd: FFModel, chunk: int):
    """ONE compiled [slots, C] CHUNKED-PREFILL program for the paged
    decode twin (the second step program of the continuous engine,
    built alongside build_paged_decode_step):

        prefill(weights, state, tokens[b, C], positions[b], block_table)
            -> new_state

    Feeds each row C consecutive prompt tokens starting at its own
    position (row i's token j lands at positions[i] + j), filling the
    KV pool C tokens per dispatch — a P-token prompt costs ~P/C steps
    instead of P.  Logits are not returned: prefill ignores them (the
    final prompt token runs through the decode program, whose logits
    seed sampling), and rows past their real token count just write
    overwritten-before-attended garbage (see the scheduler).

    BIT-IDENTITY DISCIPLINE: internally this is a lax.scan of the
    SEQ-1 decode graph over the chunk, not a seq-C forward.  Every op
    in the scan body has exactly the decode program's shapes, so the
    K/V bytes it writes are bit-identical to one-token-at-a-time
    prefill — XLA:CPU lowers same-shape dots identically, but NOT
    matmuls whose leading dim changed (a [b*C, e] FFN matmul is not
    rowwise-bitwise-equal to its [b, e] slice), which rules out the
    fused seq-C graph (build_paged_chunk_step) wherever the dense
    gather oracle's byte-identity guarantee must hold."""
    import jax
    import jax.numpy as jnp

    if chunk < 2:
        raise ValueError(f"chunk must be >= 2, got {chunk}")
    ex = ffd.executor
    max_seq = _gpt_dims(ffd)["max_seq"]

    def prefill(weights, state, tokens, positions, block_table):
        def body(carry, xs):
            tok, j = xs
            pos_j = (positions + j).astype(jnp.int32)
            # a row's trailing PAD tokens can run past the position
            # table (a near-max_seq prompt whose last chunk is mostly
            # padding).  Route those writes to scratch (zeroed table
            # row) and clamp the position in-range EXPLICITLY: today
            # jax's fill-mode gather turns the out-of-range block-id
            # lookup into an out-of-range scatter that XLA drops, but
            # that is a mode default (plain `arr[idx]` gathers CLAMP
            # instead), not a contract — an attention rewrite or
            # indexing-mode change must not be able to turn a pad
            # write into a clamped overwrite of the row's last real
            # block.  tests/test_serving_continuous.py pins the
            # byte-level contract either way.
            bt_j = jnp.where((pos_j < max_seq)[:, None], block_table, 0)
            pos_j = jnp.minimum(pos_j, max_seq - 1)
            st = {
                op: {
                    k: (bt_j if k == "block_table"
                        else pos_j if k == "seq_lens" else v)
                    for k, v in entries.items()
                }
                for op, entries in carry.items()
            }
            _, new_state, _, _ = ex.run_forward(
                weights, st,
                {"input": tok[:, None], "positions": pos_j[:, None]},
                training=False, rng=None,
            )
            return new_state, None

        state, _ = jax.lax.scan(
            body, state,
            (jnp.swapaxes(tokens, 0, 1),
             jnp.arange(chunk, dtype=jnp.int32)),
        )
        return state

    with ex.mesh:
        return jax.jit(prefill, donate_argnums=(1,))


def build_paged_verify_step(ffd: FFModel, chunk: int):
    """ONE compiled [slots, C] speculative-VERIFY program for the paged
    decode twin (docs/SERVING.md "Speculative decoding"):

        verify(weights, state, tokens[b, C], positions[b], counts[b],
               block_table)
            -> (logits [b, C, vocab], new_state)

    Row i feeds tokens[i, :counts[i]] at positions[i] .. positions[i] +
    counts[i] - 1 — its pending next token followed by counts[i]-1
    draft tokens — and gets the model's logits at EVERY fed position
    back, so the scheduler can accept the longest greedy-matching draft
    prefix plus the first corrected token from a single dispatch.
    Steps j >= counts[i] are routed to the scratch block (zeroed table
    row, clamped position) exactly like chunked prefill's pad tokens,
    so short rows ride a long row's round without touching their own
    pool bytes; counts is a traced argument, so ONE program serves
    every per-round draft-length mix.

    BIT-IDENTITY DISCIPLINE: same as build_paged_prefill_step — a
    lax.scan of the SEQ-1 decode graph, every op at the decode
    program's shapes, so both the K/V bytes written and the per-step
    logits are bit-identical to feeding the same tokens one decode
    step at a time.  Greedy acceptance over bit-identical logits makes
    speculative output token-identical to the plain engine BY
    CONSTRUCTION (Leviathan et al., arXiv:2211.17192, the temperature
    0 case), under both the gather and Pallas kernel formulations."""
    import jax
    import jax.numpy as jnp

    if chunk < 2:
        raise ValueError(f"chunk must be >= 2, got {chunk}")
    ex = ffd.executor
    max_seq = _gpt_dims(ffd)["max_seq"]

    def verify(weights, state, tokens, positions, counts, block_table):
        def body(carry, xs):
            tok, j = xs
            pos_j = (positions + j).astype(jnp.int32)
            live = (j < counts) & (pos_j < max_seq)
            # pad steps (j >= counts[i]) write to scratch at a clamped
            # position — same contract as prefill's trailing pads: the
            # row's real blocks must be unreachable from a pad step no
            # matter the gather/scatter out-of-range mode.
            bt_j = jnp.where(live[:, None], block_table, 0)
            pos_j = jnp.where(live, pos_j, 0)
            st = {
                op: {
                    k: (bt_j if k == "block_table"
                        else pos_j if k == "seq_lens" else v)
                    for k, v in entries.items()
                }
                for op, entries in carry.items()
            }
            logits, new_state, _, _ = ex.run_forward(
                weights, st,
                {"input": tok[:, None], "positions": pos_j[:, None]},
                training=False, rng=None,
            )
            return new_state, logits[:, 0]

        state, logits = jax.lax.scan(
            body, state,
            (jnp.swapaxes(tokens, 0, 1),
             jnp.arange(chunk, dtype=jnp.int32)),
        )
        return jnp.swapaxes(logits, 0, 1), state

    with ex.mesh:
        return jax.jit(verify, donate_argnums=(1,))


def build_paged_chunk_step(ffd: FFModel):
    """Step function for a CHUNKED paged twin built with
    make_gpt_decoder(step_tokens=C): one true seq-C forward per call,

        step(weights, state, tokens[b, C], positions[b], block_table)
            -> (logits [b, C, vocab], new_state)

    The attention paged path scatters each row's C tokens at its own
    positions and attends causally within the chunk (per-position
    gathers, ops/attention.py).  This is the TPU-native prefill shape
    — the MXU sees [b*C, e] matmuls instead of C seq-1 slivers — but
    its FFN/vocab matmuls are NOT rowwise-bitwise-equal to the seq-1
    program's, so the continuous engine's byte-identity oracle uses
    build_paged_prefill_step instead; this program is for
    throughput-first deployments and is the fused Pallas kernel's
    natural host-side twin (make_gpt_decoder(kv_kernel="pallas",
    step_tokens=C) runs the whole chunk's attention as ONE kernel
    dispatch per layer — ops/pallas/paged_attention.py)."""
    import jax
    import jax.numpy as jnp

    ex = ffd.executor

    def step(weights, state, tokens, positions, block_table):
        positions = positions.astype(jnp.int32)
        chunk = tokens.shape[1]
        pos_grid = positions[:, None] + jnp.arange(chunk, dtype=jnp.int32)
        state = {
            op: {
                k: (block_table if k == "block_table"
                    else positions if k == "seq_lens" else v)
                for k, v in entries.items()
            }
            for op, entries in state.items()
        }
        logits, new_state, _, _ = ex.run_forward(
            weights, state,
            {"input": tokens, "positions": pos_grid},
            training=False, rng=None,
        )
        return logits, new_state

    with ex.mesh:
        return jax.jit(step, donate_argnums=(1,))


def build_paged_copy_block(ffd: FFModel):
    """Compiled one-block copy-on-write for the paged pools:

        copy(state, src, dst) -> new_state

    copies physical block `src`'s page to block `dst` in EVERY layer's
    k/v pool (scalar int32 ids; state donated, so on TPU the copy is
    in-place scatter, not a pool clone).  The prefix cache's COW path
    (serving/kv_pool.py ensure_writable) runs this before a full-hit
    request's first write, so shared blocks stay immutable while the
    request gets a bit-exact private tail."""
    import jax
    import jax.numpy as jnp

    ex = ffd.executor

    def copy(state, src, dst):
        return {
            op: {
                k: (v.at[dst].set(v[src])
                    if k in ("k_cache", "v_cache") else v)
                for k, v in entries.items()
            }
            for op, entries in state.items()
        }

    with ex.mesh:
        return jax.jit(copy, donate_argnums=(0,))
