"""KV-cache incremental decoding (TPU-native serving machinery).

The reference has no incremental decoder at all — its legacy nmt/
re-runs the full graph per emitted token and triton/ is an incomplete
prototype.  Here decoding is a first-class graph mode: attention ops
built with decode_max_seq=N carry fixed-shape [b, N, h, d] k/v caches
plus a position counter in the op-state pytree (the same functional
state channel BatchNorm running stats use), so one decode step is a
seq-1 forward that appends to the caches — O(T) generation instead of
the O(T^2) re-forward loop of models.transformer.gpt_generate.

Two drivers:
  * gpt_generate_cached — host loop over FFModel.decode_step (one
    device round trip per token; simple, streams tokens);
  * gpt_generate_scan — the WHOLE generation (prefill + sample loop)
    as ONE jitted lax.scan program: zero host round trips until the
    final token buffer lands.  Through a high-latency link (the axon
    tunnel's ~80 ms RTT) this is the difference between RTT x T and
    RTT x 1.

`make_gpt_decoder` builds the seq-1 decode twin of a trained
models.transformer.build_gpt model by introspecting its graph and
copies the weights across (shapes are seq-independent; the position
table is shared via build_gpt's max_positions).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .fftype import LossType, OperatorType
from .model import FFModel
from .optimizer import SGDOptimizer


def _gpt_dims(ff: FFModel) -> Dict[str, int]:
    """Read the build_gpt hyperparameters back off a built graph."""
    by_name = {op.name: op for op in ff.layers.topo_order()}
    attn = [
        op for op in ff.layers.topo_order()
        if op.op_type == OperatorType.MULTIHEAD_ATTENTION
    ]
    if (not attn or "tok_embed" not in by_name or "pos_embed" not in by_name
            or "ffn1_0" not in by_name):
        raise ValueError(
            "make_gpt_decoder expects a models.transformer.build_gpt "
            "graph (tok_embed/pos_embed/attn_i/ffn1_i naming)"
        )
    p = attn[0].params
    tok = by_name["tok_embed"].params
    pos = by_name["pos_embed"].params
    ffn1 = by_name["ffn1_0"].params
    return {
        "num_layers": len(attn),
        "hidden_size": p.embed_dim,
        "num_heads": p.num_heads,
        "dropout": p.dropout,
        "vocab_size": tok.num_entries,
        "max_seq": pos.num_entries,
        "intermediate_size": ffn1.out_channels,
    }


def make_gpt_decoder(ff_train: FFModel, batch_size: Optional[int] = None,
                     devices=None) -> FFModel:
    """Build + compile the KV-cache decode twin of a trained GPT and
    transfer its weights.  The decode graph is seq-1 with
    decode_max_seq = the trained model's position-table size."""
    from .config import FFConfig
    from .models.transformer import build_gpt

    dims = _gpt_dims(ff_train)
    b = batch_size or ff_train.config.batch_size
    cfg = FFConfig(
        batch_size=b, num_devices=1,
        compute_dtype=ff_train.config.compute_dtype,
        only_data_parallel=True,
    )
    ffd = FFModel(cfg)
    build_gpt(
        ffd, batch_size=b, seq_length=1,
        hidden_size=dims["hidden_size"], num_layers=dims["num_layers"],
        num_heads=dims["num_heads"],
        intermediate_size=dims["intermediate_size"],
        vocab_size=dims["vocab_size"], dropout=0.0,
        max_positions=dims["max_seq"], decode_max_seq=dims["max_seq"],
    )
    ffd.compile(
        optimizer=SGDOptimizer(lr=0.0),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        devices=devices,
    )
    # weight transfer by (op, spec) name — all shapes are
    # seq-independent, so the trained pytree drops straight in
    missing = []
    new_w = {}
    for op_name, entries in ffd._weights.items():
        src = ff_train._weights.get(op_name)
        new_entries = {}
        for k, v in entries.items():
            if src is None or k not in src:
                missing.append(f"{op_name}.{k}")
                new_entries[k] = v
                continue
            sv = src[k]
            if tuple(sv.shape) != tuple(v.shape):
                raise ValueError(
                    f"decode weight {op_name}.{k}: trained shape "
                    f"{tuple(sv.shape)} != decode shape {tuple(v.shape)}"
                )
            new_entries[k] = sv if sv.dtype == v.dtype else sv.astype(v.dtype)
        new_w[op_name] = new_entries
    if missing:
        raise ValueError(f"decode graph weights missing in trained "
                         f"model: {missing}")
    ffd._weights = new_w
    return ffd


def gpt_generate_cached(ffd: FFModel, prompt_ids, max_new_tokens: int,
                        temperature: float = 0.0, seed: int = 0,
                        top_k: int = 0, top_p: float = 0.0) -> np.ndarray:
    """Host-loop KV-cache generation on a make_gpt_decoder model:
    prefill feeds prompt tokens one per step (caches fill as a side
    effect), then each sampled token feeds back.  Exactly matches
    gpt_generate's outputs at temperature 0 (same model, same math,
    one attention row at a time)."""
    from .models.transformer import sample_next, validate_sampling

    validate_sampling(top_k, top_p)
    prompt_ids = np.asarray(prompt_ids, np.int32)
    dims = _gpt_dims(ffd)
    max_seq = dims["max_seq"]
    batch, plen = prompt_ids.shape
    if plen < 1:
        raise ValueError("gpt_generate_cached needs a non-empty prompt")
    if batch != ffd.config.batch_size:
        raise ValueError(
            f"prompt batch {batch} != decoder batch {ffd.config.batch_size}"
        )
    total = min(max_seq, plen + max_new_tokens)
    ffd.reset_decode_state()
    buf = np.zeros((batch, total), np.int32)
    buf[:, :plen] = prompt_ids[:, :total]
    rng = np.random.RandomState(seed)
    # the token at total-1 is the last ever written, so its decode step
    # (whose logits nothing consumes) is never run
    for t in range(total - 1):
        logits = np.asarray(
            ffd.decode_step({
                "input": buf[:, t:t + 1],
                "positions": np.full((batch, 1), t, np.int32),
            }),
            np.float32,
        )
        if t + 1 < plen:
            continue  # prefill: the next token is given
        buf[:, t + 1] = sample_next(logits[:, 0], temperature, rng,
                                    top_k, top_p)
    return buf


def gpt_generate_scan(ffd: FFModel, prompt_ids, max_new_tokens: int,
                      temperature: float = 0.0, seed: int = 0) -> np.ndarray:
    """Whole-generation-as-one-XLA-program: a jitted lax.scan over the
    decode step with on-device greedy/temperature sampling.  No host
    round trips between tokens — the natural TPU serving shape (and
    through the axon tunnel, ~RTT x T faster than any host loop)."""
    import jax
    import jax.numpy as jnp

    prompt_ids = np.asarray(prompt_ids, np.int32)
    dims = _gpt_dims(ffd)
    max_seq = dims["max_seq"]
    batch, plen = prompt_ids.shape
    if plen < 1:
        raise ValueError("gpt_generate_scan needs a non-empty prompt")
    if batch != ffd.config.batch_size:
        raise ValueError(
            f"prompt batch {batch} != decoder batch {ffd.config.batch_size}"
        )
    total = int(min(max_seq, plen + max_new_tokens))
    ffd.reset_decode_state()
    ex = ffd.executor

    prompt_pad = np.zeros((batch, total), np.int32)
    prompt_pad[:, :plen] = prompt_ids[:, :total]

    # prompt length is a traced operand, so one compiled program serves
    # every plen at a given total — a serving loop over varying prompts
    # does not recompile or leak compilations (ADVICE r4).  The cache is
    # additionally FIFO-bounded as a backstop against many totals.
    cache_key = (total, float(temperature))
    fns = getattr(ffd, "_scan_gen_cache", None)
    if fns is None:
        fns = ffd._scan_gen_cache = {}
    if cache_key not in fns:

        def generate(weights, state, prompt, plen_t, key):
            def body(carry, t):
                state, tok = carry
                logits, new_state, _, _ = ex.run_forward(
                    weights, state,
                    {"input": tok[:, None],
                     "positions": jnp.full((batch, 1), t, jnp.int32)},
                    training=False, rng=None,
                )
                step = logits[:, 0]
                if temperature > 0.0:
                    nxt = jax.random.categorical(
                        jax.random.fold_in(key, t), step / temperature
                    ).astype(jnp.int32)
                else:
                    nxt = jnp.argmax(step, axis=-1).astype(jnp.int32)
                # during prefill the next token is the given prompt id
                nxt = jnp.where(t + 1 < plen_t,
                                prompt[:, (t + 1) % total], nxt)
                return (new_state, nxt), nxt

            (state, _), toks = jax.lax.scan(
                body, (state, prompt[:, 0]), jnp.arange(total - 1)
            )
            # final state is dropped: one generate call = one sequence
            return jnp.swapaxes(toks, 0, 1)  # [batch, total-1]

        while len(fns) >= 8:
            fns.pop(next(iter(fns)))
        with ex.mesh:
            fns[cache_key] = jax.jit(generate)

    key = jax.random.key(seed)
    toks = np.asarray(fns[cache_key](
        ffd._weights, ffd._state, jnp.asarray(prompt_pad),
        jnp.int32(plen), key))
    out = np.zeros((batch, total), np.int32)
    out[:, 0] = prompt_pad[:, 0]
    out[:, 1:] = toks
    out[:, :plen] = prompt_ids[:, :total]  # prompt verbatim
    return out
