"""FFModel — the model-construction and training API.

TPU-native re-design of the reference's FFModel
(/root/reference/include/flexflow/model.h:326-956,
src/runtime/model.cc): the same ~50 layer-construction methods
(`dense`, `conv2d`, `multihead_attention`, `moe`, `embedding`, …),
`compile` (which here runs the strategy search and builds the jitted
SPMD step instead of launching GRAPH_OPTIMIZE on GPU0), and the
`fit`/`forward`/`backward`/`update`/`zero_gradients` training surface.

Execution differences from the reference, by design (SURVEY §7):
  * compile produces ONE jitted train-step over a `jax.sharding.Mesh`
    (Legion task graph + tracing + mapper + NCCL all collapse into it);
  * backward is `jax.grad` (no per-op backward launches);
  * `update` is a functional sharded optimizer step (gradient psum is
    emitted by SPMD, replacing optimizer_kernel.cu's ncclAllReduce).
"""
from __future__ import annotations

import logging
import math
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .config import FFConfig, FFIterationConfig
from .executor import GraphExecutor
from .fftype import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OpBinary,
    OperatorType,
    OpUnary,
)
from .initializer import Initializer
from .loss import Loss
from .metrics import Metrics, PerfMetrics
from .ops.attention import MultiHeadAttention, MultiHeadAttentionParams
from .ops.dense import (
    BatchMatmul,
    BatchMatmulParams,
    Conv2D,
    Conv2DParams,
    Embedding,
    EmbeddingParams,
    Linear,
    LinearParams,
    Pool2D,
    Pool2DParams,
)
from .ops.element import (
    Cast,
    CastParams,
    Dropout,
    DropoutParams,
    ElementBinary,
    ElementBinaryParams,
    ElementUnary,
    ElementUnaryParams,
)
from .ops.moe import (
    Aggregate,
    AggregateParams,
    AggregateSpec,
    Cache,
    CacheParams,
    GroupBy,
    GroupByParams,
    TopK,
    TopKParams,
)
from .ops.norm import (
    BatchNorm,
    BatchNormParams,
    LayerNorm,
    LayerNormParams,
    Softmax,
    SoftmaxParams,
)
from .ops.op import Op, ShapeError, ShardConfig
from .ops.shape import (
    Concat,
    ConcatParams,
    Flat,
    Gather,
    GatherParams,
    Mean,
    Pad,
    PadParams,
    Reduce,
    ReduceParams,
    Reshape,
    ReshapeParams,
    Reverse,
    ReverseParams,
    Split,
    SplitParams,
    Transpose,
    TransposeParams,
)
from .ops.sources import InputOp, SourceParams
from .optimizer import AdamOptimizer, Optimizer, SGDOptimizer
from .parallel.machine import make_mesh
from .pcg.graph import Graph
from .strategy import (
    Strategy,
    apply_strategy,
    assign_views,
    data_parallel_strategy,
)
from .tensor import ParallelTensor, ParallelTensorShape

_log = logging.getLogger("flexflow_tpu.model")


def device_put_like(saved, current):
    """device_put each saved leaf onto the matching current leaf's
    sharding — the carry idiom shared by recompile and the resilience
    supervisor's rollback."""
    return jax.tree.map(
        lambda v, cur: (
            jax.device_put(v, cur.sharding)
            if getattr(cur, "sharding", None) is not None
            else v
        ),
        saved, current,
    )


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        # run telemetry (obs/): NULL-tracer + in-memory registry unless
        # FFConfig.trace_dir/telemetry turns recording on
        from .obs import RunTelemetry

        self.telemetry = RunTelemetry.from_config(self.config)
        self.layers = Graph()  # frontend (degree-1) graph
        self.operators: Optional[Graph] = None  # compiled strategy graph
        self.strategy: Optional[Strategy] = None
        self.mesh = None
        self.executor: Optional[GraphExecutor] = None
        self.optimizer: Optional[Optimizer] = None
        self.loss: Optional[Loss] = None
        self.metrics: Optional[Metrics] = None
        self.iter_config = FFIterationConfig()
        self._weights = None
        self._opt_state = None
        self._state = None
        self._step_fn = None
        self._step_cache: Dict[int, tuple] = {}
        self._eval_fn = None
        self._rng = None
        self._label_replication = 1
        self._name_counts: Dict[str, int] = {}
        self._used_names: set = set()
        self._fwd_fn = None
        self._stop_training = False  # set by EarlyStopping-style callbacks
        self._cache_ops: List[Op] = []
        self._compiled_cache: Dict[str, Op] = {}
        self._pending_taps = None  # one-step-late cache taps

    # ------------------------------------------------------------------
    # tensor / naming helpers
    # ------------------------------------------------------------------
    def _name(self, base: str, name: Optional[str]) -> str:
        if name:
            if name in self._used_names:
                raise ValueError(f"duplicate layer name: {name!r}")
            self._used_names.add(name)
            return name
        while True:
            n = self._name_counts.get(base, 0)
            self._name_counts[base] = n + 1
            candidate = f"{base}_{n}"
            if candidate not in self._used_names:
                self._used_names.add(candidate)
                return candidate

    def create_tensor(
        self,
        dims: Sequence[int],
        dtype: Union[DataType, str] = DataType.FLOAT,
        name: Optional[str] = None,
        create_grad: bool = True,
    ) -> ParallelTensor:
        shape = ParallelTensorShape.make(dims, DataType.from_any(
            dtype.value if isinstance(dtype, DataType) else dtype))
        op = InputOp(SourceParams(shape), [], name=self._name("input", name))
        self.layers.add_op(op)
        op.outputs[0].create_gradients = create_grad
        return op.outputs[0]

    def _add(self, op: Op):
        self.layers.add_op(op)
        if len(op.outputs) == 1:
            return op.outputs[0]
        return tuple(op.outputs)

    # ------------------------------------------------------------------
    # layer API (reference model.h:326-712)
    # ------------------------------------------------------------------
    def dense(
        self,
        input: ParallelTensor,
        out_dim: int,
        activation: ActiMode = ActiMode.NONE,
        use_bias: bool = True,
        dtype: Union[DataType, str] = DataType.FLOAT,
        kernel_initializer: Optional[Initializer] = None,
        bias_initializer: Optional[Initializer] = None,
        name: Optional[str] = None,
    ) -> ParallelTensor:
        p = LinearParams(out_dim, use_bias, activation, DataType.from_any(
            dtype.value if isinstance(dtype, DataType) else dtype))
        op = Linear(p, [input], name=self._name("dense", name))
        if kernel_initializer is not None:
            op.weight_specs[0] = op.weight_specs[0].__class__(
                "kernel", op.weight_specs[0].shape, kernel_initializer
            )
        if use_bias and bias_initializer is not None:
            op.weight_specs[1] = op.weight_specs[1].__class__(
                "bias", op.weight_specs[1].shape, bias_initializer
            )
        return self._add(op)

    def conv2d(
        self,
        input: ParallelTensor,
        out_channels: int,
        kernel_h: int,
        kernel_w: int,
        stride_h: int = 1,
        stride_w: int = 1,
        padding_h: int = 0,
        padding_w: int = 0,
        activation: ActiMode = ActiMode.NONE,
        groups: int = 1,
        use_bias: bool = True,
        name: Optional[str] = None,
    ) -> ParallelTensor:
        p = Conv2DParams(
            out_channels,
            (kernel_h, kernel_w),
            (stride_h, stride_w),
            (padding_h, padding_w),
            groups,
            use_bias,
            activation,
        )
        return self._add(Conv2D(p, [input], name=self._name("conv2d", name)))

    def pool2d(
        self,
        input: ParallelTensor,
        kernel_h: int,
        kernel_w: int,
        stride_h: int,
        stride_w: int,
        padding_h: int = 0,
        padding_w: int = 0,
        pool_type: str = "max",
        activation: ActiMode = ActiMode.NONE,
        name: Optional[str] = None,
    ) -> ParallelTensor:
        p = Pool2DParams(
            (kernel_h, kernel_w),
            (stride_h, stride_w),
            (padding_h, padding_w),
            pool_type,
            activation,
        )
        return self._add(Pool2D(p, [input], name=self._name("pool2d", name)))

    def embedding(
        self,
        input: ParallelTensor,
        num_entries: int,
        out_dim: int,
        aggr: AggrMode = AggrMode.NONE,
        dtype: Union[DataType, str] = DataType.FLOAT,
        kernel_initializer: Optional[Initializer] = None,
        name: Optional[str] = None,
    ) -> ParallelTensor:
        p = EmbeddingParams(num_entries, out_dim, aggr, DataType.from_any(
            dtype.value if isinstance(dtype, DataType) else dtype))
        op = Embedding(p, [input], name=self._name("embedding", name))
        if kernel_initializer is not None:
            op.weight_specs[0] = op.weight_specs[0].__class__(
                "weight", op.weight_specs[0].shape, kernel_initializer
            )
        return self._add(op)

    def multihead_attention(
        self,
        query: ParallelTensor,
        key: ParallelTensor,
        value: ParallelTensor,
        embed_dim: int,
        num_heads: int,
        kdim: int = 0,
        vdim: int = 0,
        dropout: float = 0.0,
        bias: bool = False,
        add_bias_kv: bool = False,
        add_zero_attn: bool = False,
        causal: bool = False,
        name: Optional[str] = None,
        decode_max_seq: int = 0,
        kv_page_size: int = 0,
        kv_num_blocks: int = 0,
        kv_kernel: str = "gather",
    ) -> ParallelTensor:
        p = MultiHeadAttentionParams(
            embed_dim, num_heads, kdim, vdim, dropout, bias, add_bias_kv,
            add_zero_attn, causal,
        )
        return self._add(
            MultiHeadAttention(p, [query, key, value],
                               name=self._name("attention", name),
                               decode_max_seq=decode_max_seq,
                               kv_page_size=kv_page_size,
                               kv_num_blocks=kv_num_blocks,
                               kv_kernel=kv_kernel)
        )

    def batch_matmul(
        self,
        a: ParallelTensor,
        b: ParallelTensor,
        a_seq_length_dim: int = -1,
        b_seq_length_dim: int = -1,
        name: Optional[str] = None,
    ) -> ParallelTensor:
        p = BatchMatmulParams(a_seq_length_dim, b_seq_length_dim)
        return self._add(BatchMatmul(p, [a, b], name=self._name("batch_matmul", name)))

    # -- elementwise binary ---------------------------------------------
    def _binary(self, kind: OpBinary, x, y, inplace_a=False, name=None):
        p = ElementBinaryParams(kind, inplace_a)
        return self._add(
            ElementBinary(p, [x, y], name=self._name(kind.value, name))
        )

    def add(self, x, y, inplace_a=False, name=None):
        return self._binary(OpBinary.ADD, x, y, inplace_a, name)

    def subtract(self, x, y, inplace_a=False, name=None):
        return self._binary(OpBinary.SUB, x, y, inplace_a, name)

    def multiply(self, x, y, inplace_a=False, name=None):
        return self._binary(OpBinary.MUL, x, y, inplace_a, name)

    def divide(self, x, y, inplace_a=False, name=None):
        return self._binary(OpBinary.DIV, x, y, inplace_a, name)

    def max(self, x, y, name=None):
        return self._binary(OpBinary.MAX, x, y, False, name)

    def min(self, x, y, name=None):
        return self._binary(OpBinary.MIN, x, y, False, name)

    # -- elementwise unary ----------------------------------------------
    def _unary(self, kind: OpUnary, x, scalar=0.0, inplace=False, name=None):
        p = ElementUnaryParams(kind, inplace, scalar)
        return self._add(ElementUnary(p, [x], name=self._name(kind.value, name)))

    def exp(self, x, name=None):
        return self._unary(OpUnary.EXP, x, name=name)

    def log(self, x, name=None):
        return self._unary(OpUnary.LOG, x, name=name)

    def sin(self, x, name=None):
        return self._unary(OpUnary.SIN, x, name=name)

    def cos(self, x, name=None):
        return self._unary(OpUnary.COS, x, name=name)

    def relu(self, x, inplace=True, name=None):
        return self._unary(OpUnary.RELU, x, inplace=inplace, name=name)

    def gelu(self, x, name=None):
        return self._unary(OpUnary.GELU, x, name=name)

    def sigmoid(self, x, name=None):
        return self._unary(OpUnary.SIGMOID, x, name=name)

    def tanh(self, x, name=None):
        return self._unary(OpUnary.TANH, x, name=name)

    def elu(self, x, inplace=True, name=None):
        return self._unary(OpUnary.ELU, x, inplace=inplace, name=name)

    def identity(self, x, name=None):
        return self._unary(OpUnary.IDENTITY, x, name=name)

    def rsqrt(self, x, name=None):
        return self._unary(OpUnary.RSQRT, x, name=name)

    def sqrt(self, x, name=None):
        return self._unary(OpUnary.SQRT, x, name=name)

    def erf(self, x, name=None):
        return self._unary(OpUnary.ERF, x, name=name)

    def floor(self, x, name=None):
        return self._unary(OpUnary.FLOOR, x, name=name)

    def pow(self, x, exponent: float, name=None):
        return self._unary(OpUnary.POW, x, scalar=exponent, name=name)

    def scalar_multiply(self, x, scalar: float, inplace=True, name=None):
        return self._unary(OpUnary.SCALAR_MULTIPLY, x, scalar=scalar, name=name)

    def scalar_add(self, x, scalar: float, inplace=True, name=None):
        return self._unary(OpUnary.SCALAR_ADD, x, scalar=scalar, name=name)

    def scalar_sub(self, x, scalar: float, inplace=True, name=None):
        return self._unary(OpUnary.SCALAR_SUB, x, scalar=scalar, name=name)

    def scalar_true_divide(self, x, scalar: float, inplace=True, name=None):
        return self._unary(OpUnary.SCALAR_TRUE_DIV, x, scalar=scalar, name=name)

    # -- norm / softmax --------------------------------------------------
    def softmax(self, input, axis: int = -1, name=None):
        return self._add(
            Softmax(SoftmaxParams(axis), [input], name=self._name("softmax", name))
        )

    def layer_norm(
        self,
        input,
        axes: Sequence[int],
        elementwise_affine: bool = True,
        eps: float = 1e-5,
        name=None,
    ):
        p = LayerNormParams(tuple(axes), elementwise_affine, eps)
        return self._add(LayerNorm(p, [input], name=self._name("layer_norm", name)))

    def batch_norm(self, input, relu: bool = True, eps: float = 1e-5,
                   momentum: float = 0.9, name=None):
        p = BatchNormParams(relu, float(eps), float(momentum))
        return self._add(BatchNorm(p, [input], name=self._name("batch_norm", name)))

    # -- shape ops -------------------------------------------------------
    def concat(self, tensors: Sequence[ParallelTensor], axis: int, name=None):
        return self._add(
            Concat(ConcatParams(axis), list(tensors), name=self._name("concat", name))
        )

    def split(self, input, sizes: Union[int, Sequence[int]], axis: int, name=None):
        if isinstance(sizes, int):
            dim_size = input.shape.logical_shape[axis]
            sizes = [dim_size // sizes] * sizes
        p = SplitParams(tuple(sizes), axis)
        return self._add(Split(p, [input], name=self._name("split", name)))

    def flat(self, input, name=None):
        return self._add(Flat(None, [input], name=self._name("flat", name)))

    def weight_tensor(self, array, trainable: bool = True, name=None):
        """A standalone parameter as a tensor (reference OP_WEIGHT /
        torch AttributeNode): initialized from `array`, trainable by
        default."""
        from .initializer import ArrayInitializer
        from .ops.op import WeightSpec
        from .ops.sources import SourceParams, WeightOp

        arr = np.asarray(array)
        shape = ParallelTensorShape.make(
            arr.shape, DataType.from_any(str(arr.dtype))
        )
        op = WeightOp(SourceParams(shape, "weight", trainable), [],
                      name=self._name("weight", name))
        op.weight_specs = [
            WeightSpec("value", shape, ArrayInitializer(arr))
        ]
        out = self._add(op)
        out.create_gradients = trainable
        return out

    def expand(self, input, sizes: Sequence[int], name=None):
        """Broadcast size-1 dims (torch Tensor.expand)."""
        from .ops.shape import Expand, ExpandParams

        p = ExpandParams(tuple(int(s) for s in sizes))
        return self._add(Expand(p, [input], name=self._name("expand", name)))

    def reshape(self, input, shape: Sequence[int], name=None):
        p = ReshapeParams(tuple(shape))
        return self._add(Reshape(p, [input], name=self._name("reshape", name)))

    def transpose(self, input, perm: Sequence[int], name=None):
        p = TransposeParams(tuple(perm))
        return self._add(Transpose(p, [input], name=self._name("transpose", name)))

    def reverse(self, input, axis: int, name=None):
        return self._add(
            Reverse(ReverseParams(axis), [input], name=self._name("reverse", name))
        )

    def pad(self, input, pads: Sequence[Sequence[int]], value: float = 0.0,
            name=None):
        """Constant-pad: pads is ((before, after), ...) per logical dim."""
        p = PadParams(tuple((int(b), int(a)) for b, a in pads), float(value))
        return self._add(Pad(p, [input], name=self._name("pad", name)))

    def reduce_sum(self, input, axes: Sequence[int], keepdims: bool = False, name=None):
        p = ReduceParams(tuple(axes), keepdims, "sum")
        return self._add(Reduce(p, [input], name=self._name("reduce_sum", name)))

    def mean(self, input, axes: Sequence[int], keepdims: bool = False, name=None):
        p = ReduceParams(tuple(axes), keepdims, "mean")
        return self._add(Mean(p, [input], name=self._name("mean", name)))

    def cast(self, input, dtype: Union[DataType, str], name=None):
        p = CastParams(DataType.from_any(
            dtype.value if isinstance(dtype, DataType) else dtype))
        return self._add(Cast(p, [input], name=self._name("cast", name)))

    def dropout(self, input, rate: float, seed: int = 0, name=None):
        p = DropoutParams(rate, seed)
        return self._add(Dropout(p, [input], name=self._name("dropout", name)))

    def gather(self, input, index, axis: int = 0, name=None):
        p = GatherParams(axis)
        return self._add(Gather(p, [input, index], name=self._name("gather", name)))

    # -- MoE -------------------------------------------------------------
    def top_k(self, input, k: int, sorted: bool = False, name=None):
        return self._add(TopK(TopKParams(k, sorted), [input], name=self._name("topk", name)))

    def group_by(self, data, assign, n: int, alpha: float, name=None):
        return self._add(
            GroupBy(GroupByParams(n, alpha), [data, assign], name=self._name("group_by", name))
        )

    def aggregate(self, gate_scores, assign, gate_full, expert_out, n: int,
                  lambda_bal: float = 0.0, name=None):
        p = AggregateParams(n, lambda_bal)
        return self._add(
            Aggregate(p, [gate_scores, assign, gate_full, expert_out],
                      name=self._name("aggregate", name))
        )

    def aggregate_spec(self, gate_scores, assign, gate_full, expert_out, n: int,
                       lambda_bal: float = 0.0, name=None):
        p = AggregateParams(n, lambda_bal)
        op = AggregateSpec(p, [gate_scores, assign, gate_full, expert_out],
                           name=self._name("aggregate_spec", name))
        out = self._add(op)
        self._label_replication = op.inputs[1].shape.logical_shape[-1]
        return out

    def cache(self, input, num_batches: int, *, score_fn=None, name=None):
        """Identity passthrough accumulating a host-side staleness score
        (reference src/ops/cache.cc, score_f moe.cc:40-63).  score_fn, if
        given, is called with this FFModel after every fit batch; its
        float feeds op.trigger for recompile_on_condition."""
        if score_fn is not None and not callable(score_fn):
            raise TypeError(f"score_fn must be callable, got {type(score_fn)}")
        op = Cache(CacheParams(num_batches), [input],
                   name=self._name("cache", name))
        op.score_fn = score_fn
        self._add(op)
        return op.outputs[0]

    def moe(
        self,
        input: ParallelTensor,
        num_exp: int,
        num_select: int,
        expert_hidden_size: int,
        alpha: float = 2.0,
        lambda_bal: float = 0.0,
        name=None,
    ) -> ParallelTensor:
        """MoE composite (reference src/ops/moe.cc:20-44): gate -> topk ->
        group_by -> per-expert FFN -> aggregate.  The expert FFN here is a
        batched dense over the stacked expert dim, so expert parallelism
        is sharding that dim (ShardConfig.expert).

        Rank-3 inputs [b, s, h] are flattened to [b*s, h] tokens around
        the dispatch and restored afterwards (the reference's group_by
        is 2-D only; its encoder path moe.cc:100-130 is dead code in its
        own example main)."""
        orig_shape = input.shape.logical_shape
        if len(orig_shape) == 3:
            b, s, h = orig_shape
            input = self.reshape(input, [b * s, h])
        gate = self.dense(input, num_exp, ActiMode.NONE)
        gate_sm = self.softmax(gate)
        topk_out = self.top_k(gate_sm, num_select)
        values, assign = topk_out
        grouped = self.group_by(input, assign, num_exp, alpha)
        # per-expert FFN: [n, cap, d] -> [n, cap, hidden]
        hidden = self.experts_dense(grouped, expert_hidden_size, activation=ActiMode.RELU)
        out = self.aggregate(values, assign, gate_sm, hidden, num_exp, lambda_bal,
                             name=name)
        if len(orig_shape) == 3:
            out = self.reshape(out, [orig_shape[0], orig_shape[1],
                                     expert_hidden_size])
        return out

    def lstm(self, input, hidden_size: int, return_sequences: bool = True,
             name=None):
        """Fused lax.scan LSTM (reference legacy nmt/ LSTM rebuilt as a
        first-class op, ops/recurrent.py)."""
        from .ops.recurrent import LSTM, LSTMParams

        p = LSTMParams(hidden_size, return_sequences)
        return self._add(LSTM(p, [input], name=self._name("lstm", name)))

    def experts_dense(self, grouped, out_dim: int, activation=ActiMode.NONE,
                      use_bias: bool = True, name=None):
        """Batched per-expert dense over stacked [n, cap, d] expert inputs."""
        from .ops.experts import ExpertsDense, ExpertsDenseParams

        p = ExpertsDenseParams(out_dim, use_bias, activation)
        return self._add(
            ExpertsDense(p, [grouped], name=self._name("experts_dense", name))
        )

    # ------------------------------------------------------------------
    # compile (reference FFModel::compile model.cc:2487-3167)
    # ------------------------------------------------------------------
    def compile(
        self,
        optimizer: Optional[Optimizer] = None,
        loss_type: Union[LossType, str] = LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics: Sequence[Union[MetricsType, str]] = (MetricsType.ACCURACY,),
        comp_mode: CompMode = CompMode.TRAINING,
        strategy: Optional[Strategy] = None,
        devices: Optional[Sequence] = None,
        seed: Optional[int] = None,
    ):
        tel = self.telemetry
        t0 = time.perf_counter()
        with tel.tracer.span("compile", cat="compile"):
            result = self._compile_inner(
                optimizer=optimizer, loss_type=loss_type, metrics=metrics,
                comp_mode=comp_mode, strategy=strategy, devices=devices,
                seed=seed,
            )
        tel.metrics.gauge("compile/total_ms").set(
            (time.perf_counter() - t0) * 1e3
        )
        return result

    def _stamp_catalog(self, strategy: Strategy) -> None:
        """Pin the catalog identity a FRESHLY searched trace used, so
        replay on another host can't silently resolve different rules
        (rewrite.rules_for_replay checks the hash).  Only ever called
        on this process's own search results — stamping an imported or
        store-restored trace with the LOCAL catalog's hash would
        fabricate provenance and defeat the replay check."""
        if strategy.catalog is not None or not any(
            str(n).startswith("taso_rule_") for n, _ in strategy.rewrites
        ):
            return
        from .pcg.rewrite import catalog_fingerprint, catalog_for_config

        path = catalog_for_config(self.config)
        if path:
            strategy.catalog = catalog_fingerprint(path)

    def _compile_inner(
        self,
        optimizer: Optional[Optimizer] = None,
        loss_type: Union[LossType, str] = LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics: Sequence[Union[MetricsType, str]] = (MetricsType.ACCURACY,),
        comp_mode: CompMode = CompMode.TRAINING,
        strategy: Optional[Strategy] = None,
        devices: Optional[Sequence] = None,
        seed: Optional[int] = None,
    ):
        cfg = self.config
        tel = self.telemetry
        self._compile_args = {
            "loss_type": loss_type,
            "metrics": tuple(metrics),
            "comp_mode": comp_mode,
            "devices": list(devices) if devices is not None else None,
        }
        self.optimizer = optimizer or SGDOptimizer(
            lr=cfg.learning_rate, weight_decay=cfg.weight_decay
        )
        # Reference convention (loss_functions.cu): a model ending in
        # Softmax feeds probabilities to the loss, not logits.
        sink_is_softmax = self.layers.sink_op().op_type == OperatorType.SOFTMAX
        self.loss = Loss(loss_type, from_logits=not sink_is_softmax)
        self.metrics = Metrics(self.loss.loss_type, metrics)
        self._fwd_fn = None

        num_devices = len(devices) if devices is not None else cfg.resolve_num_devices()

        # compiled-step persistence half of the artifact store: point
        # XLA's cache under the store root BEFORE anything jit-executes
        # so a restarted replica re-loads executables instead of
        # recompiling (store/, docs/STORE.md)
        if cfg.compilation_cache:
            from .store import enable_compilation_cache

            enable_compilation_cache(cfg)

        if strategy is None and cfg.import_strategy_file:
            strategy = Strategy.load(cfg.import_strategy_file)
        if strategy is None:
            if cfg.search_budget > 0 and not cfg.only_data_parallel:
                # reference: Unity graph_optimize is the default search
                # path (GRAPH_OPTIMIZE_TASK_ID, graph.cc:2046); MCMC is
                # the legacy SysML'19 path (model.cc:3285).  The
                # strategy store wraps either: a warm entry for (graph,
                # mesh, simulator version) skips the search entirely
                # (search_stats records store_hit)
                from .pcg.search import mcmc_search, unity_search
                from .store import cached_search

                def _run_search():
                    if cfg.search_algo == "mcmc":
                        s = mcmc_search(self, num_devices)
                    else:
                        s = unity_search(self, num_devices)
                    # stamp the catalog identity BEFORE the store
                    # publish so restored entries carry the provenance
                    # their replay check needs (see _stamp_catalog)
                    self._stamp_catalog(s)
                    return s

                t_search = time.perf_counter()
                with tel.tracer.span("search", cat="search",
                                     algo=cfg.search_algo,
                                     devices=num_devices):
                    strategy = cached_search(self, num_devices, _run_search)
                tel.metrics.gauge("compile/search_ms").set(
                    (time.perf_counter() - t_search) * 1e3
                )
            else:
                strategy = data_parallel_strategy(num_devices)
        self.strategy = strategy
        if cfg.export_strategy_file:
            strategy.save(cfg.export_strategy_file)

        # replay the strategy's graph-rewrite trace (reference: the
        # winning GraphXfer rewrites applied by graph_optimize,
        # substitution.cc:1898-1945), then apply + cancel redundant
        # parallel-op boundaries
        compiled_frontend = self.layers
        if strategy.rewrites:
            from .pcg.rewrite import apply_rewrites, rules_for_replay

            compiled_frontend = apply_rewrites(
                compiled_frontend, strategy.rewrites, rules_for_replay(cfg, strategy)
            )
        if cfg.perform_fusion:
            # reference --fusion (apply_fusion model.cc:2495): fold
            # trailing activations into their producers, skipping
            # anything the strategy names
            from .pcg.rewrite import fuse_activations

            protected = set(strategy.edge_ops) | set(strategy.shard_configs)
            compiled_frontend = fuse_activations(compiled_frontend, protected)
        self._compiled_frontend = compiled_frontend
        from .pcg.rewrite import cancel_all_inverse_parallel_ops

        self.operators = cancel_all_inverse_parallel_ops(
            apply_strategy(compiled_frontend, strategy)
        )
        # multi-slice execution (topology/, docs/TOPOLOGY.md): lower the
        # strategy's placement (which mesh axis spans the DCN boundary)
        # to a two-level execution mesh — a leading slice dim plus the
        # placement axis's intra-slice remainder — so the hierarchical
        # grad-reduction re-specs can name the intra axis and the
        # C-order device layout aligns axes with physical slices.
        # Search-facing surfaces (strategy.mesh_axes, store keys,
        # simulator costs) keep the UNEXPANDED axes; only view
        # assignment and the jax Mesh see the expansion.
        exec_axes = strategy.mesh_axes
        hier_axis = None
        if cfg.slices > 1 and not strategy.pipeline:
            from .topology.hierarchy import (
                SLICE_AXIS,
                expand_mesh_axes,
                legal_placements,
                resolve_placement,
            )

            if num_devices % cfg.slices:
                # a degraded mesh (elastic recompile on survivors) may
                # not split into equal slices: execute flat rather
                # than failing recovery
                _log.warning(
                    "%d devices do not split into %d slices; executing "
                    "flat", num_devices, cfg.slices,
                )
            elif SLICE_AXIS in strategy.mesh_axes:
                _log.warning(
                    "mesh axis %r collides with the reserved slice "
                    "axis; executing flat (placement-less)", SLICE_AXIS,
                )
            else:
                placement = strategy.placement
                if placement is not None and placement not in \
                        legal_placements(strategy.mesh_axes, cfg.slices):
                    # imported/exported strategies can carry a placement
                    # from a different slice config: degrade to the
                    # default like the simulator and MCMC do, never
                    # crash compile over it
                    _log.warning(
                        "strategy placement %r is not legal for mesh %s "
                        "with %d slices; using the default placement",
                        placement, dict(strategy.mesh_axes), cfg.slices,
                    )
                    placement = None
                if placement is None:
                    placement = resolve_placement(
                        strategy.mesh_axes, cfg.slices
                    )
                if placement is None:
                    _log.warning(
                        "no mesh axis of %s is divisible by %d slices; "
                        "executing flat (cross-slice collectives "
                        "unsynthesized)", dict(strategy.mesh_axes),
                        cfg.slices,
                    )
                else:
                    exec_axes, hier_axis = expand_mesh_axes(
                        strategy.mesh_axes, cfg.slices, placement
                    )
                    _log.info(
                        "multi-slice execution: placement=%s over %d "
                        "slices, exec mesh %s%s", placement, cfg.slices,
                        exec_axes,
                        (f" (hierarchical reduction over {hier_axis!r})"
                         if hier_axis else ""),
                    )
        self._exec_axes = exec_axes
        assign_views(self.operators, exec_axes)
        self.mesh = make_mesh(exec_axes, devices)

        pipeline_plan = None
        if strategy.pipeline:
            from .parallel.pipeline_plan import plan_pipeline

            pipeline_plan = plan_pipeline(
                self.operators, strategy.pipeline, strategy.mesh_axes
            )
        # effective ZeRO stage: search-chosen (riding the strategy, so
        # store-restored winners replay their stage) over the config
        # knob (docs/PERF.md "The ZeRO ladder")
        zero_stage = (
            strategy.zero_stage if strategy.zero_stage is not None
            else cfg.zero_stage
        )
        # searched per-segment remat plan (docs/PERF.md "Searched
        # rematerialization"): rides the strategy like the ZeRO stage,
        # so store-restored / imported winners replay their plan; the
        # global --remat bool remains the plan-less fallback
        remat_plan = getattr(strategy, "remat", None)
        if remat_plan is not None:
            _log.info(
                "searched remat plan: %d segment(s) checkpointed (%s)",
                len(remat_plan),
                ",".join(str(i) for i in remat_plan) or "none",
            )
        self.executor = GraphExecutor(
            self.operators,
            self.mesh,
            self.loss,
            self.metrics,
            self.optimizer,
            comp_mode,
            label_replication=self._label_replication,
            compute_dtype=(
                cfg.compute_dtype if cfg.compute_dtype != "float32" else None
            ),
            remat=cfg.remat,
            pipeline_plan=pipeline_plan,
            wus_axis=(cfg.wus_axis if zero_stage >= 1 else None),
            zero_stage=zero_stage,
            hier_axis=hier_axis,
            remat_segments=remat_plan,
        )
        # per-leaf fallback observability: parallel/zero.py falls back
        # to the replicated update leaf-by-leaf — count it instead of
        # staying silent (the count also rides search_stats)
        if self.executor.zero_stage >= 1:
            fallback = self.executor.zero_fallback_leaves()
            if fallback:
                _log.warning(
                    "zero_stage=%d: %d weight leaf(s) fall back to the "
                    "replicated update (no free dim divisible by the "
                    "%r axis): %s",
                    self.executor.zero_stage, len(fallback),
                    cfg.wus_axis, ", ".join(fallback[:8]) + (
                        f", ... {len(fallback) - 8} more"
                        if len(fallback) > 8 else ""
                    ),
                )
            tel.metrics.counter("parallel/zero_fallback_leaves").inc(
                len(fallback)
            )
            stats = getattr(strategy, "search_stats", None)
            if isinstance(stats, dict):
                stats["zero_fallback_leaves"] = len(fallback)
        # score hooks live on the FRONTEND ops (the user's handles);
        # strategy application clones the compiled PCG's op objects
        self._cache_ops = [
            op for op in self.layers.topo_order()
            if op.op_type == OperatorType.CACHE
        ]
        # compiled clones by name; trace-time flags synced from the
        # frontend handles (state/ring stays on the frontend op)
        self._compiled_cache = {
            op.name: op for op in self.operators.topo_order()
            if op.op_type == OperatorType.CACHE
        }
        for fop in self._cache_ops:
            cop = self._compiled_cache.get(fop.name)
            if cop is not None:
                cop.use_cached(fop._load_cached)
        for op in self.operators.topo_order():
            op._flash_min_seq = cfg.flash_min_seq
            # keep the live graph in sync with iter_config across
            # compile/recompile (ops are rebuilt, the config persists)
            op._iter_seq_length = self.iter_config.seq_length
        self._step_cache = {}
        # init_weights jit-executes eagerly, so this span IS a real XLA
        # compile; build_step/eval/forward only stage traces (their XLA
        # compile lands in the first fit step — see docs/OBSERVABILITY.md)
        with tel.tracer.span("init_weights", cat="compile"):
            self._weights, self._state = self.executor.init_weights(
                seed if seed is not None else cfg.seed
            )
        # ZeRO-1 layout: slots move to their 1/N per-device shard here,
        # so every downstream consumer (step fn, checkpoint save/restore,
        # recompile's device_put_like) inherits the sharded placement
        self._opt_state = self.executor.shard_opt_state(
            self.optimizer.init_state(self._weights)
        )
        with tel.tracer.span("build_step_fns", cat="compile"):
            self._step_fn = self.executor.build_step()
            self._eval_fn = self.executor.build_eval_step()
            self._fwd_fn = self.executor.build_forward()
        self._step_cache[self.iter_config.seq_length] = (
            self._step_fn, self._eval_fn, self._fwd_fn,
        )
        self._rng = jax.random.key(cfg.seed)
        if cfg.export_compgraph_file:
            self.layers.export_dot(cfg.export_compgraph_file)
        if cfg.export_taskgraph_file:
            cost_fn = None
            if cfg.include_costs_dot_graph:
                # reference --include-costs-dot-graph (config.h:145):
                # annotate each node with its simulated forward cost
                from .sim.machine_model import make_machine_model
                from .sim.simulator import OpCostModel

                cm = OpCostModel(make_machine_model(cfg, num_devices))
                cost_fn = lambda op: cm.cost(op).forward_time  # noqa: E731
            self.operators.export_dot(
                cfg.export_taskgraph_file,
                include_costs=cfg.include_costs_dot_graph,
                cost_fn=cost_fn,
            )
        return self

    # ------------------------------------------------------------------
    # training surface
    # ------------------------------------------------------------------
    def _device_put_batch(self, inputs: Dict[str, np.ndarray], labels: np.ndarray):
        in_sh = self.executor.input_shardings()
        put_inputs = {
            k: jax.device_put(v, in_sh[k]) for k, v in inputs.items()
        }
        # load_cached Cache ops replay their host ring through an extra
        # feed (reference load_cached forward, cache.cc:214-231)
        for fop in self._cache_ops:
            if fop._load_cached:
                cop = self._compiled_cache.get(fop.name)
                if cop is not None:
                    put_inputs[f"__cache__{fop.name}"] = jax.device_put(
                        fop.cached_value(),
                        self.executor.tensor_sharding(cop.inputs[0]),
                    )
        put_labels = jax.device_put(labels, self.executor.label_sharding())
        return put_inputs, put_labels

    def _update_caches(self, m):
        """Fold cache taps into each frontend Cache op's host ring +
        staleness score (reference cache_update, cache.cc:180-231).
        Taps are processed one step LATE: converting this step's tap to
        numpy would block on the device; holding it until the next call
        overlaps the transfer with the next step's compute.  Flush
        points (use_cached, recompile_on_condition) force currency."""
        taps = m.pop("__cache_taps__", None) if isinstance(m, dict) else None
        pending, self._pending_taps = self._pending_taps, taps
        self._apply_taps(pending)
        return m

    def _apply_taps(self, taps):
        if not taps or not self._cache_ops:
            return
        by_name = {op.name: op for op in self._cache_ops}
        for name, v in taps.items():
            op = by_name.get(name)
            if op is not None and not op._is_legacy_score():
                op.update(np.asarray(v))

    def _flush_cache_taps(self):
        pending, self._pending_taps = self._pending_taps, None
        self._apply_taps(pending)

    def use_cached(self, load_cached: bool, name: Optional[str] = None):
        """Toggle Cache ops between passthrough and cached-batch replay
        (reference Cache::use_cached, cache.cc:259); rebuilds the jitted
        step since the flag is a trace-time constant."""
        self._flush_cache_taps()
        hit = False
        for fop in self._cache_ops:
            if name is not None and fop.name != name:
                continue
            hit = True
            fop.use_cached(load_cached)
            cop = self._compiled_cache.get(fop.name)
            if cop is not None:
                cop.use_cached(load_cached)
        if name is not None and not hit:
            raise ValueError(f"no Cache op named {name!r}")
        if self.executor is not None and hit:
            self._step_fn = self.executor.build_step()
            self._eval_fn = self.executor.build_eval_step()
            self._fwd_fn = self.executor.build_forward()
            self._step_cache = {
                self.iter_config.seq_length: (
                    self._step_fn, self._eval_fn, self._fwd_fn,
                )
            }

    def set_iteration_config(self, seq_length: Optional[int]):
        """FFIterationConfig.seq_length threading (reference
        model.cc:2415-2419): BatchMatmul ops mask positions past
        seq_length on their declared seq dims.  Step functions are
        memoized per seq_length, so alternating bucketed lengths pays
        one trace each, then dict lookups."""
        if seq_length is None or seq_length == self.iter_config.seq_length:
            return
        self.iter_config.seq_length = seq_length
        for op in self.operators.topo_order():
            op._iter_seq_length = seq_length
        cached = self._step_cache.get(seq_length)
        if cached is None:
            with self.telemetry.tracer.span("build_step_fns", cat="compile",
                                            seq_length=seq_length):
                self._step_fn = self.executor.build_step()
                self._eval_fn = self.executor.build_eval_step()
                self._fwd_fn = self.executor.build_forward()
            self._step_cache[seq_length] = (
                self._step_fn, self._eval_fn, self._fwd_fn,
            )
        else:
            self._step_fn, self._eval_fn, self._fwd_fn = cached

    def train_step(self, inputs: Dict[str, np.ndarray], labels: np.ndarray,
                   seq_length: Optional[int] = None):
        """One jitted iteration: forward + loss + backward + metrics + update."""
        self._check_not_decode_graph("train_step()")
        self.set_iteration_config(seq_length)
        tel = self.telemetry
        if tel.enabled:
            with tel.tracer.span("host_transfer", cat="data"):
                put_inputs, put_labels = self._device_put_batch(inputs, labels)
        else:  # hot path: no span objects when telemetry is off
            put_inputs, put_labels = self._device_put_batch(inputs, labels)
        self._rng, step_rng = jax.random.split(self._rng)
        self._weights, self._opt_state, self._state, m = self._step_fn(
            self._weights, self._opt_state, self._state, put_inputs, put_labels,
            step_rng,
        )
        return self._update_caches(dict(m))

    def eval_step(self, inputs: Dict[str, np.ndarray], labels: np.ndarray):
        self._check_not_decode_graph("eval_step()")
        put_inputs, put_labels = self._device_put_batch(inputs, labels)
        return self._eval_fn(self._weights, self._state, put_inputs, put_labels)

    def fit(
        self,
        x: Union[np.ndarray, Sequence[np.ndarray], Dict[str, np.ndarray]],
        y: np.ndarray,
        batch_size: Optional[int] = None,
        epochs: Optional[int] = None,
        callbacks: Sequence = (),
        verbose: bool = True,
        shuffle: bool = False,
    ) -> List[PerfMetrics]:
        """Train over numpy data (reference fit loop flexflow_cffi.py:2044-2087),
        batched through SingleDataLoader (prefetched, sharded placement)."""
        from .dataloader import SingleDataLoader

        assert self._step_fn is not None, "call compile() first"
        batch_size = batch_size or self.config.batch_size
        epochs = epochs or self.config.epochs
        input_ops = self.layers.source_ops()
        if isinstance(x, dict):
            x_map = x
        elif isinstance(x, (list, tuple)):
            x_map = {op.name: arr for op, arr in zip(input_ops, x)}
        else:
            x_map = {input_ops[0].name: x}
        loader = SingleDataLoader(self, x_map, y, batch_size=batch_size,
                                  shuffle=shuffle, seed=self.config.seed)
        num_batches = loader.num_batches
        history: List[PerfMetrics] = []
        if self.config.profiling:
            from .profiler import print_profile, profile_operators

            print_profile(profile_operators(self))
        # telemetry: all per-step work lives behind ONE boolean so the
        # disabled path allocates no span objects on the hot loop
        tel = self.telemetry
        tracing = tel.enabled
        tracer = tel.tracer
        step_hist = tel.metrics.histogram("fit/step_ms") if tracing else None
        for cb in callbacks:
            cb.on_train_begin(self)
        try:
            return self._fit_loop(
                loader, epochs, callbacks, verbose, batch_size, num_batches,
                history, tel, tracing, tracer, step_hist,
            )
        finally:
            # flush in ALL exits: a crashed traced run (the case
            # observability exists for) still writes its artifacts, and
            # an interrupted --profile-steps window stops the profiler
            if tracing:
                tel.flush()
            # drain checkpoint-manager callbacks (ModelCheckpoint with
            # async_save): queued background saves must land even when
            # the fit loop died before on_train_end ran
            for cb in callbacks:
                drain = getattr(getattr(cb, "manager", None), "drain", None)
                if callable(drain):
                    drain()

    def _fit_loop(self, loader, epochs, callbacks, verbose, batch_size,
                  num_batches, history, tel, tracing, tracer, step_hist):
        global_step = 0
        epoch_step_s: List[float] = []  # per-epoch seconds/step
        for epoch in range(epochs):
            pm = PerfMetrics()
            t0 = time.perf_counter()
            for batch, labels in loader:
                if tracing:
                    tel.on_step(global_step)  # jax.profiler window
                    ts = time.perf_counter()
                    # NOTE: steps dispatch asynchronously, so this span
                    # is host dispatch time (the first one also carries
                    # the XLA compile); device time shows up in the
                    # epoch's device_drain span and the fidelity record
                    with tracer.span("step", cat="train", step=global_step,
                                     epoch=epoch):
                        m = self.train_step(batch, labels)
                    step_hist.observe((time.perf_counter() - ts) * 1e3)
                    global_step += 1
                else:
                    m = self.train_step(batch, labels)
                # device-side accumulation: float(v) here would force a
                # per-step host<->device sync that breaks the donated
                # step chain; PerfMetrics sums on device and converts
                # once per epoch (finalize below)
                pm.accumulate(m)
                for op in self._cache_ops:
                    # legacy model-level score fns poll here; 4-arg
                    # reference-style scorers already ran in train_step
                    fn = getattr(op, "score_fn", None)
                    if fn is not None and op._is_legacy_score():
                        op.update_score(float(fn(self)))
            with tracer.span("device_drain", cat="train", epoch=epoch):
                jax.block_until_ready(jax.tree.leaves(self._weights)[0])
            dt = time.perf_counter() - t0
            pm.finalize()  # the epoch's single metrics host transfer
            throughput = num_batches * batch_size / dt
            if tracing:
                epoch_step_s.append(dt / max(1, num_batches))
                tel.metrics.histogram("fit/epoch_s").observe(dt)
                tel.metrics.gauge("fit/throughput_sps").set(throughput)
                tel.metrics.fold_counters("fit/metrics", {
                    f: getattr(pm, f) for f in PerfMetrics._FIELDS
                })
                tel.metrics.gauge("fit/metrics/accuracy").set(pm.accuracy)
            if verbose:
                print(
                    f"epoch {epoch}: {pm.summary()} "
                    f"ELAPSED TIME = {dt:.4f}s, THROUGHPUT = {throughput:.2f} samples/s"
                )
            history.append(pm)
            for cb in callbacks:
                cb.on_epoch_end(self, epoch, pm)
            if self._stop_training:
                self._stop_training = False
                break
        for cb in callbacks:
            cb.on_train_end(self)
        if tracing and epoch_step_s:
            # fidelity record: predicted vs measured step time.  The
            # best epoch is the steady-state measurement (epoch 0 pays
            # the step fn's XLA compile; with a single epoch that cost
            # is in the measurement — noted in the record's source docs)
            from .obs.fidelity import report_fidelity

            report_fidelity(
                self, min(epoch_step_s),
                steps_measured=global_step, source="fit",
            )
        return history  # fit's finally clause flushes the artifacts

    def fit_resilient(
        self,
        x: Union[np.ndarray, Sequence[np.ndarray], Dict[str, np.ndarray]],
        y: np.ndarray,
        num_steps: Optional[int] = None,
        epochs: Optional[int] = None,
        batch_size: Optional[int] = None,
        directory: Optional[str] = None,
        fault_plan=None,
        retry=None,
        resume: bool = False,
    ):
        """`fit` under the resilience supervisor: periodic checkpoints
        (async verified saves with FFConfig.checkpoint_async),
        restore-and-retry on transient failures, SIGTERM/SIGINT
        preemption grace, a hung-step watchdog (step_timeout), and
        elastic re-search + recompile on device loss
        (resilience/supervisor.py; knobs from FFConfig:
        checkpoint_every/checkpoint_keep/checkpoint_async/step_timeout/
        preempt_grace/max_restarts/retry_backoff/nan_policy).
        Step-indexed and unshuffled so an interrupted run replays
        bit-identically on the same mesh.  resume=True continues from
        the directory's newest verified checkpoint — the replacement
        process of a preempted run picks up where the emergency
        checkpoint left off.  Returns a SupervisorReport."""
        from .resilience import TrainingSupervisor

        assert self._step_fn is not None, "call compile() first"
        batch_size = batch_size or self.config.batch_size
        directory = directory or self.config.checkpoint_dir
        if directory is None:
            raise ValueError(
                "fit_resilient needs a checkpoint directory: pass "
                "directory= or set FFConfig.checkpoint_dir/--checkpoint-dir"
            )
        if num_steps is None:
            num_batches = len(y) // batch_size
            num_steps = num_batches * (epochs or self.config.epochs)
        supervisor = TrainingSupervisor(
            self, directory, fault_plan=fault_plan, retry=retry
        )
        return supervisor.run(x, y, num_steps=num_steps,
                              batch_size=batch_size, resume=resume)

    # reference-parity step pieces (model.h:767-811) — all folded into the
    # single jitted step; kept as explicit methods for API compatibility.
    def init_operators(self):
        return None

    def decode_step(self, inputs: Dict[str, np.ndarray]):
        """One incremental-decode forward: runs the compiled graph with
        the current op state and threads the returned state (KV caches +
        positions advance).  Build the graph with decode-mode attention
        (decode_max_seq > 0) and call reset_decode_state() before each
        new sequence batch."""
        if getattr(self, "_decode_fn", None) is None:
            self._decode_fn = self.executor.build_decode_step()
            limits = [
                op._decode_max_seq
                for op in self.operators.topo_order()
                if getattr(op, "_decode_max_seq", 0)
            ]
            self._decode_limit = min(limits) if limits else 0
            self.sync_decode_pos()
        # host-side overflow guard: on device dynamic_update_slice would
        # silently clamp the write index and corrupt the last cache row
        step = max(
            (int(np.asarray(v).shape[1]) for v in inputs.values()
             if np.asarray(v).ndim >= 2), default=1,
        )
        if self._decode_limit and self._decode_pos + step > self._decode_limit:
            raise ValueError(
                f"decode_step past decode_max_seq={self._decode_limit} "
                f"(position {self._decode_pos}); call reset_decode_state() "
                "to start a new sequence"
            )
        put = {
            k: jax.device_put(v, self.executor.input_shardings()[k])
            for k, v in inputs.items()
        }
        logits, self._state = self._decode_fn(self._weights, self._state, put)
        self._decode_pos += step
        return logits

    def sync_decode_pos(self):
        """Rebuild the host-side overflow-guard counter from the device
        cache_pos entries.  Called after any external `_state` swap
        (checkpoint restore, weight transfer) so the decode_step guard
        never trusts a stale shadow counter."""
        pos = 0
        for entries in (self._state or {}).values():
            cp = entries.get("cache_pos")
            if cp is not None:
                arr = np.asarray(cp).reshape(-1)
                if arr.size:
                    pos = max(pos, int(arr[0]))
        self._decode_pos = pos

    def reset_decode_state(self):
        """Zero the decode caches (k_cache/v_cache/cache_pos state
        entries, plus the paged-mode block_table/seq_lens) so the next
        decode_step starts a fresh sequence."""
        import jax.numpy as jnp

        names = ("k_cache", "v_cache", "cache_pos", "block_table",
                 "seq_lens")
        self._state = {
            op: {
                k: (jnp.zeros_like(v) if k in names else v)
                for k, v in entries.items()
            }
            for op, entries in self._state.items()
        }
        self._decode_pos = 0

    def _check_not_decode_graph(self, caller: str):
        """Plain forward/eval/train on a decode-mode graph would run
        decode attention but mis-thread the caches — forward/eval drop
        the updates (stale cache_pos=0 forever), train appends every
        step until cache_pos hits decode_max_seq and the write silently
        clamps.  The flag is a graph invariant, computed once."""
        flag = getattr(self, "_is_decode_graph", None)
        if flag is None:
            flag = self._is_decode_graph = any(
                getattr(op, "_decode_max_seq", 0)
                for op in self.operators.topo_order()
            )
        if flag:
            raise RuntimeError(
                f"{caller} on a decode-mode graph (decode_max_seq > 0) "
                "would discard the KV-cache updates; use decode_step() "
                "(or gpt_generate_cached / gpt_generate_scan)"
            )

    def forward(self, inputs: Dict[str, np.ndarray],
                seq_length: Optional[int] = None):
        self._check_not_decode_graph("forward()")
        self.set_iteration_config(seq_length)
        if self._fwd_fn is None:
            self._fwd_fn = self.executor.build_forward()
        put = {
            k: jax.device_put(v, self.executor.input_shardings()[k])
            for k, v in inputs.items()
        }
        for fop in self._cache_ops:
            if fop._load_cached:
                cop = self._compiled_cache.get(fop.name)
                if cop is not None:
                    put[f"__cache__{fop.name}"] = jax.device_put(
                        fop.cached_value(),
                        self.executor.tensor_sharding(cop.inputs[0]),
                    )
        return self._fwd_fn(self._weights, self._state, put)

    def zero_gradients(self):
        return None  # gradients are functional; nothing to zero

    def backward(self):
        raise RuntimeError(
            "backward is fused into train_step under jax.grad; call train_step"
        )

    def update(self):
        return None

    def recompile(self, strategy=None, devices=None):
        """Re-run compile under a new Strategy/device set, carrying the
        trained weights and optimizer state across (RecompileState's
        alter-hook workhorse; reference model.cc:2422-2427).  Weights
        transfer by op/weight name; shapes must be unchanged."""
        saved_w = self.get_weights()
        saved_opt = jax.tree.map(np.asarray, self._opt_state)
        saved_state = jax.tree.map(np.asarray, self._state)
        saved_rng = self._rng  # mid-training stream must not restart
        args = self._compile_args
        self.compile(
            optimizer=self.optimizer,
            loss_type=args["loss_type"],
            metrics=args["metrics"],
            comp_mode=args["comp_mode"],
            strategy=strategy,
            devices=devices if devices is not None else args["devices"],
        )
        self.set_weights(saved_w)
        # optimizer slots mirror the weight tree (SGD v, Adam m/v), so
        # a pipeline<->per-op strategy change re-maps them through the
        # same layout adaptation; scalar entries (Adam t) pass through
        saved_opt = {
            k: self._adapt_weight_layout(sub) if isinstance(sub, dict)
            else sub
            for k, sub in saved_opt.items()
        }
        self._opt_state = device_put_like(saved_opt, self._opt_state)
        self._state = device_put_like(saved_state, self._state)
        self._rng = saved_rng

    def recompile_on_condition(self, r) -> bool:
        """Fire r.alter() when r.trigger() holds (model.cc:2422)."""
        from .recompile import recompile_on_condition

        self._flush_cache_taps()  # triggers read current cache scores
        return recompile_on_condition(self, r)

    def set_learning_rate(self, lr: float):
        """Change the optimizer lr; rebuilds the jitted step (lr is a
        trace-time constant — the rebuild hits XLA's compile cache for
        previously-seen values)."""
        self.optimizer.set_lr(lr)
        if self.executor is not None:
            self._step_fn = self.executor.build_step()
            self._eval_fn = self.executor.build_eval_step()
            self._fwd_fn = self.executor.build_forward()
            # step fns traced under the old lr are stale
            self._step_cache = {
                self.iter_config.seq_length: (
                    self._step_fn, self._eval_fn, self._fwd_fn,
                )
            }

    # -- weight access (reference get_tensor/set_tensor,
    #    parallel_tensor.cc:650-750) -------------------------------------
    def get_weights(self) -> Dict[str, Dict[str, np.ndarray]]:
        return jax.tree.map(np.asarray, self._weights)

    def _adapt_weight_layout(self, weights):
        """Convert a weight-shaped pytree between the per-op layout and
        the pipeline-stacked layout (the '__pipeline__' group of
        executor.py, keyed '<j>.<name>' with the block dim leading) to
        match the CURRENT executor.  recompile carries trained state by
        op/weight name across strategies; when exactly one side of the
        carry is a PIPELINE strategy the names disagree — this is the
        mapping that makes the carry land (ROADMAP: elastic recompile
        onto a pipeline strategy died on this key mismatch)."""
        plan = getattr(self.executor, "pipeline_plan", None)
        has_stacked = "__pipeline__" in weights
        if (plan is not None) == has_stacked:
            return weights  # layouts already agree
        if plan is not None:
            # per-op -> stacked: gather each template weight across the
            # L blocks onto a leading dim (matches init_weights' layout)
            block_names = {op.name for blk in plan.blocks for op in blk}
            out = {k: dict(v) for k, v in weights.items()
                   if k not in block_names}
            entry = {}
            for j, t_op in enumerate(plan.blocks[0]):
                for spec in t_op.weight_specs:
                    entry[f"{j}.{spec.name}"] = np.stack([
                        np.asarray(weights[blk[j].name][spec.name])
                        for blk in plan.blocks
                    ])
            out["__pipeline__"] = entry
            return out
        # stacked -> per-op: unstack onto the block ops of the current
        # graph (find_repeated_blocks is deterministic on the graph
        # structure, so block order and template op order match the
        # plan that produced the stacked tree)
        from .pcg.segments import find_repeated_blocks

        blocks = find_repeated_blocks(self.layers)
        if not blocks:
            raise ValueError(
                "weights carry a '__pipeline__' group but the current "
                "graph has no repeated block stack to unstack it onto"
            )
        out = {k: dict(v) for k, v in weights.items()
               if k != "__pipeline__"}
        for key, stacked in weights["__pipeline__"].items():
            j_s, wname = key.split(".", 1)
            j = int(j_s)
            arr = np.asarray(stacked)
            if arr.shape[0] != len(blocks):
                raise ValueError(
                    f"stacked weight {key!r} has {arr.shape[0]} block "
                    f"layers but the graph repeats {len(blocks)} blocks"
                )
            for l, blk in enumerate(blocks):
                out.setdefault(blk[j].name, {})[wname] = arr[l]
        return out

    def set_weights(self, weights: Dict[str, Dict[str, np.ndarray]]):
        weights = self._adapt_weight_layout(weights)
        # master layout: the strategy shardings below ZeRO stage 3,
        # the scattered resident layout at stage 3
        shardings = self.executor.master_weight_shardings()
        self._weights = jax.tree.map(
            lambda v, s: jax.device_put(jnp.asarray(v), s), weights, shardings
        )

    def get_parameter(self, op_name: str, weight_name: str) -> np.ndarray:
        return np.asarray(self._weights[op_name][weight_name])
