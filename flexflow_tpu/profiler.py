"""Per-operator profiling (reference --profiling + per-kernel cudaEvent
timing, kernels/linear_kernels.cu:95-118, and the search's
inner_measure_operator_cost harness, model.cu:38-75).

TPU-native: each op's forward is jitted standalone on shard-shaped
random inputs and timed with block_until_ready — warmup runs absorb
compile, repeat runs are averaged.  `make_measure_fn` adapts this into
the simulator's OpCostModel measured-override hook so the strategy
search can calibrate against real chip timings.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .fftype import OperatorType
from .ops.op import Op


def _rand_array(shape, dtype, key):
    jd = jnp.dtype(dtype.np_dtype)
    if jnp.issubdtype(jd, jnp.floating):
        return jax.random.normal(key, shape, jd)
    return jnp.zeros(shape, jd)  # int inputs (indices): zeros are in-range


_base_fetch_time_cache: Dict[str, float] = {}


def _base_fetch_time(device=None, refresh: bool = False) -> float:
    """Fixed cost of one jitted-dispatch + hard value fetch — on a
    tunneled TPU this is the ~80 ms round trip that would otherwise be
    charged to every op; subtracted from chain timings."""
    key = str(device)
    hit = _base_fetch_time_cache.get(key)
    if hit is not None and not refresh:
        return hit
    x = jnp.zeros((8,), jnp.float32)
    if device is not None:
        x = jax.device_put(x, device)
    triv = jax.jit(lambda v: jnp.sum(v))
    float(triv(x))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(triv(x))  # hard fetch: the only wait that is honest
        best = min(best, time.perf_counter() - t0)
    _base_fetch_time_cache[key] = best
    return best


def measure_op_forward(
    op: Op,
    device=None,
    warmup: int = 1,
    repeats: int = 3,
    shard_shapes: bool = True,
    chain: int = 16,
) -> Optional[float]:
    """Mean forward wall time in seconds of the op's jitted kernel on
    shard-local shapes (one device's share of the work); None when the
    op cannot be profiled standalone (e.g. needs graph context).

    The op runs `chain` times inside one jitted lax.scan whose carry
    passes through an optimization_barrier with the op's output — the
    barrier stops XLA from hoisting the (loop-invariant) op out of the
    loop, and the single hard value fetch at the end is the only
    device wait.  One-shot block_until_ready timings are NOT trusted:
    through a tunneled runtime they return before execution finishes,
    and the per-call fetch latency would swamp microsecond kernels.
    """
    # standalone inputs are built on the LOGICAL (NCHW) shapes; a
    # compiled executor may have pinned this op to the physical NHWC
    # layout (pcg/layout.py), so force logical for the measurement
    saved_layout = getattr(op, "_data_layout", None)
    op._data_layout = "nchw"
    try:
        key = jax.random.key(0)
        ins = []
        for i, t in enumerate(op.inputs):
            shp = t.shape.shard_shape if shard_shapes else t.shape.logical_shape
            ins.append(_rand_array(shp, t.shape.dtype, jax.random.fold_in(key, i)))
        ws = []
        for i, spec in enumerate(op.weight_specs):
            shp = (spec.shape.shard_shape if shard_shapes
                   else spec.shape.logical_shape)
            ws.append(_rand_array(shp, spec.shape.dtype,
                                  jax.random.fold_in(key, 100 + i)))
        if not ins:
            return None

        def chained(first, rest, ws, rng):
            def body(x, _):
                out = op.forward([x] + rest, ws, training=False, rng=rng)
                leaf = jax.tree_util.tree_leaves(out)[0]
                # ties the next iteration's input to this output without
                # letting XLA see that the value is unchanged
                x2, _ = jax.lax.optimization_barrier((x, leaf))
                return x2, ()

            xn, _ = jax.lax.scan(body, first, None, length=chain)
            out = op.forward([xn] + rest, ws, training=False, rng=rng)
            return jax.tree_util.tree_leaves(out)[0].ravel()[0]

        jfn = jax.jit(chained, static_argnums=())
        if device is not None:
            ins = jax.device_put(ins, device)
            ws = jax.device_put(ws, device)
        rng = jax.random.key(1)
        first, rest = ins[0], list(ins[1:])
        for _ in range(max(1, warmup)):
            float(jfn(first, rest, ws, rng))  # compile + warm caches
        base = _base_fetch_time(device)
        best = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            float(jfn(first, rest, ws, rng))
            best = min(best, time.perf_counter() - t0)
        # chain+1 op executions per call (scan body + final fetch op)
        if best <= base:
            # a stale (load-inflated) cached base can swallow the kernel
            # time; re-measure it once under current conditions
            base = _base_fetch_time(device, refresh=True)
        if best <= base:
            # fetch-latency jitter swallowed the kernel time — a 0 here
            # would be cached as "free" forever; report unmeasurable and
            # let the analytic estimate stand
            return None
        return (best - base) / (chain + 1)
    except Exception:
        return None
    finally:
        if saved_layout is None:
            del op._data_layout
        else:
            op._data_layout = saved_layout


def make_measure_fn(device=None, warmup: int = 1, repeats: int = 3,
                    chain: int = 16):
    """OpCostModel measure_fn: op -> forward seconds (or None).
    Defaults mirror measure_op_forward's — the chained-scan timing makes
    extra repeats redundant."""

    def fn(op: Op) -> Optional[float]:
        return measure_op_forward(op, device=device, warmup=warmup,
                                  repeats=repeats, chain=chain)

    return fn


_SKIP = {OperatorType.INPUT, OperatorType.WEIGHT, OperatorType.NOOP}


def profile_operators(
    ff, device=None, warmup: int = 2, repeats: int = 5,
) -> List[Dict[str, object]]:
    """Per-op timing table for a compiled FFModel (reference --profiling
    printout).  Rows: name, type, fwd_ms, flops, shard shapes."""
    graph = ff.operators if ff.operators is not None else ff.layers
    rows: List[Dict[str, object]] = []
    for op in graph.topo_order():
        if op.op_type in _SKIP or op.is_parallel_op():
            continue
        t = measure_op_forward(op, device=device, warmup=warmup,
                               repeats=repeats)
        rows.append({
            "name": op.name,
            "type": op.op_type.name,
            "fwd_ms": None if t is None else t * 1e3,
            "flops": op.flops(),
            "out_shape": [tuple(o.shape.shard_shape) for o in op.outputs],
        })
    return rows


def print_profile(rows: List[Dict[str, object]]):
    name_w = max((len(str(r["name"])) for r in rows), default=4) + 2
    print(f"{'op':<{name_w}}{'type':<20}{'fwd ms':>10}{'GFLOP':>12}")
    for r in rows:
        ms = "n/a" if r["fwd_ms"] is None else f"{r['fwd_ms']:.3f}"
        gf = r["flops"] / 1e9
        print(f"{r['name']:<{name_w}}{r['type']:<20}{ms:>10}{gf:>12.3f}")
    total = sum(r["fwd_ms"] or 0.0 for r in rows)
    print(f"{'TOTAL':<{name_w}}{'':<20}{total:>10.3f}")
