"""Per-operator profiling (reference --profiling + per-kernel cudaEvent
timing, kernels/linear_kernels.cu:95-118, and the search's
inner_measure_operator_cost harness, model.cu:38-75).

TPU-native: each op's forward is jitted standalone on shard-shaped
random inputs and timed with block_until_ready — warmup runs absorb
compile, repeat runs are averaged.  `make_measure_fn` adapts this into
the simulator's OpCostModel measured-override hook so the strategy
search can calibrate against real chip timings.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .fftype import OperatorType
from .ops.op import Op


def _rand_array(shape, dtype, key):
    jd = jnp.dtype(dtype.np_dtype)
    if jnp.issubdtype(jd, jnp.floating):
        return jax.random.normal(key, shape, jd)
    return jnp.zeros(shape, jd)  # int inputs (indices): zeros are in-range


_base_fetch_time_cache: Dict[str, float] = {}


def _base_fetch_time(device=None, refresh: bool = False) -> float:
    """Fixed cost of one jitted-dispatch + hard value fetch — on a
    tunneled TPU this is the ~80 ms round trip that would otherwise be
    charged to every op; subtracted from chain timings."""
    key = str(device)
    hit = _base_fetch_time_cache.get(key)
    if hit is not None and not refresh:
        return hit
    x = jnp.zeros((8,), jnp.float32)
    if device is not None:
        x = jax.device_put(x, device)
    triv = jax.jit(lambda v: jnp.sum(v))
    float(triv(x))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(triv(x))  # hard fetch: the only wait that is honest
        best = min(best, time.perf_counter() - t0)
    _base_fetch_time_cache[key] = best
    return best


def measure_op_forward(
    op: Op,
    device=None,
    warmup: int = 1,
    repeats: int = 3,
    shard_shapes: bool = True,
    chain: int = 16,
) -> Optional[float]:
    """Mean forward wall time in seconds of the op's jitted kernel on
    shard-local shapes (one device's share of the work); None when the
    op cannot be profiled standalone (e.g. needs graph context).

    The op runs `chain` times inside one jitted lax.scan whose carry
    passes through an optimization_barrier with the op's output — the
    barrier stops XLA from hoisting the (loop-invariant) op out of the
    loop, and the single hard value fetch at the end is the only
    device wait.  One-shot block_until_ready timings are NOT trusted:
    through a tunneled runtime they return before execution finishes,
    and the per-call fetch latency would swamp microsecond kernels.
    """
    # standalone inputs are built on the LOGICAL (NCHW) shapes; a
    # compiled executor may have pinned this op to the physical NHWC
    # layout (pcg/layout.py), so force logical for the measurement
    saved_layout = getattr(op, "_data_layout", None)
    op._data_layout = "nchw"
    try:
        key = jax.random.key(0)
        ins = []
        for i, t in enumerate(op.inputs):
            shp = t.shape.shard_shape if shard_shapes else t.shape.logical_shape
            ins.append(_rand_array(shp, t.shape.dtype, jax.random.fold_in(key, i)))
        ws = []
        for i, spec in enumerate(op.weight_specs):
            shp = (spec.shape.shard_shape if shard_shapes
                   else spec.shape.logical_shape)
            ws.append(_rand_array(shp, spec.shape.dtype,
                                  jax.random.fold_in(key, 100 + i)))
        if not ins:
            return None

        def chained(first, rest, ws, rng):
            def body(x, _):
                out = op.forward([x] + rest, ws, training=False, rng=rng)
                leaf = jax.tree_util.tree_leaves(out)[0]
                # REAL dataflow from this iteration's output into the
                # next iteration's input: a bare optimization_barrier
                # gets split per element by XLA, the unused leaf is
                # DCE'd, and LICM then hoists the loop-invariant op out
                # of the scan — the chain times nothing.  x + 0.0*sum(y)
                # is never folded for floats (NaN semantics).
                eps = 0.0 * jnp.sum(leaf).astype(jnp.float32)
                if jnp.issubdtype(x.dtype, jnp.floating):
                    x2 = x + eps.astype(x.dtype)
                else:
                    x2 = x + eps.astype(jnp.int32).astype(x.dtype)
                return x2, ()

            xn, _ = jax.lax.scan(body, first, None, length=chain)
            out = op.forward([xn] + rest, ws, training=False, rng=rng)
            return jax.tree_util.tree_leaves(out)[0].ravel()[0]

        jfn = jax.jit(chained, static_argnums=())
        if device is not None:
            ins = jax.device_put(ins, device)
            ws = jax.device_put(ws, device)
        rng = jax.random.key(1)
        first, rest = ins[0], list(ins[1:])
        for _ in range(max(1, warmup)):
            float(jfn(first, rest, ws, rng))  # compile + warm caches
        base = _base_fetch_time(device)
        best = float("inf")
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            float(jfn(first, rest, ws, rng))
            best = min(best, time.perf_counter() - t0)
        # chain+1 op executions per call (scan body + final fetch op)
        if best <= base:
            # a stale (load-inflated) cached base can swallow the kernel
            # time; re-measure it once under current conditions
            base = _base_fetch_time(device, refresh=True)
        if best <= base:
            # fetch-latency jitter swallowed the kernel time — a 0 here
            # would be cached as "free" forever; report unmeasurable and
            # let the analytic estimate stand
            return None
        return (best - base) / (chain + 1)
    except Exception:
        return None
    finally:
        if saved_layout is None:
            del op._data_layout
        else:
            op._data_layout = saved_layout


def make_measure_fn(device=None, warmup: int = 1, repeats: int = 3,
                    chain: int = 16):
    """OpCostModel measure_fn: op -> forward seconds (or None).
    Defaults mirror measure_op_forward's — the chained-scan timing makes
    extra repeats redundant."""

    def fn(op: Op) -> Optional[float]:
        return measure_op_forward(op, device=device, warmup=warmup,
                                  repeats=repeats, chain=chain)

    return fn


_SKIP = {OperatorType.INPUT, OperatorType.WEIGHT, OperatorType.NOOP}


def profile_operators(
    ff, device=None, warmup: int = 2, repeats: int = 5,
) -> List[Dict[str, object]]:
    """Per-op timing table for a compiled FFModel (reference --profiling
    printout).  Rows: name, type, fwd_ms, flops, shard shapes."""
    graph = ff.operators if ff.operators is not None else ff.layers
    rows: List[Dict[str, object]] = []
    for op in graph.topo_order():
        if op.op_type in _SKIP or op.is_parallel_op():
            continue
        t = measure_op_forward(op, device=device, warmup=warmup,
                               repeats=repeats)
        rows.append({
            "name": op.name,
            "type": op.op_type.name,
            "fwd_ms": None if t is None else t * 1e3,
            "flops": op.flops(),
            "out_shape": [tuple(o.shape.shard_shape) for o in op.outputs],
        })
    return rows


def print_profile(rows: List[Dict[str, object]]):
    name_w = max((len(str(r["name"])) for r in rows), default=4) + 2
    print(f"{'op':<{name_w}}{'type':<20}{'fwd ms':>10}{'GFLOP':>12}")
    for r in rows:
        ms = "n/a" if r["fwd_ms"] is None else f"{r['fwd_ms']:.3f}"
        gf = r["flops"] / 1e9
        print(f"{r['name']:<{name_w}}{r['type']:<20}{ms:>10}{gf:>12.3f}")
    # unmeasurable ops (fwd_ms None) are EXCLUDED from the total, and
    # the row says so — a sum that silently counted them as 0 ms read
    # as a complete step time when it wasn't
    measured = [r for r in rows if r["fwd_ms"] is not None]
    total = sum(r["fwd_ms"] for r in measured)
    qualifier = f"({len(measured)} measured / {len(rows)} total ops"
    excluded = len(rows) - len(measured)
    if excluded:
        qualifier += f", {excluded} excluded"
    qualifier += ")"
    print(f"{'TOTAL':<{name_w}}{'':<20}{total:>10.3f}  {qualifier}")


# ---------------------------------------------------------------------------
# Region-granularity calibration (fused segments)
# ---------------------------------------------------------------------------

def _merge_regions(raw_segments, ex, max_regions: int):
    """Merge runs of measurable single-tensor segments into at most
    ~max_regions regions (transformer-layer / bottleneck-block size).
    Unmeasurable segments (cache replay, pipeline blocks) break runs
    and are dropped — they stay analytic."""
    group_size = max(1, -(-len(raw_segments) // max_regions))
    regions, run, pending = [], [], 0
    for rseg in raw_segments:
        blocked = any(
            op.op_type == OperatorType.CACHE or op.guid in ex._block_guids
            for op in rseg
        )
        if blocked:
            if run:
                regions.append(run)
            run, pending = [], 0
            continue
        run = run + rseg
        pending += 1
        if pending >= group_size:
            regions.append(run)
            run, pending = [], 0
    if run:
        regions.append(run)
    return regions


def measure_segment_costs(
    ff, device=None, chain: int = 48, repeats: int = 3,
    max_regions: int = 16,
):
    """Measured fwd+bwd seconds for fused regions of a compiled model.

    Standalone per-op timing is blind to XLA fusion context (the r02
    fidelity miss: per-op sums predicted 0.45x..3.6x of the real step),
    and timing every single-tensor segment over-counts the small ones
    (a lone LayerNorm segment materializes boundary cotangents the real
    fused step never writes).  So consecutive pure segments
    (pcg/segments.py boundaries) are merged into ~max_regions regions
    and each region's value_and_grad over its boundary activations and
    member weights is timed, chained through a lax.scan whose next
    input genuinely depends on this iteration's grads; `chain` is sized
    so the measured work dwarfs the tunnel round trip's +-50 ms jitter.

    Returns [(member op guids, seconds)] for measured regions; anything
    not covered stays analytic in the simulator.
    """
    from .pcg.layout import NHWC, TO_NHWC_PERM
    from .pcg.segments import external_inputs, split_segments

    ex = ff.executor
    graph = ex.graph
    raw_segments, _ = split_segments(graph)
    regions = _merge_regions(raw_segments, ex, max_regions)
    tensor_by_guid = {t.guid: t for op in graph.ops for t in op.outputs}
    consumed_by: Dict[int, set] = {}
    for op in graph.ops:
        for t in op.inputs:
            consumed_by.setdefault(t.guid, set()).add(op.guid)
    key = jax.random.key(17)
    results = []

    def to_compute(x):
        cd = ex.compute_dtype
        if cd is not None and jnp.issubdtype(x.dtype, jnp.floating) \
                and x.dtype != cd:
            return x.astype(cd)
        return x

    def _measure_region(region, chain_n):
        body_ops = [
            op for op in region
            if op.op_type not in (OperatorType.INPUT, OperatorType.NOOP)
        ]
        moved = sum(
            t.shape.shard_bytes()
            for op in body_ops for t in list(op.outputs) + list(op.weights)
        )
        if not body_ops or (
            moved < (1 << 16) and all(op.flops() <= 0 for op in body_ops)
        ):
            return None, []
        nonlocal key
        in_guids = external_inputs(body_ops)
        in_vals, ok = [], True
        for g in in_guids:
            t = tensor_by_guid.get(g)
            if t is None:
                ok = False
                break
            key, sub = jax.random.split(key)
            v = _rand_array(tuple(t.shape.shard_shape), t.shape.dtype, sub)
            v = to_compute(v)
            if ex._t_layout.get(g) == NHWC and v.ndim == 4:
                v = jnp.transpose(v, TO_NHWC_PERM)
            in_vals.append(v)
        if not ok or not in_vals:
            return None, []
        weights = {
            op.name: ff._weights[op.name]
            for op in body_ops if op.name in ff._weights
        }
        member = {op.guid for op in body_ops}
        # backward seeds only from tensors leaving the region — summing
        # intermediates would add cotangents the real step never has
        out_guids = tuple(
            t.guid for op in body_ops for t in op.outputs
            if consumed_by.get(t.guid, set()) - member
            or not consumed_by.get(t.guid)
        )
        first_is_float = bool(
            jnp.issubdtype(in_vals[0].dtype, jnp.floating)
        )
        if not out_guids or (not first_is_float and not weights):
            return None, []

        def seg_grad(first, rest, w, _ops=tuple(body_ops),
                     _in=tuple(in_guids), _out=out_guids,
                     _diff_first=first_is_float, chain=chain_n):
            def run(first, w):
                env = dict(zip(_in, [first] + list(rest)))
                ctx = {
                    "pipeline_done": True,
                    "weights": {**ff._weights, **w},
                    "state": ff._state,
                    "new_state": {k: dict(v) for k, v in ff._state.items()},
                    "aux": [],
                    "inputs": {},
                    "training": True,
                    "rng": None,
                    "to_compute": to_compute,
                }
                for op in _ops:
                    ex._exec_op(op, env, ctx)
                return sum(
                    jnp.sum(env[g].astype(jnp.float32)) for g in _out
                )

            argnums = (0, 1) if _diff_first else (1,)

            def body(carry, _):
                x, wc = carry
                _, grads = jax.value_and_grad(run, argnums=argnums)(x, wc)
                # REAL dataflow from this iteration's grads into the
                # next iteration's input AND weights: a bare
                # optimization_barrier is not enough (XLA splits the
                # barrier per element, DCEs the unused grad leaf, then
                # LICM hoists what remains), and loop-invariant weights
                # would hoist their casts/prep out of the scan — work
                # the real step pays every step.  x + 0.0*g is never
                # folded for floats (NaN semantics).
                gsum = sum(
                    jnp.sum(g).astype(jnp.float32)
                    for g in jax.tree_util.tree_leaves(grads)
                )
                eps = 0.0 * gsum
                if jnp.issubdtype(x.dtype, jnp.floating):
                    x = x + eps.astype(x.dtype)
                else:
                    x = x + eps.astype(jnp.int32).astype(x.dtype)
                wc = jax.tree_util.tree_map(
                    lambda a: a + eps.astype(a.dtype), wc
                )
                return (x, wc), ()

            (out, _), _ = jax.lax.scan(body, (first, w), None, length=chain)
            return jnp.sum(out.astype(jnp.float32))

        try:
            jfn = jax.jit(seg_grad)
            first, rest = in_vals[0], tuple(in_vals[1:])
            if device is not None:
                first = jax.device_put(first, device)
            float(jfn(first, rest, weights))  # compile + warm
            base = _base_fetch_time(device)
            best = float("inf")
            for _ in range(max(1, repeats)):
                t0 = time.perf_counter()
                float(jfn(first, rest, weights))
                best = min(best, time.perf_counter() - t0)
            if best <= base:
                base = _base_fetch_time(device, refresh=True)
            if best <= base:
                return None, []
            return (best - base) / chain_n, sorted(member)
        except Exception as e:
            # calibration failures flow through the shared logging
            # surface (flexflow_tpu.calib) — the obs TelemetryLogHandler
            # puts them in run_telemetry.jsonl; the full traceback is a
            # DEBUG-level detail
            import traceback

            from .logger import calib_logger

            calib_logger.info(
                "region %s... failed: %r",
                [op.name for op in body_ops][:4], e,
            )
            calib_logger.debug("%s", traceback.format_exc())
            return None, []

    measured_regions = []
    for region in regions:
        t, member = _measure_region(region, chain)
        if t is not None:
            results.append((member, t))
            measured_regions.append(region)

    # Renormalize: sums of per-region chains systematically undershoot
    # the one-program cost (per-cut scheduling/fusion effects the chain
    # cannot see — measured ~0.8 ms/cut on BERT-base).  One measurement
    # of the UNION OF SUCCESSFUL regions with the same harness pins the
    # absolute scale (failed regions stay analytic in the simulator —
    # including them here would charge their cost twice); the regions
    # keep the relative attribution.
    if len(results) > 1:
        whole = [op for r in measured_regions for op in r]
        t_whole, _ = _measure_region(whole, max(8, chain // 4))
        s = sum(c for _, c in results)
        if t_whole is not None and s > 0:
            scale = t_whole / s
            results = [(g, c * scale) for g, c in results]
    return results
