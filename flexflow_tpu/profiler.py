"""Per-operator profiling (reference --profiling + per-kernel cudaEvent
timing, kernels/linear_kernels.cu:95-118, and the search's
inner_measure_operator_cost harness, model.cu:38-75).

TPU-native: each op's forward is jitted standalone on shard-shaped
random inputs and timed with block_until_ready — warmup runs absorb
compile, repeat runs are averaged.  `make_measure_fn` adapts this into
the simulator's OpCostModel measured-override hook so the strategy
search can calibrate against real chip timings.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .fftype import OperatorType
from .ops.op import Op


def _rand_array(shape, dtype, key):
    jd = jnp.dtype(dtype.np_dtype)
    if jnp.issubdtype(jd, jnp.floating):
        return jax.random.normal(key, shape, jd)
    return jnp.zeros(shape, jd)  # int inputs (indices): zeros are in-range


def measure_op_forward(
    op: Op,
    device=None,
    warmup: int = 2,
    repeats: int = 5,
    shard_shapes: bool = True,
) -> Optional[float]:
    """Mean forward wall time in seconds of the op's jitted kernel on
    shard-local shapes (one device's share of the work); None when the
    op cannot be profiled standalone (e.g. needs graph context)."""
    try:
        key = jax.random.key(0)
        ins = []
        for i, t in enumerate(op.inputs):
            shp = t.shape.shard_shape if shard_shapes else t.shape.logical_shape
            ins.append(_rand_array(shp, t.shape.dtype, jax.random.fold_in(key, i)))
        ws = []
        for i, spec in enumerate(op.weight_specs):
            shp = (spec.shape.shard_shape if shard_shapes
                   else spec.shape.logical_shape)
            ws.append(_rand_array(shp, spec.shape.dtype,
                                  jax.random.fold_in(key, 100 + i)))

        def fn(ins, ws, rng):
            return op.forward(ins, ws, training=False, rng=rng)

        jfn = jax.jit(fn)
        if device is not None:
            ins = jax.device_put(ins, device)
            ws = jax.device_put(ws, device)
        rng = jax.random.key(1)
        for _ in range(max(1, warmup)):
            jax.block_until_ready(jfn(ins, ws, rng))
        t0 = time.perf_counter()
        for _ in range(max(1, repeats)):
            out = jfn(ins, ws, rng)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / max(1, repeats)
    except Exception:
        return None


def make_measure_fn(device=None, warmup: int = 2, repeats: int = 5):
    """OpCostModel measure_fn: op -> forward seconds (or None)."""

    def fn(op: Op) -> Optional[float]:
        return measure_op_forward(op, device=device, warmup=warmup,
                                  repeats=repeats)

    return fn


_SKIP = {OperatorType.INPUT, OperatorType.WEIGHT, OperatorType.NOOP}


def profile_operators(
    ff, device=None, warmup: int = 2, repeats: int = 5,
) -> List[Dict[str, object]]:
    """Per-op timing table for a compiled FFModel (reference --profiling
    printout).  Rows: name, type, fwd_ms, flops, shard shapes."""
    graph = ff.operators if ff.operators is not None else ff.layers
    rows: List[Dict[str, object]] = []
    for op in graph.topo_order():
        if op.op_type in _SKIP or op.is_parallel_op():
            continue
        t = measure_op_forward(op, device=device, warmup=warmup,
                               repeats=repeats)
        rows.append({
            "name": op.name,
            "type": op.op_type.name,
            "fwd_ms": None if t is None else t * 1e3,
            "flops": op.flops(),
            "out_shape": [tuple(o.shape.shard_shape) for o in op.outputs],
        })
    return rows


def print_profile(rows: List[Dict[str, object]]):
    name_w = max((len(str(r["name"])) for r in rows), default=4) + 2
    print(f"{'op':<{name_w}}{'type':<20}{'fwd ms':>10}{'GFLOP':>12}")
    for r in rows:
        ms = "n/a" if r["fwd_ms"] is None else f"{r['fwd_ms']:.3f}"
        gf = r["flops"] / 1e9
        print(f"{r['name']:<{name_w}}{r['type']:<20}{ms:>10}{gf:>12.3f}")
    total = sum(r["fwd_ms"] or 0.0 for r in rows)
    print(f"{'TOTAL':<{name_w}}{'':<20}{total:>10.3f}")
