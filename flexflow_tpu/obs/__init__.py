"""obs — unified run telemetry (tracing, metrics, fidelity).

Three pillars (docs/OBSERVABILITY.md):

  * `trace`    — Chrome trace-event timeline spans (Perfetto-viewable)
                 plus `jax.named_scope` op attribution in device profiles;
  * `metrics`  — typed counters/gauges/histograms unifying search stats,
                 resilience counters and PerfMetrics into one JSONL;
  * `fidelity` — per-run predicted-vs-measured step-time records.

`RunTelemetry` bundles them per-FFModel, wired through FFConfig
(`trace_dir`, `profile_steps`, `telemetry`) / CLI (`--trace-dir`,
`--profile-steps`, `--telemetry`).  Disabled is the default and is
zero-cost on the step hot path: the tracer is the shared NULL_TRACER
and `fit` never constructs a span (tests/test_telemetry.py guards the
no-allocation property).
"""
from __future__ import annotations

import logging
import os
import time
import weakref
from typing import Dict, Optional, Tuple

from .fidelity import fidelity_record, report_fidelity
from .metrics import (
    MetricsRegistry,
    TelemetryLogHandler,
    emit_counters,
    registry_of,
)
from .reqtrace import (
    FRONT_PID,
    NULL_REQTRACER,
    NullReqTracer,
    ReqTracer,
    TraceContext,
)
from .trace import NULL_TRACER, Tracer, span_allocations, tracer_of

TRACE_FILENAME = "trace.json"
TELEMETRY_FILENAME = "run_telemetry.jsonl"


def parse_profile_steps(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """`--profile-steps start:count` -> (first step, one-past-last);
    raises ValueError on malformed specs (validated at config time)."""
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) != 2:
        raise ValueError(
            f"profile_steps must be 'start:count', got {spec!r}"
        )
    try:
        start, count = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"profile_steps must be 'start:count' integers, got {spec!r}"
        ) from None
    if start < 0 or count < 1:
        raise ValueError(
            f"profile_steps needs start >= 0 and count >= 1, got {spec!r}"
        )
    return start, start + count


class RunTelemetry:
    """Per-run telemetry bundle: tracer + metrics registry + artifact
    paths.  The metrics registry always exists (searches/supervisors
    fold their counters unconditionally — one dict walk per run); the
    tracer and the on-disk artifacts only when enabled."""

    def __init__(
        self,
        trace_dir: Optional[str] = None,
        enabled: Optional[bool] = None,
        profile_steps: Optional[str] = None,
        run_id: Optional[str] = None,
        trace_sample: float = 1.0,
    ):
        self.trace_dir = trace_dir
        self.enabled = bool(trace_dir) if enabled is None else bool(enabled)
        self.run_id = run_id or f"run-{int(time.time())}-{os.getpid()}"
        self.metrics = MetricsRegistry()
        self.tracer = Tracer(run_id=self.run_id) if self.enabled else NULL_TRACER
        # per-request serving traces (obs/reqtrace.py): spans drain into
        # the same registry/JSONL and merge into trace.json at flush()
        self.reqtrace = (
            ReqTracer(registry=self.metrics, sample=trace_sample,
                      run_id=self.run_id)
            if self.enabled else NULL_REQTRACER
        )
        self.profile_window = parse_profile_steps(profile_steps)
        self._profiling = False
        self._log_handler: Optional[TelemetryLogHandler] = None
        self._detach = None
        if self.enabled:
            # capture flexflow_tpu.* log records (calibration failures,
            # supervisor notices) into the run's JSONL; explicit
            # telemetry opt-in also opts the library logger into INFO
            # when the app left it unconfigured (NOTSET would gate the
            # records out before the handler ever saw them).  The
            # handler detaches on close() or GC (weakref.finalize), so
            # per-model telemetry can't pile handlers onto the shared
            # logger for the process lifetime.  NOTE: logging is
            # process-global — two concurrently LIVE traced models each
            # capture the library's log stream (records aren't
            # attributable to a run without contextvars).
            self._log_handler = TelemetryLogHandler(self.metrics)
            lib_logger = logging.getLogger("flexflow_tpu")
            lib_logger.addHandler(self._log_handler)
            if lib_logger.level == logging.NOTSET:
                lib_logger.setLevel(logging.INFO)
            self._detach = weakref.finalize(
                self, lib_logger.removeHandler, self._log_handler
            )

    @classmethod
    def from_config(cls, cfg) -> "RunTelemetry":
        return cls(
            trace_dir=getattr(cfg, "trace_dir", None),
            enabled=(
                bool(getattr(cfg, "trace_dir", None))
                or bool(getattr(cfg, "telemetry", False))
            ),
            profile_steps=getattr(cfg, "profile_steps", None),
            trace_sample=getattr(cfg, "trace_sample", 1.0),
        )

    # -- jax profiler window --------------------------------------------
    def on_step(self, step: int) -> None:
        """Drive the optional `jax.profiler.trace` capture window around
        the configured [start, stop) steps.  Called from `fit` only when
        telemetry is enabled."""
        if self.profile_window is None or self.trace_dir is None:
            return
        start, stop = self.profile_window
        if step == start and not self._profiling:
            import jax

            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(
                os.path.join(self.trace_dir, "jax_profile")
            )
            self._profiling = True
            self.tracer.instant("jax_profiler_start", cat="profile",
                                step=step)
        elif step >= stop and self._profiling:
            self._stop_profiler(step)

    def _stop_profiler(self, step: int) -> None:
        import jax

        jax.profiler.stop_trace()
        self._profiling = False
        self.tracer.instant("jax_profiler_stop", cat="profile", step=step)

    # -- artifacts -------------------------------------------------------
    @property
    def trace_path(self) -> Optional[str]:
        return (
            os.path.join(self.trace_dir, TRACE_FILENAME)
            if self.trace_dir else None
        )

    @property
    def telemetry_path(self) -> Optional[str]:
        return (
            os.path.join(self.trace_dir, TELEMETRY_FILENAME)
            if self.trace_dir else None
        )

    def flush(self) -> Dict[str, str]:
        """Write/refresh the run artifacts: the Chrome trace JSON (full
        rewrite — events accumulate over the run) and the telemetry
        JSONL (append of newly drained records).  No-op when disabled
        or no trace_dir is set."""
        if self._profiling:  # a fit that ended inside the window
            self._stop_profiler(-1)
        if not self.enabled or not self.trace_dir:
            return {}
        os.makedirs(self.trace_dir, exist_ok=True)
        self.tracer.write(self.trace_path,
                          extra_events=self.reqtrace.chrome_events())
        self.metrics.write_jsonl(self.telemetry_path)
        return {"trace": self.trace_path, "telemetry": self.telemetry_path}

    def close(self) -> None:
        """Flush and detach the log handler (idempotent)."""
        self.flush()
        if self._detach is not None:
            self._detach()  # weakref.finalize: safe to call twice
        self._log_handler = None


__all__ = [
    "FRONT_PID",
    "MetricsRegistry",
    "NULL_REQTRACER",
    "NULL_TRACER",
    "NullReqTracer",
    "ReqTracer",
    "RunTelemetry",
    "TraceContext",
    "TELEMETRY_FILENAME",
    "TRACE_FILENAME",
    "Tracer",
    "emit_counters",
    "fidelity_record",
    "parse_profile_steps",
    "registry_of",
    "report_fidelity",
    "span_allocations",
    "tracer_of",
]
