"""Request-scoped distributed tracing for the serving fleet.

`obs/trace.py` answers "where did this RUN's time go"; this module
answers "where did THIS request's 900 ms TTFT go" across a
disaggregated fleet: one `TraceContext` is minted per admitted request
at the front (sampled by `--trace-sample`) and rides the request
through dispatch, the disagg dispatcher's priced migrate-vs-re-prefill
decision, the FFKV `kv_transfer` fabric (the wire dict travels in the
frame header so the adopting decode replica's spans join the same
tree), each replica's continuous scheduler (prefill / decode phase
spans that REFERENCE the shared per-dispatch batch spans instead of
duplicating them), and the speculative verify rounds.

Spans land in two places:

* the metrics registry's event stream as `"kind":"span"` JSONL records
  (drained into `run_telemetry.jsonl` — the input to
  `tools/trace_analyze.py` and `telemetry_summary.py`'s Tracing
  section), and
* Chrome trace-event "X" events merged into the run's `trace.json`
  (one track per replica: `pid` = replica id, `FRONT_PID` for the
  front), so a cross-replica migration renders as one connected tree
  in Perfetto.

Zero-cost-when-disabled contract: a front built without a `ReqTracer`
(or one whose sampler rejects the request) carries `req.trace = None`
and every hot-path call site guards on that — the decode loop
allocates NO span objects, extending the `obs.trace.span_allocations`
guard (every real `ReqSpan` construction bumps the same counter the
training-side `Span` does).
"""
from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional

from . import trace as _trace

# the front's Perfetto track; replica spans use pid = replica id (>= 0)
FRONT_PID = -1

__all__ = ["FRONT_PID", "ReqSpan", "TraceContext", "ReqTracer",
           "NullReqTracer", "NULL_REQTRACER"]


class ReqSpan:
    """One timed span in a request's trace tree.  `end()` is
    idempotent: the first call stamps `t_end` and records the span,
    later calls (e.g. the context's finish() sweep over still-open
    spans) are no-ops."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "pid", "t_start", "t_end", "args")

    def __init__(self, tracer: "ReqTracer", trace_id: Optional[str],
                 span_id: int, parent_id: Optional[int], name: str,
                 pid: int, args: Dict):
        # same process-wide counter the training-side Span bumps: the
        # disabled-path guard test covers both tracers at once
        _trace._SPAN_ALLOCS += 1
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.pid = pid
        self.args = args
        self.t_start = tracer.now()
        self.t_end: Optional[float] = None

    def end(self, **args) -> None:
        if self.t_end is not None:
            return
        if args:
            self.args.update(args)
        self.t_end = self.tracer.now()
        self.tracer._record(self)

    def __enter__(self) -> "ReqSpan":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.end()
        return False


class TraceContext:
    """One request's trace: a root span plus a name->open-span registry
    so begin/end pairs can straddle threads (admission happens on the
    caller, dispatch on the dispatcher thread, phase spans on replica
    worker threads).  `finish()` force-ends anything still open so a
    failed/shed request never leaves a dangling span."""

    def __init__(self, tracer: "ReqTracer", trace_id: str, name: str,
                 pid: int, args: Dict):
        self.tracer = tracer
        self.trace_id = trace_id
        self._lock = threading.Lock()
        self.root = tracer._span(trace_id, None, name, pid, args)
        self._open: Dict[str, ReqSpan] = {}

    # -- span lifecycle ---------------------------------------------------
    def begin(self, name: str, pid: Optional[int] = None,
              parent: Optional[int] = None, **args) -> ReqSpan:
        """Open a named span (child of the root unless `parent` given).
        Re-opening a still-open name ends the stale one first — the
        registry holds at most one open span per name."""
        span = self.tracer._span(
            self.trace_id,
            self.root.span_id if parent is None else parent,
            name,
            self.root.pid if pid is None else pid,
            args,
        )
        with self._lock:
            stale = self._open.pop(name, None)
            self._open[name] = span
        if stale is not None:
            stale.end(truncated=True)
        return span

    def end(self, name: str, **args) -> None:
        with self._lock:
            span = self._open.pop(name, None)
        if span is not None:
            span.end(**args)

    def annotate(self, name: str, **args) -> None:
        """Merge attributes into a still-open named span (e.g. the
        disagg dispatcher stamping cost terms onto the dispatch span)."""
        with self._lock:
            span = self._open.get(name)
        if span is not None:
            span.args.update(args)

    def open_id(self, name: str) -> Optional[int]:
        with self._lock:
            span = self._open.get(name)
        return span.span_id if span is not None else None

    def wire(self, parent: Optional[int] = None,
             pid: Optional[int] = None) -> Dict:
        """JSON-safe context for a frame header: the adopting side's
        spans join this tree via `ReqTracer.begin_remote`."""
        return {
            "trace_id": self.trace_id,
            "parent": self.root.span_id if parent is None else parent,
            "pid": self.root.pid if pid is None else pid,
        }

    def finish(self, **args) -> None:
        """End the root span (and force-end any still-open children)."""
        with self._lock:
            dangling = list(self._open.values())
            self._open.clear()
        for span in dangling:
            span.end()
        self.root.end(**args)


class ReqTracer:
    """Mints sampled per-request trace contexts and collects finished
    spans: each one is pushed into the registry's event stream as a
    `"kind":"span"` record (draining into run_telemetry.jsonl) and
    kept in memory for Chrome trace.json export."""

    enabled = True

    def __init__(self, registry=None, sample: float = 1.0, seed: int = 0,
                 run_id: Optional[str] = None, max_spans: int = 200_000):
        if not 0.0 <= sample <= 1.0:
            raise ValueError(f"trace sample must be in [0, 1], got {sample}")
        self.registry = registry
        self.sample = float(sample)
        self.run_id = run_id
        self.max_spans = int(max_spans)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)
        self.spans: List[Dict] = []
        self.traces_started = 0
        self.spans_recorded = 0
        self.spans_dropped = 0

    def now(self) -> float:
        return time.perf_counter() - self._t0

    # -- trace/span construction ------------------------------------------
    def trace(self, name: str = "request", pid: int = FRONT_PID,
              **args) -> Optional[TraceContext]:
        """A new per-request context, or None when the sampler rejects
        the request (the caller then carries `trace=None` and every
        downstream call site stays allocation-free)."""
        if self.sample <= 0.0:
            return None
        if self.sample < 1.0:
            with self._lock:
                keep = self._rng.random() < self.sample
            if not keep:
                return None
        with self._lock:
            self.traces_started += 1
            tid = f"req-{next(self._trace_ids):06d}"
        return TraceContext(self, tid, name, pid, args)

    def _span(self, trace_id: Optional[str], parent_id: Optional[int],
              name: str, pid: int, args: Dict) -> ReqSpan:
        return ReqSpan(self, trace_id, next(self._span_ids), parent_id,
                       name, pid, args)

    def batch_span(self, name: str, pid: int, **args) -> ReqSpan:
        """A shared per-dispatch span (prefill chunk, decode step, spec
        verify round) that serves EVERY traced request in the batch:
        it belongs to no single trace (trace_id None) and per-request
        spans reference it by span id instead of duplicating it."""
        return self._span(None, None, name, pid, args)

    def begin_remote(self, wire: Optional[Dict], name: str,
                     pid: Optional[int] = None, **args
                     ) -> Optional[ReqSpan]:
        """Adopt a wire dict (from `TraceContext.wire`, e.g. out of an
        FFKV frame header) — the new span joins the originating tree."""
        if not wire or "trace_id" not in wire:
            return None
        return self._span(
            wire["trace_id"], wire.get("parent"), name,
            int(wire.get("pid", FRONT_PID)) if pid is None else int(pid),
            args)

    # -- sinks --------------------------------------------------------------
    def _record(self, span: ReqSpan) -> None:
        rec = {
            "kind": "span",
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "pid": span.pid,
            "t_start_us": round(span.t_start * 1e6, 1),
            "dur_us": round((span.t_end - span.t_start) * 1e6, 1),
            "args": span.args,
        }
        with self._lock:
            if len(self.spans) < self.max_spans:
                self.spans.append(rec)
                self.spans_recorded += 1
            else:
                self.spans_dropped += 1
                return
        if self.registry is not None:
            self.registry.span(rec)

    def chrome_events(self) -> List[Dict]:
        """Finished spans as Chrome trace-event "X" (complete) events:
        one track per replica (`pid` = replica id; the front is
        FRONT_PID) plus process_name metadata naming the tracks."""
        with self._lock:
            spans = list(self.spans)
        events: List[Dict] = []
        pids = set()
        for rec in spans:
            pids.add(rec["pid"])
            args = dict(rec["args"])
            if rec["trace_id"] is not None:
                args["trace_id"] = rec["trace_id"]
            args["span_id"] = rec["span_id"]
            if rec["parent_id"] is not None:
                args["parent_id"] = rec["parent_id"]
            events.append({
                "ph": "X",
                "name": rec["name"],
                "cat": "reqtrace",
                "ts": rec["t_start_us"],
                "dur": rec["dur_us"],
                "pid": rec["pid"],
                "tid": 0,
                "args": args,
            })
        for pid in sorted(pids):
            label = "front" if pid == FRONT_PID else f"replica {pid}"
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "ts": 0.0, "args": {"name": f"serving {label}"},
            })
        return events

    def write(self, path: str) -> int:
        """A standalone Perfetto-loadable trace.json of just the
        request spans (runs without a `Tracer` — bare fronts in tests
        and bench legs — still get a Chrome artifact)."""
        events = sorted(self.chrome_events(), key=lambda e: e["ts"])
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if self.run_id:
            doc["otherData"] = {"run_id": self.run_id}
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return len(events)

    def stats(self) -> Dict:
        with self._lock:
            return {
                "sample": self.sample,
                "traces_started": self.traces_started,
                "spans_recorded": self.spans_recorded,
                "spans_dropped": self.spans_dropped,
            }


class NullReqTracer:
    """Disabled request tracer: every method is a constant-time no-op
    that allocates nothing — `trace()` returns None, so downstream
    `req.trace is not None` guards all fall through."""

    enabled = False
    sample = 0.0

    def trace(self, name: str = "request", pid: int = FRONT_PID,
              **args) -> None:
        return None

    def begin_remote(self, wire, name, pid=None, **args) -> None:
        return None

    def chrome_events(self) -> List[Dict]:
        return []

    def write(self, path: str) -> int:
        return 0

    def stats(self) -> Dict:
        return {"sample": 0.0, "traces_started": 0,
                "spans_recorded": 0, "spans_dropped": 0}


NULL_REQTRACER = NullReqTracer()
