"""Metrics registry: typed counters/gauges/histograms + a JSONL drain.

Unifies the repo's three previously disjoint observability surfaces —
the searches' `EvalStats`/`strategy.search_stats` dicts, the resilience
supervisor's `resilience_logger` counters, and `PerfMetrics` epoch
summaries — into one registry that drains to a per-run
`run_telemetry.jsonl` with a stable schema (SCHEMA_VERSION below; see
docs/OBSERVABILITY.md).

Record schema, one JSON object per line:

    {"schema": 1, "ts": <unix seconds>, "kind": "counter" | "gauge" |
     "histogram" | "event" | "fidelity" | "span", "name": <str>, ...payload}

    counter   -> {"value": int}
    gauge     -> {"value": float}
    histogram -> {"count", "sum", "min", "max", "mean"
                  [, "exemplar": {"value", "trace_id"}]}
    event     -> {"fields": {...}}   (log records, one-shot markers)
    fidelity  -> the obs/fidelity.py record verbatim
    span      -> a request-trace span (obs/reqtrace.py): {"trace_id",
                 "span_id", "parent_id", "pid", "t_start_us", "dur_us",
                 "args"}
"""
from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

SCHEMA_VERSION = 1


class Counter:
    """Monotonic cumulative count."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        self.value += n

    def record(self) -> Dict:
        return {"kind": "counter", "name": self.name, "value": self.value}


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def record(self) -> Dict:
        return {"kind": "gauge", "name": self.name, "value": self.value}


class Histogram:
    """Streaming count/sum/min/max summary of observations.

    An observation may carry an **exemplar** (a trace_id from
    obs/reqtrace.py): the histogram keeps the worst (largest) sampled
    value's exemplar per drain window, so an SLO regression in e.g.
    `serving/ttft_ms` links straight to the offending request's trace.
    The exemplar resets at drain; count/sum stay cumulative."""

    __slots__ = ("name", "count", "sum", "min", "max",
                 "exemplar_value", "exemplar_trace")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.exemplar_value = float("-inf")
        self.exemplar_trace: Optional[str] = None

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if exemplar is not None and v > self.exemplar_value:
            self.exemplar_value = v
            self.exemplar_trace = exemplar

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def reset_exemplar(self) -> None:
        self.exemplar_value = float("-inf")
        self.exemplar_trace = None

    def record(self) -> Dict:
        rec = {
            "kind": "histogram",
            "name": self.name,
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
            "mean": self.mean,
        }
        if self.exemplar_trace is not None:
            rec["exemplar"] = {"value": self.exemplar_value,
                               "trace_id": self.exemplar_trace}
        return rec


class MetricsRegistry:
    """Create-or-get typed metrics; same-name different-type is a bug
    and raises."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._events: List[Dict] = []

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def remove(self, name: str) -> None:
        """Drop a metric whose subject is gone (e.g. a retired serving
        replica's per-id gauge) — per-entity names minted from
        monotonically increasing ids would otherwise accumulate
        without bound in a long-lived process."""
        self._metrics.pop(name, None)

    # -- bulk folds ------------------------------------------------------
    def fold_counters(self, group: str, mapping: Dict) -> None:
        """Snapshot a flat counters dict (search_stats, supervisor
        counters, PerfMetrics fields) as gauges named `group/key` —
        these surfaces report cumulative totals, so last-write-wins is
        the correct fold."""
        for k, v in mapping.items():
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, (int, float)):
                self.gauge(f"{group}/{k}").set(v)

    def event(self, name: str, **fields) -> None:
        """One-shot structured record (log lines, run markers)."""
        self._events.append({
            "kind": "event",
            "name": name,
            "ts": time.time(),
            "fields": fields,
        })

    def fidelity(self, record: Dict) -> None:
        """Attach a simulator-fidelity record (obs/fidelity.py)."""
        rec = dict(record)
        rec["kind"] = "fidelity"
        rec.setdefault("name", "fidelity")
        rec.setdefault("ts", time.time())
        self._events.append(rec)

    def span(self, record: Dict) -> None:
        """Attach a finished request-trace span (obs/reqtrace.py) to
        the event stream — spans drain exactly once, like events."""
        rec = dict(record)
        rec["kind"] = "span"
        rec.setdefault("ts", time.time())
        self._events.append(rec)

    # -- drain -----------------------------------------------------------
    def drain(self) -> List[Dict]:
        """Buffered events (cleared) + a snapshot of every metric's
        current value.  Each record carries the schema version and a
        timestamp; re-draining re-snapshots metrics (cumulative values,
        later ts wins for readers)."""
        now = time.time()
        records: List[Dict] = []
        events, self._events = self._events, []
        for ev in events:
            ev.setdefault("ts", now)
            ev["schema"] = SCHEMA_VERSION
            records.append(ev)
        for name in sorted(self._metrics):
            metric = self._metrics[name]
            rec = metric.record()
            rec["ts"] = now
            rec["schema"] = SCHEMA_VERSION
            records.append(rec)
            if isinstance(metric, Histogram):
                # exemplars are per-drain-window: the next window's
                # worst sample gets a fresh link
                metric.reset_exemplar()
        return records

    def write_jsonl(self, path: str) -> int:
        """Append drained records to a JSONL file; returns the count."""
        records = self.drain()
        if not records:
            return 0
        with open(path, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return len(records)


def _prom_name(name: str) -> str:
    """Registry names (`serving/ttft_ms`) to Prometheus metric names
    (`serving_ttft_ms`): slashes and anything outside [a-zA-Z0-9_:]
    become underscores."""
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    return "".join(out)


def to_prometheus(registry: MetricsRegistry) -> str:
    """Render the live registry as Prometheus text exposition
    (`# TYPE` comments + `name value` samples).  Histograms render as
    summaries (`_count`/`_sum`) plus `_min`/`_max` gauges; a histogram
    holding an exemplar annotates its `_count` sample with the
    OpenMetrics exemplar syntax (`# {trace_id="..."} <value>`) so an
    SLO scrape links to the offending request trace."""
    lines: List[str] = []
    for name in sorted(registry._metrics):
        metric = registry._metrics[name]
        pname = _prom_name(name)
        if isinstance(metric, Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {metric.value}")
        elif isinstance(metric, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {metric.value}")
        elif isinstance(metric, Histogram):
            lines.append(f"# TYPE {pname} summary")
            count_line = f"{pname}_count {metric.count}"
            if metric.exemplar_trace is not None:
                count_line += (
                    f' # {{trace_id="{metric.exemplar_trace}"}}'
                    f" {metric.exemplar_value}")
            lines.append(count_line)
            lines.append(f"{pname}_sum {metric.sum}")
            lines.append(f"# TYPE {pname}_min gauge")
            lines.append(f"{pname}_min {metric.min if metric.count else 0.0}")
            lines.append(f"# TYPE {pname}_max gauge")
            lines.append(f"{pname}_max {metric.max if metric.count else 0.0}")
    return "\n".join(lines) + "\n"


def registry_of(ff) -> Optional[MetricsRegistry]:
    """The model's metrics registry, or None for anything without a
    telemetry bundle (plain executors, tests poking internals) — the
    counterpart of `obs.trace.tracer_of` for metric call sites."""
    tel = getattr(ff, "telemetry", None)
    return tel.metrics if tel is not None else None


def emit_counters(logger, label: str, mapping: Dict,
                  registry: Optional[MetricsRegistry] = None,
                  group: Optional[str] = None) -> None:
    """The migration shim for the legacy `RecursiveLogger.counters`
    call sites (mcmc/unity/supervisor): emits the EXACT same log line
    the old call did, then folds the mapping into the registry (when
    one is wired) so the counters also land in run_telemetry.jsonl."""
    logger.counters(label, mapping)
    if registry is not None:
        registry.fold_counters(group or label.replace(" ", "_"), mapping)


class TelemetryLogHandler(logging.Handler):
    """Captures `flexflow_tpu.*` log records (calibration failures,
    supervisor restore notices) into the registry's event stream so
    they land in run_telemetry.jsonl instead of dying on stdout/stderr."""

    def __init__(self, registry: MetricsRegistry, level=logging.INFO):
        super().__init__(level=level)
        self.registry = registry

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.registry.event(
                "log",
                logger=record.name,
                level=record.levelname,
                message=record.getMessage(),
            )
        except Exception:  # pragma: no cover - never break the app on telemetry
            self.handleError(record)
