"""Metrics registry: typed counters/gauges/histograms + a JSONL drain.

Unifies the repo's three previously disjoint observability surfaces —
the searches' `EvalStats`/`strategy.search_stats` dicts, the resilience
supervisor's `resilience_logger` counters, and `PerfMetrics` epoch
summaries — into one registry that drains to a per-run
`run_telemetry.jsonl` with a stable schema (SCHEMA_VERSION below; see
docs/OBSERVABILITY.md).

Record schema, one JSON object per line:

    {"schema": 1, "ts": <unix seconds>, "kind": "counter" | "gauge" |
     "histogram" | "event" | "fidelity", "name": <str>, ...payload}

    counter   -> {"value": int}
    gauge     -> {"value": float}
    histogram -> {"count", "sum", "min", "max", "mean"}
    event     -> {"fields": {...}}   (log records, one-shot markers)
    fidelity  -> the obs/fidelity.py record verbatim
"""
from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional

SCHEMA_VERSION = 1


class Counter:
    """Monotonic cumulative count."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        self.value += n

    def record(self) -> Dict:
        return {"kind": "counter", "name": self.name, "value": self.value}


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def record(self) -> Dict:
        return {"kind": "gauge", "name": self.name, "value": self.value}


class Histogram:
    """Streaming count/sum/min/max summary of observations."""

    __slots__ = ("name", "count", "sum", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def record(self) -> Dict:
        return {
            "kind": "histogram",
            "name": self.name,
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
            "mean": self.mean,
        }


class MetricsRegistry:
    """Create-or-get typed metrics; same-name different-type is a bug
    and raises."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._events: List[Dict] = []

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def remove(self, name: str) -> None:
        """Drop a metric whose subject is gone (e.g. a retired serving
        replica's per-id gauge) — per-entity names minted from
        monotonically increasing ids would otherwise accumulate
        without bound in a long-lived process."""
        self._metrics.pop(name, None)

    # -- bulk folds ------------------------------------------------------
    def fold_counters(self, group: str, mapping: Dict) -> None:
        """Snapshot a flat counters dict (search_stats, supervisor
        counters, PerfMetrics fields) as gauges named `group/key` —
        these surfaces report cumulative totals, so last-write-wins is
        the correct fold."""
        for k, v in mapping.items():
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, (int, float)):
                self.gauge(f"{group}/{k}").set(v)

    def event(self, name: str, **fields) -> None:
        """One-shot structured record (log lines, run markers)."""
        self._events.append({
            "kind": "event",
            "name": name,
            "ts": time.time(),
            "fields": fields,
        })

    def fidelity(self, record: Dict) -> None:
        """Attach a simulator-fidelity record (obs/fidelity.py)."""
        rec = dict(record)
        rec["kind"] = "fidelity"
        rec.setdefault("name", "fidelity")
        rec.setdefault("ts", time.time())
        self._events.append(rec)

    # -- drain -----------------------------------------------------------
    def drain(self) -> List[Dict]:
        """Buffered events (cleared) + a snapshot of every metric's
        current value.  Each record carries the schema version and a
        timestamp; re-draining re-snapshots metrics (cumulative values,
        later ts wins for readers)."""
        now = time.time()
        records: List[Dict] = []
        events, self._events = self._events, []
        for ev in events:
            ev.setdefault("ts", now)
            ev["schema"] = SCHEMA_VERSION
            records.append(ev)
        for name in sorted(self._metrics):
            rec = self._metrics[name].record()
            rec["ts"] = now
            rec["schema"] = SCHEMA_VERSION
            records.append(rec)
        return records

    def write_jsonl(self, path: str) -> int:
        """Append drained records to a JSONL file; returns the count."""
        records = self.drain()
        if not records:
            return 0
        with open(path, "a") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return len(records)


def registry_of(ff) -> Optional[MetricsRegistry]:
    """The model's metrics registry, or None for anything without a
    telemetry bundle (plain executors, tests poking internals) — the
    counterpart of `obs.trace.tracer_of` for metric call sites."""
    tel = getattr(ff, "telemetry", None)
    return tel.metrics if tel is not None else None


def emit_counters(logger, label: str, mapping: Dict,
                  registry: Optional[MetricsRegistry] = None,
                  group: Optional[str] = None) -> None:
    """The migration shim for the legacy `RecursiveLogger.counters`
    call sites (mcmc/unity/supervisor): emits the EXACT same log line
    the old call did, then folds the mapping into the registry (when
    one is wired) so the counters also land in run_telemetry.jsonl."""
    logger.counters(label, mapping)
    if registry is not None:
        registry.fold_counters(group or label.replace(" ", "_"), mapping)


class TelemetryLogHandler(logging.Handler):
    """Captures `flexflow_tpu.*` log records (calibration failures,
    supervisor restore notices) into the registry's event stream so
    they land in run_telemetry.jsonl instead of dying on stdout/stderr."""

    def __init__(self, registry: MetricsRegistry, level=logging.INFO):
        super().__init__(level=level)
        self.registry = registry

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.registry.event(
                "log",
                logger=record.name,
                level=record.levelname,
                message=record.getMessage(),
            )
        except Exception:  # pragma: no cover - never break the app on telemetry
            self.handleError(record)
