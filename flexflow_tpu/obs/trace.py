"""Timeline tracing: Chrome trace-event spans for a training run.

The reference leans on Legion's profiler for "where did this strategy's
time go"; here a run records host-side spans (step begin/end, jit
compile, host transfer, checkpoint writes, restarts, search phases)
into a Chrome trace-event JSON that Perfetto / chrome://tracing opens
directly, while `jax.named_scope` on every PCG op (executor._exec_op)
attributes the device-side XLA profile to operator names.

Zero-cost-when-disabled contract: the module-level NULL_TRACER is what
every call site holds when telemetry is off — its `span()` returns one
preallocated no-op context manager, so the step hot path allocates no
span objects (tests/test_telemetry.py guards this via
`span_allocations()`).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

# global allocation counter for the disabled-path guard test: every real
# Span construction bumps it; the NULL path never constructs one
_SPAN_ALLOCS = 0


def span_allocations() -> int:
    """How many Span objects have been constructed process-wide."""
    return _SPAN_ALLOCS


class Span:
    """One B/E event pair; used as a context manager."""

    __slots__ = ("_tracer", "name", "cat", "args")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: Dict):
        global _SPAN_ALLOCS
        _SPAN_ALLOCS += 1
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._tracer._emit("B", self.name, self.cat, self.args)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._emit("E", self.name, self.cat, None)
        return False


class _NullSpan:
    """Shared no-op span: one instance serves every disabled call site."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every method is a constant-time no-op that
    allocates nothing."""

    enabled = False

    def span(self, name: str, cat: str = "run", **args) -> _NullSpan:
        return _NULL_SPAN

    def instant(self, name: str, cat: str = "run", **args) -> None:
        return None

    def write(self, path: str, extra_events=None) -> None:
        return None


NULL_TRACER = NullTracer()


class Tracer:
    """Records B/E span pairs + instant events with microsecond
    timestamps (the Chrome trace-event clock unit)."""

    enabled = True

    def __init__(self, run_id: Optional[str] = None):
        self.run_id = run_id
        self.events: List[Dict] = []
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _emit(self, ph: str, name: str, cat: str, args: Optional[Dict]):
        ev = {
            "ph": ph,
            "name": name,
            "cat": cat,
            "ts": self._now_us(),
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)

    def span(self, name: str, cat: str = "run", **args) -> Span:
        return Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "run", **args) -> None:
        ev = {
            "ph": "i",
            "name": name,
            "cat": cat,
            "ts": self._now_us(),
            "pid": self._pid,
            "tid": threading.get_ident(),
            "s": "t",  # thread-scoped instant
        }
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)

    def write(self, path: str, extra_events=None) -> None:
        """Serialize as Chrome trace-event JSON (Perfetto-loadable),
        events sorted by timestamp.  `extra_events` merges additional
        pre-built events (e.g. the request tracer's per-replica span
        tracks) into the same document."""
        with self._lock:
            events = list(self.events)
        if extra_events:
            events.extend(extra_events)
        events.sort(key=lambda e: e["ts"])
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
        }
        if self.run_id:
            doc["otherData"] = {"run_id": self.run_id}
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)


def tracer_of(ff) -> "Tracer | NullTracer":
    """The model's active tracer, or NULL_TRACER for anything without
    telemetry (plain executors, tests poking internals)."""
    tel = getattr(ff, "telemetry", None)
    return tel.tracer if tel is not None else NULL_TRACER
