"""Simulator-fidelity reporting: predicted vs measured step time.

After a traced `FFModel.fit` (or a bench leg), compare the simulator's
`predicted_step_ms` for the compiled strategy against the measured step
timeline and emit a per-run fidelity record — so sim drift becomes a
tracked artifact in `run_telemetry.jsonl` instead of a bench footnote,
and the (predicted, measured) pairs accumulate into exactly the dataset
a learned TPU cost model trains on (arXiv:2008.01040).

The predictor is configured the way the strategy search's simulator was
(same fitted overlap constants, parameter-sync mode, remat and
weight-update-sharding flags), so the record measures the fidelity of
the costs the search actually ranked candidates with.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

FIDELITY_SCHEMA = 1


def predicted_step(ff, segment_costs: Optional[
        Sequence[Tuple[Sequence[int], float]]] = None):
    """SimResult for the compiled model's strategy on its mesh, using
    the search's own simulator configuration
    (pcg.mcmc.make_search_simulator — shared, not duplicated, so a new
    simulator knob cannot silently diverge the two)."""
    from ..pcg.mcmc import make_search_simulator
    from ..sim.machine_model import make_machine_model
    from ..sim.simulator import make_cost_model

    cfg = ff.config
    num_devices = int(ff.mesh.devices.size)
    machine = make_machine_model(cfg, num_devices)
    cost_model = make_cost_model(cfg, machine)
    sim = make_search_simulator(cfg, machine, cost_model)
    return sim.simulate(
        ff.operators, ff.strategy.mesh_axes, training=True,
        segment_costs=segment_costs,
        zero_stage=ff.strategy.zero_stage,
        placement=getattr(ff.strategy, "placement", None),
        remat_plan=getattr(ff.strategy, "remat", None),
    )


def fidelity_record(
    ff,
    measured_step_s: float,
    steps_measured: int = 0,
    source: str = "fit",
    segment_costs: Optional[Sequence[Tuple[Sequence[int], float]]] = None,
    sim_result=None,
) -> Dict:
    """The per-run fidelity record (stable schema, FIDELITY_SCHEMA).

    measured_step_s: steady-state seconds per training step (callers
    exclude the compile step).  segment_costs, when provided (bench legs
    run profiler.measure_segment_costs), calibrates the prediction at
    fused-region granularity and is summarized under "regions".
    sim_result: a caller's already-computed SimResult (bench passes its
    own so the record agrees with its predicted_* fields instead of
    paying — and possibly disagreeing with — a second simulation)."""
    res = (
        sim_result if sim_result is not None
        else predicted_step(ff, segment_costs=segment_costs)
    )
    predicted_ms = res.total_time * 1e3
    measured_ms = measured_step_s * 1e3
    record: Dict = {
        "fidelity_schema": FIDELITY_SCHEMA,
        "source": source,
        "predicted_step_ms": round(predicted_ms, 4),
        "measured_step_ms": round(measured_ms, 4),
        "predicted_vs_measured": (
            round(predicted_ms / measured_ms, 4) if measured_ms > 0 else None
        ),
        "predicted_compute_ms": round(res.compute_time * 1e3, 4),
        "predicted_comm_ms": round(res.comm_time * 1e3, 4),
        "predicted_sync_ms": round(res.sync_time * 1e3, 4),
        "mesh_axes": dict(ff.strategy.mesh_axes),
        "num_devices": int(ff.mesh.devices.size),
        "steps_measured": int(steps_measured),
        "calibrated": bool(segment_costs),
        "backend": str(ff.mesh.devices.flat[0].platform),
    }
    # per-tier predicted comm split (topology subsystem): zero on flat
    # meshes; on a multi-slice run this is the ICI-vs-DCN decomposition
    # the placement search priced the winner with (docs/TOPOLOGY.md)
    tiers = getattr(res, "comm_tiers", None)
    if tiers:
        record["predicted_ici_ms"] = round(tiers.get("ici_time", 0.0) * 1e3, 4)
        record["predicted_dcn_ms"] = round(tiers.get("dcn_time", 0.0) * 1e3, 4)
        record["predicted_ici_bytes"] = int(tiers.get("ici_bytes", 0.0))
        record["predicted_dcn_bytes"] = int(tiers.get("dcn_bytes", 0.0))
        record["placement"] = getattr(ff.strategy, "placement", None)
    # searched-remat memory/recompute split (docs/PERF.md "Searched
    # rematerialization"): saved-activation bytes under the compiled
    # plan and the recompute seconds the plan pays; the plan itself is
    # recorded so fidelity drift can be attributed to a remat choice
    record["predicted_activation_bytes"] = int(
        getattr(res, "activation_bytes", 0.0)
    )
    record["predicted_recompute_ms"] = round(
        getattr(res, "recompute_s", 0.0) * 1e3, 4
    )
    plan = getattr(ff.strategy, "remat", None)
    record["remat"] = (
        ",".join(str(i) for i in plan) if plan else ""
    )
    if segment_costs:
        regions: List[Dict] = [
            {"ops": len(guids), "measured_ms": round(cost * 1e3, 4)}
            for guids, cost in segment_costs
        ]
        record["regions"] = regions
        record["region_ops_covered"] = sum(r["ops"] for r in regions)
    return record


def report_fidelity(ff, measured_step_s: float, steps_measured: int = 0,
                    source: str = "fit", segment_costs=None) -> Optional[Dict]:
    """Build the record and attach it to the model's telemetry registry
    (when telemetry is enabled).  Returns the record, or None when the
    prediction cannot be computed (never fails a training run over a
    diagnostic)."""
    try:
        record = fidelity_record(
            ff, measured_step_s, steps_measured=steps_measured,
            source=source, segment_costs=segment_costs,
        )
    except Exception as e:
        from ..logger import calib_logger

        calib_logger.info("fidelity prediction failed: %r", e)
        return None
    tel = getattr(ff, "telemetry", None)
    if tel is not None and tel.enabled:
        tel.metrics.fidelity(record)
        tel.metrics.gauge("fidelity/predicted_step_ms").set(
            record["predicted_step_ms"]
        )
        tel.metrics.gauge("fidelity/measured_step_ms").set(
            record["measured_step_ms"]
        )
        if record["predicted_vs_measured"] is not None:
            tel.metrics.gauge("fidelity/predicted_vs_measured").set(
                record["predicted_vs_measured"]
            )
        # per-tier comm-bytes telemetry (docs/TOPOLOGY.md): counters so
        # multi-run drains accumulate total predicted traffic per tier
        if "predicted_ici_bytes" in record:
            tel.metrics.counter("comm/ici_bytes").inc(
                record["predicted_ici_bytes"]
            )
            tel.metrics.counter("comm/dcn_bytes").inc(
                record["predicted_dcn_bytes"]
            )
            tel.metrics.gauge("comm/ici_ms").set(record["predicted_ici_ms"])
            tel.metrics.gauge("comm/dcn_ms").set(record["predicted_dcn_ms"])
        # searched-remat memory telemetry (docs/PERF.md): counters so
        # multi-run drains accumulate per-run saved-activation bytes
        # and recompute seconds
        tel.metrics.counter("mem/activation_bytes").inc(
            record["predicted_activation_bytes"]
        )
        tel.metrics.counter("compute/recompute_s").inc(
            record["predicted_recompute_ms"] / 1e3
        )
    return record
