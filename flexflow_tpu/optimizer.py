"""Optimizers: SGD (momentum/nesterov) and Adam, sharded-state.

Reference: src/runtime/optimizer.cc + optimizer_kernel.cu — per-parameter
Legion update tasks, with the NCCL variant doing ncclAllReduce(grad)
inline before the update (optimizer_kernel.cu:88 SGD, :196 Adam), or a
parameter-server task tree (ParameterSyncType::PS).

TPU-first: gradients arrive already reduced — jax.grad of the SPMD step
emits the psum over the data axes as part of backward — so the optimizer
is a pure functional update over the weight pytree.  Optimizer slots
(momentum/adam m,v) inherit each weight's NamedSharding, which is the
sharded-optimizer-state ("ZeRO-esque") layout for free when weights are
sharded; with --weight-update-sharding the executor additionally shards
slots and the update itself along the data axis (true ZeRO-1,
executor._make_update_fn) — the update body here stays layout-agnostic.
API kept close to the reference (SGDOptimizer/AdamOptimizer names,
optimizer.h:36-110) while the math is optax-compatible.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


class Optimizer:
    def init_state(self, weights) -> Dict[str, Any]:
        raise NotImplementedError

    def next_step(self, state):
        """Host-side per-iteration bookkeeping (reference Optimizer::next)."""
        return state

    def update(self, weights, grads, state):
        raise NotImplementedError

    # uniform lr access (SGD stores `lr`, Adam stores `alpha` after the
    # reference's naming, optimizer.h:36-110)
    def get_lr(self) -> float:
        lr = getattr(self, "lr", None)
        return self.alpha if lr is None else lr

    def set_lr(self, lr: float):
        if hasattr(self, "alpha"):
            self.alpha = lr
        else:
            self.lr = lr


@dataclasses.dataclass
class SGDOptimizer(Optimizer):
    lr: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    weight_decay: float = 0.0

    def init_state(self, weights):
        if self.momentum == 0.0:
            return {}
        return {"v": jax.tree.map(jnp.zeros_like, weights)}

    def update(self, weights, grads, state):
        wd = self.weight_decay

        if self.momentum == 0.0:
            new_w = jax.tree.map(
                lambda w, g: w - self.lr * (g + wd * w), weights, grads
            )
            return new_w, state

        # one tree traversal per output (the tuple-leaf tree + two
        # is_leaf re-traversals this replaces did the same math in
        # three passes)
        mu = self.momentum
        new_v = jax.tree.map(
            lambda w, g, v: mu * v + g + wd * w, weights, grads, state["v"]
        )
        if self.nesterov:
            new_w = jax.tree.map(
                lambda w, g, v: w - self.lr * (g + wd * w + mu * v),
                weights, grads, new_v,
            )
        else:
            new_w = jax.tree.map(
                lambda w, v: w - self.lr * v, weights, new_v
            )
        return new_w, {"v": new_v}


@dataclasses.dataclass
class AdamOptimizer(Optimizer):
    alpha: float = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    weight_decay: float = 0.0
    epsilon: float = 1e-8

    def init_state(self, weights):
        return {
            "m": jax.tree.map(jnp.zeros_like, weights),
            "v": jax.tree.map(jnp.zeros_like, weights),
            "t": jnp.zeros((), jnp.int32),
        }

    def update(self, weights, grads, state):
        t = state["t"] + 1
        # bias-corrected alpha (reference Optimizer::next, optimizer.cc)
        alpha_t = (
            self.alpha
            * jnp.sqrt(1.0 - jnp.power(self.beta2, t.astype(jnp.float32)))
            / (1.0 - jnp.power(self.beta1, t.astype(jnp.float32)))
        )

        def upd(w, g, m, v):
            g = g + self.weight_decay * w
            m = self.beta1 * m + (1 - self.beta1) * g
            v = self.beta2 * v + (1 - self.beta2) * jnp.square(g)
            w = w - alpha_t * m / (jnp.sqrt(v) + self.epsilon)
            return w, m, v

        flat = jax.tree.map(upd, weights, grads, state["m"], state["v"])
        is_t = lambda t_: isinstance(t_, tuple)
        new_w = jax.tree.map(lambda x: x[0], flat, is_leaf=is_t)
        new_m = jax.tree.map(lambda x: x[1], flat, is_leaf=is_t)
        new_v = jax.tree.map(lambda x: x[2], flat, is_leaf=is_t)
        return new_w, {"m": new_m, "v": new_v, "t": t}
