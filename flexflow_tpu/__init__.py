"""flexflow_tpu — a TPU-native auto-parallelizing DNN training framework.

Brand-new implementation of Unity-era FlexFlow's capabilities
(reference: Yanivmd/FlexFlow, read-only at /root/reference) designed
TPU-first: jax/XLA SPMD over a named device Mesh replaces the Legion
runtime + mapper; Pallas kernels replace custom CUDA; ICI/DCN
collectives replace NCCL; and the Unity/MCMC strategy search drives a
TPU-pod machine model.  See SURVEY.md at the repo root.
"""
from .checkpoint import (
    CheckpointCompatibilityError,
    CheckpointManager,
    CheckpointVerifyError,
    LocalCheckpointManager,
    ModelCheckpoint,
    load_weights_npz,
    save_weights_npz,
)
from .config import FFConfig, FFIterationConfig
from .dataloader import SingleDataLoader
from .fftype import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OpBinary,
    OperatorType,
    OpUnary,
    ParameterSyncType,
)
from .initializer import (
    ConstantInitializer,
    GlorotUniform,
    NormInitializer,
    UniformInitializer,
    ZeroInitializer,
)
from .loss import Loss
from .metrics import Metrics, PerfMetrics
from .model import FFModel
from .obs import MetricsRegistry, RunTelemetry
from .optimizer import AdamOptimizer, SGDOptimizer
from .recompile import RecompileState
from .resilience import (
    FaultKind,
    FaultPlan,
    HungStepFault,
    RetryPolicy,
    StepWatchdog,
    TrainingSupervisor,
)
from .strategy import Strategy, data_parallel_strategy
from .tensor import ParallelDim, ParallelTensor, ParallelTensorShape, Tensor

__version__ = "0.1.0"
