"""flexflow_tpu — a TPU-native auto-parallelizing DNN training framework.

Brand-new implementation of Unity-era FlexFlow's capabilities
(reference: Yanivmd/FlexFlow, read-only at /root/reference) designed
TPU-first: jax/XLA SPMD over a named device Mesh replaces the Legion
runtime + mapper; Pallas kernels replace custom CUDA; ICI/DCN
collectives replace NCCL; and the Unity/MCMC strategy search drives a
TPU-pod machine model.  See SURVEY.md at the repo root.
"""
import os as _os

import jax as _jax

# Sharding-invariant RNG: with the legacy (non-partitionable) threefry,
# GSPMD-partitioning a `jax.random` draw CHANGES the values it
# produces, so a weight initialized onto a sharded layout differs from
# the same seed initialized replicated — a tensor-parallel model would
# genuinely train different weights than its single-device twin
# (tests/test_parallelism.py caught this).  The partitionable
# implementation makes every draw a pure function of (key, shape)
# regardless of how XLA partitions it; it is also the jax default
# going forward.  NOTE this is a process-global flag and changes the
# values unrelated `jax.random` draws produce in the host application;
# an explicit JAX_THREEFRY_PARTITIONABLE env setting wins over us.
if "JAX_THREEFRY_PARTITIONABLE" not in _os.environ:
    _jax.config.update("jax_threefry_partitionable", True)

from .checkpoint import (  # noqa: E402
    CheckpointCompatibilityError,
    CheckpointManager,
    CheckpointVerifyError,
    LocalCheckpointManager,
    ModelCheckpoint,
    load_weights_npz,
    save_weights_npz,
)
from .config import FFConfig, FFIterationConfig
from .dataloader import SingleDataLoader
from .fftype import (
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OpBinary,
    OperatorType,
    OpUnary,
    ParameterSyncType,
)
from .initializer import (
    ConstantInitializer,
    GlorotUniform,
    NormInitializer,
    UniformInitializer,
    ZeroInitializer,
)
from .loss import Loss
from .metrics import Metrics, PerfMetrics
from .model import FFModel
from .obs import MetricsRegistry, RunTelemetry
from .optimizer import AdamOptimizer, SGDOptimizer
from .recompile import RecompileState
from .resilience import (
    FaultKind,
    FaultPlan,
    HungStepFault,
    RetryPolicy,
    StepWatchdog,
    TrainingSupervisor,
)
from .strategy import Strategy, data_parallel_strategy
from .tensor import ParallelDim, ParallelTensor, ParallelTensorShape, Tensor

__version__ = "0.1.0"
