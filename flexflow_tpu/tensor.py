"""Parallel tensor representation — the core IR datatype.

Fresh TPU-first re-design of the reference's parallel-tensor layer
(/root/reference/include/flexflow/parallel_tensor.h:36-198): a logical
tensor whose dims each carry a partition *degree*, plus an explicit
trailing **replica dimension** so replication degree is itself a
shardable dimension (the reference's trick at
src/runtime/model.cc:2611-2633).  Unlike the reference there are no
Legion regions: a ParallelTensor lowers to a `jax.sharding.NamedSharding`
via its MachineView (see flexflow_tpu/parallel/machine.py), and XLA SPMD
performs all data movement.

Dims are stored in **row-major logical order** (numpy convention), not
the reference's Legion column-major order.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

from .fftype import DataType


@dataclasses.dataclass(frozen=True)
class ParallelDim:
    """One dimension of a parallel tensor.

    size: global logical extent of the dim (1 for pure replica dims).
    degree: number of shards this dim is split into.
    is_replica_dim: if True the dim exists only to express replication
        (size is ignored; degree = replication factor).
    """

    size: int
    degree: int = 1
    is_replica_dim: bool = False

    def __post_init__(self):
        if not self.is_replica_dim and self.degree > 1 and self.size % self.degree != 0:
            raise ValueError(
                f"dim size {self.size} not divisible by degree {self.degree}"
            )

    @property
    def shard_size(self) -> int:
        if self.is_replica_dim:
            return 1
        return self.size // self.degree

    def with_degree(self, degree: int) -> "ParallelDim":
        return dataclasses.replace(self, degree=degree)


@dataclasses.dataclass(frozen=True)
class ParallelTensorShape:
    """Shape + dtype of a parallel tensor (hashable — used as search key).

    Reference: ParallelTensorShape parallel_tensor.h:76-111.
    """

    dims: Tuple[ParallelDim, ...]
    dtype: DataType

    @classmethod
    def make(
        cls,
        shape: Sequence[int],
        dtype: DataType = DataType.FLOAT,
        degrees: Optional[Sequence[int]] = None,
        replica_degree: int = 1,
    ) -> "ParallelTensorShape":
        """Build from a plain logical shape, appending the replica dim."""
        degrees = list(degrees) if degrees is not None else [1] * len(shape)
        if len(degrees) != len(shape):
            raise ValueError("degrees must match shape rank")
        dims = tuple(ParallelDim(s, d) for s, d in zip(shape, degrees)) + (
            ParallelDim(1, replica_degree, is_replica_dim=True),
        )
        return cls(dims, DataType.from_any(dtype))

    # -- logical (user-facing) view -------------------------------------
    @property
    def logical_shape(self) -> Tuple[int, ...]:
        return tuple(d.size for d in self.dims if not d.is_replica_dim)

    @property
    def logical_rank(self) -> int:
        return len(self.logical_shape)

    # -- parallel view ---------------------------------------------------
    @property
    def replica_degree(self) -> int:
        deg = 1
        for d in self.dims:
            if d.is_replica_dim:
                deg *= d.degree
        return deg

    @property
    def degrees(self) -> Tuple[int, ...]:
        """Partition degree per logical dim (replica dims excluded)."""
        return tuple(d.degree for d in self.dims if not d.is_replica_dim)

    @property
    def total_degree(self) -> int:
        deg = 1
        for d in self.dims:
            deg *= d.degree
        return deg

    @property
    def shard_shape(self) -> Tuple[int, ...]:
        return tuple(d.shard_size for d in self.dims if not d.is_replica_dim)

    def num_elements(self) -> int:
        return int(np.prod(self.logical_shape, dtype=np.int64)) if self.dims else 0

    def shard_elements(self) -> int:
        return int(np.prod(self.shard_shape, dtype=np.int64)) if self.dims else 0

    def size_bytes(self) -> int:
        return self.num_elements() * self.dtype.size_bytes

    def shard_bytes(self) -> int:
        return self.shard_elements() * self.dtype.size_bytes

    def is_valid(self) -> bool:
        return all(
            d.is_replica_dim or (d.size > 0 and d.size % d.degree == 0)
            for d in self.dims
        )

    # -- derivation helpers ----------------------------------------------
    def with_degrees(
        self, degrees: Sequence[int], replica_degree: Optional[int] = None
    ) -> "ParallelTensorShape":
        degrees = list(degrees)
        new_dims = []
        di = 0
        for d in self.dims:
            if d.is_replica_dim:
                new_dims.append(
                    d if replica_degree is None else d.with_degree(replica_degree)
                )
            else:
                new_dims.append(d.with_degree(degrees[di]))
                di += 1
        if di != len(degrees):
            raise ValueError("degrees length mismatch")
        return ParallelTensorShape(tuple(new_dims), self.dtype)

    def data_parallel(self, degree: int) -> "ParallelTensorShape":
        """Shard dim 0 (the sample dim) by `degree`; everything else whole."""
        degrees = [1] * self.logical_rank
        if degrees:
            degrees[0] = degree
        return self.with_degrees(degrees, replica_degree=1)

    def replicate_all(self, degree: int) -> "ParallelTensorShape":
        return self.with_degrees([1] * self.logical_rank, replica_degree=degree)

    def __str__(self) -> str:
        parts = []
        for d in self.dims:
            if d.is_replica_dim:
                if d.degree > 1:
                    parts.append(f"r{d.degree}")
            elif d.degree > 1:
                parts.append(f"{d.size}/{d.degree}")
            else:
                parts.append(str(d.size))
        return f"[{', '.join(parts)}]:{self.dtype.value}"


_tensor_guid = [1000]


class Tensor:
    """Frontend tensor handle returned by FFModel layer methods.

    Analogue of the reference's logical TensorBase (include/flexflow/tensor.h):
    carries only the logical shape/dtype plus graph-edge info.  Parallel
    degrees appear after compile, on ParallelTensor.
    """

    def __init__(
        self,
        shape: Sequence[int],
        dtype: DataType = DataType.FLOAT,
        owner_layer=None,
        owner_idx: int = 0,
        name: str = "",
    ):
        _tensor_guid[0] += 1
        self.guid: int = _tensor_guid[0]
        self.shape: Tuple[int, ...] = tuple(int(s) for s in shape)
        self.dtype: DataType = DataType.from_any(dtype)
        self.owner_layer = owner_layer
        self.owner_idx = owner_idx
        self.name = name or f"tensor_{self.guid}"

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def num_elements(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1

    def __repr__(self) -> str:
        return f"Tensor({self.name}, shape={self.shape}, dtype={self.dtype.value})"


class ParallelTensor:
    """A tensor inside the compiled PCG: shape + machine view + state.

    Reference: ParallelTensorBase parallel_tensor.h:134-198.  The
    `machine_view` (set during strategy assignment) names the mesh axes
    each partitioned dim maps to; `sharding(mesh)` materializes the
    corresponding NamedSharding.
    """

    def __init__(
        self,
        shape: ParallelTensorShape,
        owner_op=None,
        owner_idx: int = 0,
        create_gradients: bool = True,
        name: str = "",
    ):
        _tensor_guid[0] += 1
        self.guid: int = _tensor_guid[0]
        self.shape = shape
        self.owner_op = owner_op
        self.owner_idx = owner_idx
        self.create_gradients = create_gradients
        self.machine_view = None  # set by strategy assignment
        self.name = name or f"ptensor_{self.guid}"

    @property
    def dims(self) -> Tuple[ParallelDim, ...]:
        return self.shape.dims

    @property
    def dtype(self) -> DataType:
        return self.shape.dtype

    def sharding(self, mesh):
        from .parallel.machine import view_to_sharding

        return view_to_sharding(self, mesh)

    def __repr__(self) -> str:
        return f"ParallelTensor({self.name}, {self.shape}, view={self.machine_view})"
