"""Parallelization strategy: representation, application, (de)serialization.

A Strategy says, for a frontend (degree-1) PCG:
  * per-op ShardConfig (op-internal parallelism: channel/reduction/
    attribute/expert degrees);
  * parallel-op insertions on tensor edges (repartition/combine/
    replicate/reduction/all_to_all chains);
  * the mesh axis sizes the degrees map onto.

Applying a strategy rebuilds the PCG with propagated parallel shapes and
assigns every tensor a MachineView — replacing the reference's
convert_graph_to_operators + per-op MachineView assignment
(model.cc:2832-2940) and its Legion-serialized strategy export
(graph.cc:2164-2400, --export-strategy/--import-strategy) with JSON.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

from .fftype import OperatorType
from .ops.op import Op, ShardConfig
from .parallel.machine import MachineView, assign_axes, validate_view
from .parallel.parallel_op import (
    PARALLEL_OP_KINDS,
    AllToAllParams,
    CombineParams,
    FusedParallelParams,
    ReductionParams,
    RepartitionParams,
    ReplicateParams,
)
from .pcg.graph import Graph


def _fused_params(**pdict) -> FusedParallelParams:
    """JSON {"ops": [[kind, {...}], ...]} -> nested frozen params
    (reference FusedParallelOp, fused_parallel_op.cc — one boundary,
    one fused resharding chain)."""
    ops = tuple(
        (kind, _PARAM_CLASSES[kind](**dict(pp)))
        for kind, pp in pdict["ops"]
    )
    return FusedParallelParams(ops=ops)


_PARAM_CLASSES = {
    "repartition": RepartitionParams,
    "combine": CombineParams,
    "replicate": ReplicateParams,
    "reduction": ReductionParams,
    "all_to_all": AllToAllParams,
    "fused": _fused_params,
}


@dataclasses.dataclass
class Strategy:
    """mesh_axes: ordered axis name -> size.
    shard_configs: frontend op NAME -> ShardConfig.
    edge_ops: frontend tensor NAME -> list of (kind, params-dict) chains
        inserted after the producing tensor (applies to all consumers).
    """

    mesh_axes: Dict[str, int]
    shard_configs: Dict[str, ShardConfig] = dataclasses.field(default_factory=dict)
    edge_ops: Dict[str, List[Tuple[str, dict]]] = dataclasses.field(default_factory=dict)
    # graph-rewrite trace: [(rule name, match index), ...] replayed on
    # the frontend graph by pcg/rewrite.py before the strategy applies
    # (reference: the rewrites GraphXfer::run applied to the winning
    # graph, substitution.cc:1898-1945)
    rewrites: List[List] = dataclasses.field(default_factory=list)
    # pipeline parallelism payload {"degree", "num_microbatches",
    # "axis", "dp_axis"} lowered by parallel/pipeline_plan.py (the
    # reference's vestigial PIPELINE_* hooks, model.h:190-192, made
    # first-class)
    pipeline: Optional[Dict] = None
    # identity of the TASO catalog the rewrites were searched with
    # ({"path", "sha256", "engine"}), recorded whenever `rewrites`
    # references catalog rules: replay resolves rule names to match
    # INDICES, so the replaying host must load byte-identical rules or
    # fail loudly (rewrite.rules_for_replay checks this)
    catalog: Optional[Dict] = None
    # search-chosen ZeRO ladder stage (0-3, docs/PERF.md); None means
    # "not chosen by the search" — the executor falls back to
    # FFConfig.zero_stage.  Rides the strategy so a store-restored or
    # imported winner replays with the stage it was costed under.
    zero_stage: Optional[int] = None
    # search-chosen multi-slice placement (docs/TOPOLOGY.md): the mesh
    # axis that spans the DCN boundary between slices.  None means "not
    # chosen" — the executor/simulator fall back to the shared
    # topology.resolve_placement default.  Meaningless (and ignored) on
    # single-slice runs, so flat strategies serialize unchanged.
    placement: Optional[str] = None
    # search-chosen per-segment remat plan (docs/PERF.md "Searched
    # rematerialization"): sorted indices of the single-tensor-boundary
    # segments whose internals recompute in backward (jax.checkpoint).
    # None means "not chosen" — the executor falls back to the global
    # FFConfig.remat bool (all pure segments).  [] is an explicit
    # all-off plan.  Serialized ONLY when set, so remat-free strategies
    # keep byte-identical JSON (and store-entry digests) to before the
    # dimension existed — the single-slice key guarantee's pattern.
    remat: Optional[List[int]] = None

    # -- serialization ---------------------------------------------------
    def to_json(self) -> str:
        payload = {
            "mesh_axes": self.mesh_axes,
            "shard_configs": {
                k: dataclasses.asdict(v) for k, v in self.shard_configs.items()
            },
            "edge_ops": self.edge_ops,
            "rewrites": [list(r) for r in self.rewrites],
            "pipeline": self.pipeline,
            "catalog": self.catalog,
            "zero_stage": self.zero_stage,
            "placement": self.placement,
        }
        if self.remat is not None:
            payload["remat"] = [int(i) for i in self.remat]
        return json.dumps(payload, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "Strategy":
        d = json.loads(text)
        return cls(
            mesh_axes=dict(d["mesh_axes"]),
            shard_configs={
                k: ShardConfig(**v) for k, v in d.get("shard_configs", {}).items()
            },
            edge_ops={
                k: [(kind, dict(p)) for kind, p in v]
                for k, v in d.get("edge_ops", {}).items()
            },
            rewrites=[list(r) for r in d.get("rewrites", [])],
            pipeline=d.get("pipeline"),
            catalog=d.get("catalog"),
            zero_stage=d.get("zero_stage"),
            placement=d.get("placement"),
            remat=(
                [int(i) for i in d["remat"]] if d.get("remat") is not None
                else None
            ),
        )

    def save(self, path: str):
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "Strategy":
        with open(path) as f:
            return cls.from_json(f.read())

    @property
    def total_devices(self) -> int:
        n = 1
        for v in self.mesh_axes.values():
            n *= v
        return n


def data_parallel_strategy(num_devices: int) -> Strategy:
    """The reference's default / --only-data-parallel strategy
    (get_basic_data_parallel_config model.h:250, model.cc:2638-2642):
    Repartition every input's sample dim across all devices."""
    s = Strategy(mesh_axes={"data": num_devices})
    s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": num_devices})]
    return s


def reapply_op(op: Op, new_inputs: Sequence, strategy: Strategy) -> Op:
    """Re-instantiate one frontend op under a strategy's ShardConfig —
    the unit apply step shared by apply_strategy and the incremental
    evaluator (pcg/evaluator.py), so search-time costing and execution
    can never instantiate ops differently."""
    if op.op_type == OperatorType.INPUT:
        return type(op)(op.params, [], name=op.name)
    shard = strategy.shard_configs.get(op.name, ShardConfig())
    return type(op)(op.params, list(new_inputs), name=op.name, shard=shard,
                    **op.ctor_kwargs())


def edge_chain_for(op: Op, out, strategy: Strategy,
                   input_chain: List) -> List:
    """The parallel-op chain a strategy inserts after one output tensor
    (INPUT ops fall back to the __inputs__ chain)."""
    if op.op_type == OperatorType.INPUT:
        return strategy.edge_ops.get(out.name, input_chain)
    return strategy.edge_ops.get(out.name, [])


def build_edge_chain(pt, chain, add_op):
    """Instantiate a parallel-op chain on `pt`, handing each new op to
    `add_op`; returns the chain's final output tensor."""
    for kind, pdict in chain:
        params = _PARAM_CLASSES[kind](**dict(pdict))
        pop = PARALLEL_OP_KINDS[kind](params, [pt], name=f"{kind}_{pt.name}")
        add_op(pop)
        pt = pop.outputs[0]
    return pt


def apply_strategy(graph: Graph, strategy: Strategy) -> Graph:
    """Rebuild the frontend PCG under a strategy.

    Walks the graph in topo order; for each frontend op instantiates a
    fresh op of the same class with the strategy's ShardConfig and
    re-propagated input tensors, inserting the strategy's parallel-op
    chains on edges.  Shape rules raise ShapeError on illegal combos —
    the search catches that to prune candidates.
    """
    new_graph = Graph()
    tensor_map: Dict[int, object] = {}  # old tensor guid -> new ParallelTensor
    input_chain = strategy.edge_ops.get("__inputs__", [])
    for op in graph.topo_order():
        if op.op_type == OperatorType.INPUT:
            new_op = reapply_op(op, [], strategy)
            new_graph.add_op(new_op)
            chain = edge_chain_for(op, op.outputs[0], strategy, input_chain)
            tensor_map[op.outputs[0].guid] = build_edge_chain(
                new_op.outputs[0], chain, new_graph.add_op
            )
            continue
        new_inputs = [tensor_map[t.guid] for t in op.inputs]
        new_op = reapply_op(op, new_inputs, strategy)
        # carry user-supplied initializers and grad flags from the frontend op
        old_by_name = {s.name: s for s in op.weight_specs}
        new_op.weight_specs = [
            dataclasses.replace(s, initializer=old_by_name[s.name].initializer)
            if s.name in old_by_name
            else s
            for s in new_op.weight_specs
        ]
        for old_out, new_out in zip(op.outputs, new_op.outputs):
            new_out.create_gradients = old_out.create_gradients
        new_graph.add_op(new_op)
        for old_out, new_out in zip(op.outputs, new_op.outputs):
            chain = edge_chain_for(op, old_out, strategy, input_chain)
            tensor_map[old_out.guid] = build_edge_chain(
                new_out, chain, new_graph.add_op
            )
    return new_graph


def assign_op_views(op: Op, mesh_axes: Dict[str, int]):
    """Assign MachineViews to one op's outputs and weights — the unit
    step of assign_views, also used by the incremental evaluator to
    re-view only a delta's rebuilt frontier (pcg/evaluator.py)."""
    for pt in list(op.outputs) + list(op.weights):
        try:
            view = assign_axes(pt.shape, mesh_axes)
            validate_view(view, pt.shape, mesh_axes)
        except ValueError as e:
            raise ValueError(f"{pt.name} {pt.shape}: {e}") from e
        pt.machine_view = view


def assign_views(graph: Graph, mesh_axes: Dict[str, int]):
    """Assign a MachineView to every tensor by factoring its degrees onto
    the mesh axes (the view normalizer; SURVEY §7 hard part 4)."""
    for op in graph.topo_order():
        assign_op_views(op, mesh_axes)
