"""Training metrics.

Reference: src/runtime/metrics_functions.cc — per-shard METRICS_COMP
task + future-chain UPDATE_METRICS fold (model.cc:3387-3400), with
`PerfMetrics` (metrics_functions.h:27-42) accumulating counts.  TPU-first:
metrics are computed inside the jitted step as global reductions (SPMD
does the cross-shard sum — the future chain collapses into a psum) and
accumulated on host in a PerfMetrics dataclass.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .fftype import MetricsType


@dataclasses.dataclass
class PerfMetrics:
    train_all: int = 0
    train_correct: int = 0
    cce_loss: float = 0.0
    sparse_cce_loss: float = 0.0
    mse_loss: float = 0.0
    rmse_loss: float = 0.0
    mae_loss: float = 0.0

    _FIELDS = ("train_all", "train_correct", "cce_loss", "sparse_cce_loss",
               "mse_loss", "rmse_loss", "mae_loss")

    def __post_init__(self):
        # running DEVICE-side sums (see accumulate); plain attribute so
        # dataclass eq/asdict semantics are untouched
        self._device_acc: Dict[str, jax.Array] = {}

    def update(self, other: Dict[str, float]):
        self.train_all += int(other.get("train_all", 0))
        self.train_correct += int(other.get("train_correct", 0))
        for f in ("cce_loss", "sparse_cce_loss", "mse_loss", "rmse_loss", "mae_loss"):
            setattr(self, f, getattr(self, f) + float(other.get(f, 0.0)))

    def accumulate(self, step_metrics: Dict[str, jax.Array]):
        """Fold one step's metric arrays into device-side running sums —
        no host sync, so back-to-back donated steps stay chained on
        device.  finalize() converts once (per epoch)."""
        for k in self._FIELDS:
            v = step_metrics.get(k)
            if v is None:
                continue
            acc = self._device_acc.get(k)
            self._device_acc[k] = v if acc is None else acc + v

    def finalize(self) -> "PerfMetrics":
        """One host transfer: fold the accumulated device sums into the
        scalar fields.  Idempotent between accumulate() calls."""
        if self._device_acc:
            vals = jax.device_get(self._device_acc)
            self._device_acc = {}
            self.update({k: float(np.asarray(v)) for k, v in vals.items()})
        return self

    @property
    def accuracy(self) -> float:
        return self.train_correct / max(1, self.train_all)

    def summary(self) -> str:
        parts = [f"accuracy={self.accuracy*100:.2f}% ({self.train_correct}/{self.train_all})"]
        for f in ("cce_loss", "sparse_cce_loss", "mse_loss", "rmse_loss", "mae_loss"):
            v = getattr(self, f)
            if v:
                parts.append(f"{f}={v:.4f}")
        return " ".join(parts)


class Metrics:
    def __init__(self, loss_type, metrics: Sequence):
        self.metrics = [MetricsType(m) if isinstance(m, str) else m for m in metrics]
        self.loss_type = loss_type

    def compute(self, logits: jax.Array, labels: jax.Array) -> Dict[str, jax.Array]:
        """Jit-side metric computation; returns scalar sums per metric."""
        sparse = labels.ndim < logits.ndim or labels.shape[-1] == 1
        if sparse:
            # class-id labels: same rank as logits with trailing dim 1
            # (reference label-tensor layout) or one rank less (per-sample
            # or per-token ids)
            lab = labels[..., 0] if labels.ndim == logits.ndim else labels
            lab = lab.astype(jnp.int32)
            n_scored = int(np.prod(lab.shape))
        else:
            # one-hot labels: one scored position per class-dim slice
            n_scored = int(np.prod(labels.shape[:-1]))
        out: Dict[str, jax.Array] = {"train_all": jnp.array(n_scored, jnp.int32)}
        for m in self.metrics:
            if m == MetricsType.ACCURACY:
                pred = jnp.argmax(logits, axis=-1)
                tgt = lab if sparse else jnp.argmax(labels, axis=-1)
                out["train_correct"] = jnp.sum(pred == tgt).astype(jnp.int32)
            elif m == MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY:
                logp = jax.nn.log_softmax(logits, axis=-1)
                out["sparse_cce_loss"] = -jnp.sum(
                    jnp.take_along_axis(logp, lab[..., None], axis=-1)
                )
            elif m == MetricsType.CATEGORICAL_CROSSENTROPY:
                logp = jnp.log(jnp.clip(logits, 1e-12, 1.0))
                out["cce_loss"] = -jnp.sum(labels * logp)
            elif m == MetricsType.MEAN_SQUARED_ERROR:
                out["mse_loss"] = jnp.sum(jnp.mean(jnp.square(logits - labels), axis=-1))
            elif m == MetricsType.ROOT_MEAN_SQUARED_ERROR:
                out["rmse_loss"] = jnp.sum(
                    jnp.sqrt(jnp.mean(jnp.square(logits - labels), axis=-1))
                )
            elif m == MetricsType.MEAN_ABSOLUTE_ERROR:
                out["mae_loss"] = jnp.sum(jnp.mean(jnp.abs(logits - labels), axis=-1))
        return out
