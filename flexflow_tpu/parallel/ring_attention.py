"""Ring attention: sequence-parallel attention over an ICI ring.

The reference has NO context parallelism (SURVEY §5 — attention is one
cudnnMultiHeadAttnForward call; the closest capability is "Repartition
on the sequence dim + FFIterationConfig.seq_length").  This module is
the TPU-native instantiation of that capability slot: q/k/v arrive
sharded on the sequence dim over a mesh axis; K/V shards rotate around
the ring via `ppermute` while each device accumulates its queries'
online-softmax state — total memory O(s_local^2) and the transfers ride
ICI neighbor links (bandwidth-optimal on a torus axis).

Used by MultiHeadAttention when its inputs' seq dim is partitioned
(strategy inserts Repartition(dim=1)); lowered via `shard_map`.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

_NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask):
    """One (q_block, kv_block) partial attention in f32.

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; mask: [sq, sk] bool or None.
    Returns (scores_max, exp_scores_rowsum, weighted_v) for online merge.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [b, h, sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m, l, pv


def _ring_attention_sharded(qh, kh, vh, *, axis_name: str, sp: int,
                            scale: float, causal: bool):
    """Per-shard body (inside shard_map). qh/kh/vh: [b, s_local, h, d]."""
    idx = jax.lax.axis_index(axis_name)
    s_local = qh.shape[1]
    k_local = kh.shape[1]  # may differ from s_local (cross-attention)
    b, _, h, d = qh.shape

    m_acc = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    l_acc = jnp.zeros((b, h, s_local), jnp.float32)
    o_acc = jnp.zeros((b, s_local, h, d), jnp.float32)

    k_blk, v_blk = kh, vh
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    for step in range(sp):
        # the block we currently hold started at device (idx - step) % sp
        src = (idx - step) % sp
        if causal:
            # absolute-position causality (matches the dense path's
            # tril over [qlen, klen] global positions)
            q_pos = idx * s_local + jnp.arange(s_local)[:, None]
            k_pos = src * k_local + jnp.arange(k_local)[None, :]
            mask = q_pos >= k_pos  # [sq, sk]
        else:
            mask = None
        m_b, l_b, pv_b = _block_attend(qh, k_blk, v_blk, scale, mask)
        m_new = jnp.maximum(m_acc, m_b)
        c_old = jnp.exp(m_acc - m_new)
        c_new = jnp.exp(m_b - m_new)
        l_acc = l_acc * c_old + l_b * c_new
        o_acc = (
            o_acc * c_old.transpose(0, 2, 1)[..., None]
            + pv_b * c_new.transpose(0, 2, 1)[..., None]
        )
        m_acc = m_new
        if step + 1 < sp:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    l_safe = jnp.where(l_acc > 0.0, l_acc, 1.0)
    out = o_acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(qh.dtype)


def ring_attention(
    qh,
    kh,
    vh,
    mesh: Mesh,
    seq_axis: str,
    *,
    batch_spec=None,
    head_spec=None,
    scale: float = 1.0,
    causal: bool = False,
):
    """Sequence-parallel attention on [b, s, h, d] arrays whose s dim is
    sharded over `seq_axis`.  batch_spec/head_spec name the mesh axes (or
    None) sharding the batch/head dims, so the shard_map specs match the
    surrounding SPMD sharding."""
    sp = mesh.shape[seq_axis]
    spec = PartitionSpec(batch_spec, seq_axis, head_spec, None)
    fn = functools.partial(
        _ring_attention_sharded,
        axis_name=seq_axis,
        sp=sp,
        scale=scale,
        causal=causal,
    )
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(qh, kh, vh)
