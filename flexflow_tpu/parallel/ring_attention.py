"""Ring attention: sequence-parallel attention over an ICI ring.

The reference has NO context parallelism (SURVEY §5 — attention is one
cudnnMultiHeadAttnForward call; the closest capability is "Repartition
on the sequence dim + FFIterationConfig.seq_length").  This module is
the TPU-native instantiation of that capability slot: q/k/v arrive
sharded on the sequence dim over a mesh axis; K/V shards rotate around
the ring via `ppermute` while each device accumulates its queries'
online-softmax state — total memory O(s_local^2) and the transfers ride
ICI neighbor links (bandwidth-optimal on a torus axis).

Used by MultiHeadAttention when its inputs' seq dim is partitioned
(strategy inserts Repartition(dim=1)); lowered via `shard_map`.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

_NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask):
    """One (q_block, kv_block) partial attention in f32 (dense path).

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; mask: [sq, sk] bool or None.
    Returns (o_b [b, sq, h, d] normalized, lse_b [b, h, sq]); fully
    masked rows carry lse = -inf and o = 0 so the merge ignores them.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [b, h, sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.where(l > 0.0, l, 1.0)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_b = pv / l_safe.transpose(0, 2, 1)[..., None]
    lse_b = jnp.where(l > 0.0, m + jnp.log(l_safe), _NEG_INF)
    return o_b, lse_b


def _block_attend_flash(q, k, v, scale, interpret):
    """Flash-kernel block attend (non-causal ring steps): the Pallas
    fwd kernel already returns (normalized out, lse) — exactly the
    merge state — so no [sq, sk] score tensor ever touches HBM.
    q: [b, sq, h, d]; k/v: [b, sk, h, d]."""
    from ..ops.pallas import flash_attention as fa

    b, sq, h, d = q.shape
    sk = k.shape[1]

    def flat(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, t.shape[1], d)

    out, lse = fa._flash_fwd_pallas(
        flat(q), flat(k), flat(v), scale, False,
        *fa._pick_blocks("fwd", sq, sk), interpret=interpret,
    )
    o_b = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return o_b.astype(jnp.float32), lse.reshape(b, h, sq)


def _use_flash_blocks(qh, kh, block_impl: str) -> bool:
    from ..ops.pallas import flash_attention as fa

    if block_impl == "dense":
        return False
    b, sq, h, d = qh.shape
    q2 = jax.ShapeDtypeStruct((b * h, sq, d), qh.dtype)
    k2 = jax.ShapeDtypeStruct((b * h, kh.shape[1], d), kh.dtype)
    ok = fa._HAVE_PALLAS and fa._supported(q2, k2)
    if block_impl == "flash":
        # forced: a silent dense fallback would make callers (and the
        # equivalence test) believe they exercised the kernel
        if not ok:
            raise ValueError(
                f"block_impl='flash' unsupported here (pallas="
                f"{fa._HAVE_PALLAS}, shard shapes {tuple(qh.shape)}/"
                f"{tuple(kh.shape)})"
            )
        return True
    return ok and jax.default_backend() == "tpu"  # "auto"


def _ring_attention_sharded(qh, kh, vh, *, axis_name: str, sp: int,
                            scale: float, causal: bool,
                            block_impl: str = "auto",
                            training: bool = False):
    """Per-shard body (inside shard_map). qh/kh/vh: [b, s_local, h, d].

    Per-block state is (normalized out, lse) — the same pair the Pallas
    flash kernel emits — merged with the log-sum-exp reweighting, so
    non-causal ring steps run the flash kernel directly (O(tile) VMEM
    score blocks instead of a dense [sq, sk] HBM tensor per step).
    Causal rings keep the dense block path: each step's mask offset is
    device-dependent (traced), which the Pallas kernel's static causal
    masking cannot express.  Training rings also stay dense: the raw
    Pallas forward has no autodiff rule, and a correct ring BACKWARD
    needs lse cotangents through the merge (future work) — the dense
    path differentiates via plain jax ops."""
    if block_impl == "flash" and (causal or training):
        raise ValueError(
            "block_impl='flash' is forward-only and non-causal "
            f"(causal={causal}, training={training})"
        )
    idx = jax.lax.axis_index(axis_name)
    s_local = qh.shape[1]
    k_local = kh.shape[1]  # may differ from s_local (cross-attention)
    b, _, h, d = qh.shape
    flash_blocks = (not causal and not training
                    and _use_flash_blocks(qh, kh, block_impl))

    lse_acc = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    o_acc = jnp.zeros((b, s_local, h, d), jnp.float32)

    k_blk, v_blk = kh, vh
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    for step in range(sp):
        # the block we currently hold started at device (idx - step) % sp
        src = (idx - step) % sp
        if causal:
            # absolute-position causality (matches the dense path's
            # tril over [qlen, klen] global positions)
            q_pos = idx * s_local + jnp.arange(s_local)[:, None]
            k_pos = src * k_local + jnp.arange(k_local)[None, :]
            mask = q_pos >= k_pos  # [sq, sk]
            o_b, lse_b = _block_attend(qh, k_blk, v_blk, scale, mask)
        elif flash_blocks:
            o_b, lse_b = _block_attend_flash(
                qh, k_blk, v_blk, scale,
                interpret=jax.default_backend() != "tpu",
            )
        else:
            o_b, lse_b = _block_attend(qh, k_blk, v_blk, scale, None)
        # log-sum-exp merge of normalized partials; -inf-safe (a row
        # with no live keys yet keeps lse -inf and zero output)
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        live = lse_new > _NEG_INF / 2
        c_old = jnp.where(live, jnp.exp(lse_acc - lse_new), 0.0)
        c_new = jnp.where(live, jnp.exp(lse_b - lse_new), 0.0)
        o_acc = (
            o_acc * c_old.transpose(0, 2, 1)[..., None]
            + o_b * c_new.transpose(0, 2, 1)[..., None]
        )
        lse_acc = lse_new
        if step + 1 < sp:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return o_acc.astype(qh.dtype)


def ring_attention(
    qh,
    kh,
    vh,
    mesh: Mesh,
    seq_axis: str,
    *,
    batch_spec=None,
    head_spec=None,
    scale: float = 1.0,
    causal: bool = False,
    block_impl: str = "auto",
    training: bool = False,
):
    """Sequence-parallel attention on [b, s, h, d] arrays whose s dim is
    sharded over `seq_axis`.  batch_spec/head_spec name the mesh axes (or
    None) sharding the batch/head dims, so the shard_map specs match the
    surrounding SPMD sharding.  block_impl: "auto" (flash per-block on
    TPU for non-causal INFERENCE rings, dense otherwise), "dense", or
    "flash" (forced — raises when unsupported; interpret-mode off-TPU
    for tests).  training=True pins the dense block path, which
    differentiates via plain jax ops."""
    sp = mesh.shape[seq_axis]
    spec = PartitionSpec(batch_spec, seq_axis, head_spec, None)
    fn = functools.partial(
        _ring_attention_sharded,
        axis_name=seq_axis,
        sp=sp,
        scale=scale,
        causal=causal,
        block_impl=block_impl,
        training=training,
    )
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(qh, kh, vh)
