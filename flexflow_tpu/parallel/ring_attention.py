"""Ring attention: sequence-parallel attention over an ICI ring.

The reference has NO context parallelism (SURVEY §5 — attention is one
cudnnMultiHeadAttnForward call; the closest capability is "Repartition
on the sequence dim + FFIterationConfig.seq_length").  This module is
the TPU-native instantiation of that capability slot: q/k/v arrive
sharded on the sequence dim over a mesh axis; K/V shards rotate around
the ring via `ppermute` while each device accumulates its queries'
online-softmax state — total memory O(s_local^2) and the transfers ride
ICI neighbor links (bandwidth-optimal on a torus axis).

Used by MultiHeadAttention when its inputs' seq dim is partitioned
(strategy inserts Repartition(dim=1)); lowered via `shard_map`.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax

from .shard_map_compat import shard_map as _shard_map
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

_NEG_INF = -1e30


def _block_attend(q, k, v, scale, mask):
    """One (q_block, kv_block) partial attention in f32 (dense path).

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; mask: [sq, sk] bool or None.
    Returns (o_b [b, sq, h, d] normalized, lse_b [b, h, sq]); fully
    masked rows carry lse = -inf and o = 0 so the merge ignores them.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [b, h, sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.where(l > 0.0, l, 1.0)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_b = pv / l_safe.transpose(0, 2, 1)[..., None]
    lse_b = jnp.where(l > 0.0, m + jnp.log(l_safe), _NEG_INF)
    return o_b, lse_b


def _block_attend_flash(q, k, v, scale, causal, interpret):
    """Flash-kernel block attend: the Pallas fwd kernel already returns
    (normalized out, lse) — exactly the merge state — so no [sq, sk]
    score tensor ever touches HBM.  `causal` uses the kernel's static
    intra-block masking (the ring's DIAGONAL blocks, where local and
    global positions coincide).  q: [b, sq, h, d]; k/v: [b, sk, h, d].
    """
    from ..ops.pallas import flash_attention as fa

    b, sq, h, d = q.shape
    sk = k.shape[1]

    def flat(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, t.shape[1], d)

    out, lse = fa._flash_fwd_pallas(
        flat(q), flat(k), flat(v), scale, causal,
        *fa._pick_blocks("fwd", sq, sk), interpret=interpret,
    )
    o_b = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return o_b.astype(jnp.float32), lse.reshape(b, h, sq)


def _use_flash_blocks(qh, kh, sp: int, block_impl: str) -> bool:
    """Decide on the PER-SHARD shapes (global seq / sp): the kernels
    run inside shard_map, so a globally-divisible length whose shard
    has no >=128 tile must still fall back to dense."""
    from ..ops.pallas import flash_attention as fa

    if block_impl == "dense":
        return False
    b, sq, h, d = qh.shape
    sk = kh.shape[1]
    ok = (
        fa._HAVE_PALLAS
        and sq % sp == 0
        and sk % sp == 0
        and fa._supported(
            jax.ShapeDtypeStruct((b * h, sq // sp, d), qh.dtype),
            jax.ShapeDtypeStruct((b * h, sk // sp, d), kh.dtype),
        )
    )
    if block_impl == "flash":
        # forced: a silent dense fallback would make callers (and the
        # equivalence test) believe they exercised the kernel
        if not ok:
            raise ValueError(
                f"block_impl='flash' unsupported here (pallas="
                f"{fa._HAVE_PALLAS}, global shapes {tuple(qh.shape)}/"
                f"{tuple(kh.shape)}, sp={sp} -> shard seqs "
                f"{sq // sp if sq % sp == 0 else 'indivisible'}/"
                f"{sk // sp if sk % sp == 0 else 'indivisible'})"
            )
        return True
    return ok and jax.default_backend() == "tpu"  # "auto"


def _ring_attention_sharded(qh, kh, vh, *, axis_name: str, sp: int,
                            scale: float, causal: bool):
    """DENSE per-shard body (inside shard_map); qh/kh/vh:
    [b, s_local, h, d].  Per-block state is (normalized out, lse),
    merged with an -inf-safe log-sum-exp reweighting.  This path
    differentiates through plain jax ops; it is the fallback for
    shapes the Pallas kernels cannot tile (and for non-square causal
    cross-attention) — supported rings, causal included, route through
    _ring_flash_trainable instead."""
    idx = jax.lax.axis_index(axis_name)
    s_local = qh.shape[1]
    k_local = kh.shape[1]  # may differ from s_local (cross-attention)
    b, _, h, d = qh.shape

    lse_acc = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    o_acc = jnp.zeros((b, s_local, h, d), jnp.float32)

    k_blk, v_blk = kh, vh
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    for step in range(sp):
        # the block we currently hold started at device (idx - step) % sp
        src = (idx - step) % sp
        if causal:
            # absolute-position causality (matches the dense path's
            # tril over [qlen, klen] global positions)
            q_pos = idx * s_local + jnp.arange(s_local)[:, None]
            k_pos = src * k_local + jnp.arange(k_local)[None, :]
            mask = q_pos >= k_pos  # [sq, sk]
        else:
            mask = None
        o_b, lse_b = _block_attend(qh, k_blk, v_blk, scale, mask)
        # log-sum-exp merge of normalized partials; -inf-safe (a row
        # with no live keys yet keeps lse -inf and zero output)
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        live = lse_new > _NEG_INF / 2
        c_old = jnp.where(live, jnp.exp(lse_acc - lse_new), 0.0)
        c_new = jnp.where(live, jnp.exp(lse_b - lse_new), 0.0)
        o_acc = (
            o_acc * c_old.transpose(0, 2, 1)[..., None]
            + o_b * c_new.transpose(0, 2, 1)[..., None]
        )
        lse_acc = lse_new
        if step + 1 < sp:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return o_acc.astype(qh.dtype)


def _ring_flash_fwd_sharded(qh, kh, vh, *, axis_name: str, sp: int,
                            scale: float, causal: bool, interpret: bool):
    """Flash ring FORWARD returning (out, lse) — the residuals the
    manual backward needs.

    Causality without kernel offsets: ring step 0 is every device's
    DIAGONAL block (src == idx), which is exactly the kernel's static
    causal masking; later steps hold strictly earlier (fully visible)
    or strictly later (fully masked) blocks, decided by the traced
    `step <= idx` — masked blocks simply don't merge (their compute is
    the inherent idle work of an unbalanced causal ring)."""
    idx = jax.lax.axis_index(axis_name)
    b, s_local, h, d = qh.shape
    lse_acc = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    o_acc = jnp.zeros((b, s_local, h, d), jnp.float32)
    k_blk, v_blk = kh, vh
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    for step in range(sp):
        o_b, lse_b = _block_attend_flash(
            qh, k_blk, v_blk, scale, causal and step == 0, interpret)
        if causal and step > 0:
            lse_b = jnp.where(step <= idx, lse_b, _NEG_INF)
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        live = lse_new > _NEG_INF / 2
        c_old = jnp.where(live, jnp.exp(lse_acc - lse_new), 0.0)
        c_new = jnp.where(live, jnp.exp(lse_b - lse_new), 0.0)
        o_acc = (
            o_acc * c_old.transpose(0, 2, 1)[..., None]
            + o_b * c_new.transpose(0, 2, 1)[..., None]
        )
        lse_acc = lse_new
        if step + 1 < sp:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return o_acc.astype(qh.dtype), lse_acc


def _ring_flash_bwd_sharded(qh, kh, vh, out, lse, dout, *,
                            axis_name: str, sp: int, scale: float,
                            causal: bool, interpret: bool):
    """Flash ring BACKWARD (causal via the same diagonal-step /
    gated-visibility scheme as the forward).

    Each device owns its q rows' (out, lse, dout) and accumulates dq
    locally with the Pallas dq kernel; dk/dv partial sums ROTATE WITH
    their k/v blocks (the dkv kernel adds each device's contribution
    as the block passes through), so after sp steps plus one homing
    ppermute every gradient block is complete on its owner.  The
    global softmax statistics ride in `lse` — each block's
    probabilities recompute against the FULL-sequence normalizer, which
    is what makes blockwise dk/dv sums exact."""
    from ..ops.pallas import flash_attention as fa

    b, s_local, h, d = qh.shape
    k_local = kh.shape[1]

    def flat(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, t.shape[1], d)

    q2, do2, o2 = flat(qh), flat(dout), flat(out)
    lse2 = lse.reshape(b * h, s_local)
    dq_bq, dq_bk = fa._pick_blocks("dq", s_local, k_local)
    dkv_bq, dkv_bk = fa._pick_blocks("dkv", s_local, k_local)

    def unflat(t2, s):
        return t2.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    idx = jax.lax.axis_index(axis_name)
    dq_acc = jnp.zeros((b, s_local, h, d), jnp.float32)
    k_blk, v_blk = kh, vh
    dk_blk = jnp.zeros_like(kh, dtype=jnp.float32)
    dv_blk = jnp.zeros_like(vh, dtype=jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    for step in range(sp):
        # causal off-diagonal steps: this device's queries see the held
        # block only when it is strictly earlier (step <= idx).  Masked
        # blocks must not reach the kernel with the true lse: their raw
        # scores can EXCEED the global normalizer (they never entered
        # the softmax), and exp(s - lse) would overflow before the gate
        # zeroes it — feeding a huge lse drives p to exactly 0 instead.
        if causal and step > 0:
            live = step <= idx
            lse_in = jnp.where(live, lse2, jnp.float32(1e30))
            g = live.astype(jnp.float32)
        else:
            lse_in, g = lse2, jnp.float32(1.0)
        dq_b, dk_b, dv_b = fa._flash_bwd_pallas(
            q2, flat(k_blk), flat(v_blk), o2, lse_in, do2, scale,
            causal and step == 0,
            dq_bq, dq_bk, interpret=interpret,
            dkv_blocks=(dkv_bq, dkv_bk),
        )
        dq_acc = dq_acc + g * unflat(dq_b, s_local).astype(jnp.float32)
        dk_blk = dk_blk + g * unflat(dk_b, k_local).astype(jnp.float32)
        dv_blk = dv_blk + g * unflat(dv_b, k_local).astype(jnp.float32)
        # rotate the k/v blocks with their accumulating gradients; the
        # FINAL rotation homes each gradient block to its owner, so
        # only the accumulators ride it (k/v are dead after the last
        # kernel call)
        if step + 1 < sp:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
    return (dq_acc.astype(qh.dtype), dk_blk.astype(kh.dtype),
            dv_blk.astype(vh.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring_flash_trainable(qh, kh, vh, mesh, seq_axis, spec, sp, scale,
                          causal, interpret):
    return _ring_flash_trainable_fwd(qh, kh, vh, mesh, seq_axis, spec,
                                     sp, scale, causal, interpret)[0]


def _ring_flash_trainable_fwd(qh, kh, vh, mesh, seq_axis, spec, sp,
                              scale, causal, interpret):
    out, lse = _shard_map(
        functools.partial(_ring_flash_fwd_sharded, axis_name=seq_axis,
                          sp=sp, scale=scale, causal=causal,
                          interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, PartitionSpec(spec[0], spec[2], seq_axis)),
        check_vma=False,
    )(qh, kh, vh)
    return out, (qh, kh, vh, out, lse)


def _ring_flash_trainable_bwd(mesh, seq_axis, spec, sp, scale, causal,
                              interpret, res, dout):
    qh, kh, vh, out, lse = res
    lse_spec = PartitionSpec(spec[0], spec[2], seq_axis)
    dq, dk, dv = _shard_map(
        functools.partial(_ring_flash_bwd_sharded, axis_name=seq_axis,
                          sp=sp, scale=scale, causal=causal,
                          interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, lse_spec, spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )(qh, kh, vh, out, lse, dout)
    return dq, dk, dv


_ring_flash_trainable.defvjp(_ring_flash_trainable_fwd,
                             _ring_flash_trainable_bwd)


def ring_attention(
    qh,
    kh,
    vh,
    mesh: Mesh,
    seq_axis: str,
    *,
    batch_spec=None,
    head_spec=None,
    scale: float = 1.0,
    causal: bool = False,
    block_impl: str = "auto",
    training: bool = False,
):
    """Sequence-parallel attention on [b, s, h, d] arrays whose s dim is
    sharded over `seq_axis`.  batch_spec/head_spec name the mesh axes (or
    None) sharding the batch/head dims, so the shard_map specs match the
    surrounding SPMD sharding.

    block_impl: "auto" routes rings whose shard shapes the Pallas
    kernels can tile through the FLASH ring — fully differentiable via
    the manual ring backward (_ring_flash_trainable), O(tile) VMEM
    score blocks, no [sq, sk] HBM tensor in either pass; causal rings
    qualify too when shards are square (self-attention: the diagonal
    step uses the kernel's static causal mask, off-diagonal steps gate
    a traced visibility bit).  Everything else takes the dense jax-op
    path.  "dense" forces the dense path; "flash" forces the flash
    ring (raises when unsupported; interpret-mode off-TPU for tests).
    `training` is accepted for call-site symmetry but both paths
    differentiate."""
    sp = mesh.shape[seq_axis]
    spec = PartitionSpec(batch_spec, seq_axis, head_spec, None)
    if causal and qh.shape[1] != kh.shape[1]:
        # causal flash needs square diagonal blocks (self-attention)
        if block_impl == "flash":
            raise ValueError(
                "block_impl='flash' causal rings need equal q/k seq "
                f"lengths, got {qh.shape[1]}/{kh.shape[1]}")
        flash = False
    else:
        flash = _use_flash_blocks(qh, kh, sp, block_impl)
    if flash:
        return _ring_flash_trainable(
            qh, kh, vh, mesh, seq_axis, spec, sp, float(scale),
            bool(causal), jax.default_backend() != "tpu",
        )
    fn = functools.partial(
        _ring_attention_sharded,
        axis_name=seq_axis,
        sp=sp,
        scale=scale,
        causal=causal,
    )
    return _shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(qh, kh, vh)
