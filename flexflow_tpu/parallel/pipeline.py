"""Pipeline parallelism (first-class, TPU-native).

The reference has only vestigial pipeline hooks — PIPELINE_INIT/FWD/BWD
task IDs exist (include/flexflow/model.h:190-192) but no pipeline op is
implemented; SURVEY §2.3 directs this build to treat PP as a
build-fresh strategy.  TPU-native design (the scaling-book recipe):

* mesh axis ``pp`` holds the stages; each device owns a contiguous
  chunk of identical blocks, stacked on a leading dim and sharded over
  ``pp`` (homogeneous-stage pipelining — the transformer case);
* the GPipe schedule is a ``lax.scan`` over ``M + S - 1`` ticks inside
  ``shard_map``: every tick each stage runs its block chunk, then
  ``lax.ppermute`` shifts activations one stage forward over ICI;
* the *backward* pipeline is not hand-written: ``jax.grad`` through the
  scan + ppermute emits the reverse schedule (ppermute transposes to
  the opposite shift) automatically — the functional-autodiff win over
  the reference's task-based design.

All-stages-equal SPMD means invalid ticks (pipeline fill/drain) compute
garbage that is masked, costing the standard bubble fraction
(S-1)/(M+S-1).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def gpipe(
    stage_fn: Callable,
    stage_params,
    x_mb: jax.Array,
    *,
    axis_name: str = "pp",
    num_stages: int,
    num_microbatches: int,
):
    """GPipe forward over one pipeline group.  Call INSIDE shard_map.

    stage_fn(stage_params, act) -> act: this device's stage (shape
    preserved — homogeneous stages).
    stage_params: the local stage's parameters (already pp-sharded).
    x_mb: [M, mb, ...] microbatched input (read on stage 0; other
    stages may hold anything of the same shape).
    Returns [M, mb, ...] outputs, broadcast to every stage of the group.
    """
    S, M = num_stages, num_microbatches
    stage = lax.axis_index(axis_name)
    zero = jnp.zeros_like(x_mb[0])

    def tick(buf, t):
        # stage 0 consumes microbatch t (clipped; masked when t >= M)
        x_t = jnp.take(x_mb, jnp.minimum(t, M - 1), axis=0)
        x_t = jnp.where(t < M, x_t, zero)
        inp = jnp.where(stage == 0, x_t, buf)
        y = stage_fn(stage_params, inp)
        nxt = lax.ppermute(y, axis_name, [(i, (i + 1) % S) for i in range(S)])
        return nxt, y

    _, ys = lax.scan(tick, zero, jnp.arange(M + S - 1))
    outs = ys[S - 1:]  # [M, mb, ...]; real values live on the last stage
    # where-mask (not multiply) so NaN/inf from fill/drain garbage ticks
    # on earlier stages cannot leak through the psum broadcast
    outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis_name)  # broadcast to the group


def _split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches {num_microbatches}"
        )
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def pipelined_apply(
    block_fn: Callable,
    stacked_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    pp_axis: str = "pp",
    dp_axis: str = "data",
    remat: bool = False,
):
    """Apply a stack of identical blocks as a dp x pp pipelined SPMD
    computation.

    block_fn(params_i, act) -> act: ONE block (e.g. a transformer
    layer).  stacked_params: pytree with leading dim L = num blocks,
    sharded over ``pp`` (L % pp == 0).  x: [batch, ...] sharded over
    ``data``.  Differentiable end to end.

    remat=True checkpoints each block: autodiff through the schedule
    then stores only per-(tick, block) boundary activations instead of
    every block's internals (attention scores, ffn hiddens) for every
    in-flight microbatch — the activation-memory lever that lets deep
    pipelines raise num_microbatches (smaller bubble) without raising
    peak HBM.  Same schedule, same collectives; backward recomputes
    block internals (the standard TPU pipeline recipe — an interleaved
    1F1B would cap in-flight microbatches at S instead of M but costs
    ~2x compute under lockstep SPMD masking, a bad trade here).
    """
    pp = mesh.shape[pp_axis]
    layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if layers % pp:
        raise ValueError(f"{layers} blocks not divisible by pp={pp}")
    body_block = jax.checkpoint(block_fn) if remat else block_fn

    def stage_fn(local_params, act):
        # run this stage's L/pp blocks in order
        def body(a, p):
            return body_block(p, a), None

        out, _ = lax.scan(body, act, local_params)
        return out

    def spmd(params, xb):
        x_mb = _split_microbatches(xb, num_microbatches)
        y_mb = gpipe(stage_fn, params, x_mb, axis_name=pp_axis,
                     num_stages=pp, num_microbatches=num_microbatches)
        return y_mb.reshape((-1,) + y_mb.shape[2:])

    param_specs = jax.tree.map(
        lambda a: P(pp_axis, *([None] * (a.ndim - 1))), stacked_params
    )
    in_x = P(dp_axis, *([None] * (x.ndim - 1)))
    return jax.shard_map(
        spmd,
        mesh=mesh,
        in_specs=(param_specs, in_x),
        out_specs=in_x,
        check_vma=False,
    )(stacked_params, x)


def stacked_param_sharding(mesh: Mesh, a, pp_axis: str = "pp"):
    """NamedSharding for a [L, ...] stacked block-parameter array."""
    return NamedSharding(mesh, P(pp_axis, *([None] * (a.ndim - 1))))


# ----------------------------------------------------------------------
# Reference-parity demo model: a pipelined transformer-encoder train
# step used by tests and the driver's multichip dryrun.
# ----------------------------------------------------------------------

def _init_block_params(key, layers, hidden, ffn, dtype=jnp.float32):
    ks = jax.random.split(key, 4)
    scale = 1.0 / jnp.sqrt(hidden)
    return {
        "w_qkv": jax.random.normal(ks[0], (layers, hidden, 3 * hidden), dtype) * scale,
        "w_o": jax.random.normal(ks[1], (layers, hidden, hidden), dtype) * scale,
        "w_in": jax.random.normal(ks[2], (layers, hidden, ffn), dtype) * scale,
        "w_out": jax.random.normal(ks[3], (layers, ffn, hidden), dtype) * scale,
    }


def _encoder_block(p, x, *, num_heads: int):
    b, s, h = x.shape
    hd = h // num_heads
    qkv = x @ p["w_qkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, num_heads, hd).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    a = jax.nn.softmax(q @ k.transpose(0, 1, 3, 2) / jnp.sqrt(hd), axis=-1)
    o = (a @ v).transpose(0, 2, 1, 3).reshape(b, s, h)
    x = _ln(x + o @ p["w_o"])
    y = jax.nn.relu(x @ p["w_in"]) @ p["w_out"]
    return _ln(x + y)


def _ln(x, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps)


def make_pipelined_transformer_step(
    mesh: Mesh,
    *,
    layers: int,
    hidden: int,
    ffn: int,
    num_heads: int,
    num_classes: int,
    num_microbatches: int,
    lr: float = 0.01,
    pp_axis: str = "pp",
    dp_axis: str = "data",
):
    """(init_fn, step_fn): a full SGD train step (fwd+loss+bwd+update)
    for a block-stacked encoder pipelined over `pp` and batch-sharded
    over `data`."""

    def init_fn(seed: int):
        key = jax.random.key(seed)
        kb, kh = jax.random.split(key)
        params = {
            "blocks": _init_block_params(kb, layers, hidden, ffn),
            "head": jax.random.normal(kh, (hidden, num_classes)) / jnp.sqrt(hidden),
        }
        shardings = {
            "blocks": jax.tree.map(
                lambda a: stacked_param_sharding(mesh, a, pp_axis),
                params["blocks"],
            ),
            "head": NamedSharding(mesh, P(None, None)),
        }
        return jax.tree.map(jax.device_put, params, shardings)

    block = functools.partial(_encoder_block, num_heads=num_heads)

    def loss_fn(params, x, y):
        h = pipelined_apply(block, params["blocks"], x, mesh=mesh,
                            num_microbatches=num_microbatches,
                            pp_axis=pp_axis, dp_axis=dp_axis)
        logits = h.mean(axis=1) @ params["head"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    @jax.jit
    def step_fn(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return init_fn, step_fn
