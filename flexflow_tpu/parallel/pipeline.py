"""Pipeline parallelism (first-class, TPU-native).

The reference has only vestigial pipeline hooks — PIPELINE_INIT/FWD/BWD
task IDs exist (include/flexflow/model.h:190-192) but no pipeline op is
implemented; SURVEY §2.3 directs this build to treat PP as a
build-fresh strategy.  TPU-native design (the scaling-book recipe):

* mesh axis ``pp`` holds the stages; each device owns a contiguous
  chunk of identical blocks, stacked on a leading dim and sharded over
  ``pp`` (homogeneous-stage pipelining — the transformer case);
* the GPipe schedule is a ``lax.scan`` over ``M + S - 1`` ticks inside
  ``shard_map``: every tick each stage runs its block chunk, then
  ``lax.ppermute`` shifts activations one stage forward over ICI;
* the *backward* pipeline is not hand-written: ``jax.grad`` through the
  scan + ppermute emits the reverse schedule (ppermute transposes to
  the opposite shift) automatically — the functional-autodiff win over
  the reference's task-based design.

All-stages-equal SPMD means invalid ticks (pipeline fill/drain) compute
garbage that is masked, costing the standard bubble fraction
(S-1)/(M+S-1).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .shard_map_compat import shard_map as _shard_map


def gpipe(
    stage_fn: Callable,
    stage_params,
    x_mb: jax.Array,
    *,
    axis_name: str = "pp",
    num_stages: int,
    num_microbatches: int,
):
    """GPipe forward over one pipeline group.  Call INSIDE shard_map.

    stage_fn(stage_params, act) -> act: this device's stage (shape
    preserved — homogeneous stages).
    stage_params: the local stage's parameters (already pp-sharded).
    x_mb: [M, mb, ...] microbatched input (read on stage 0; other
    stages may hold anything of the same shape).
    Returns [M, mb, ...] outputs, broadcast to every stage of the group.
    """
    S, M = num_stages, num_microbatches
    stage = lax.axis_index(axis_name)
    zero = jnp.zeros_like(x_mb[0])

    def tick(buf, t):
        # stage 0 consumes microbatch t (clipped; masked when t >= M)
        x_t = jnp.take(x_mb, jnp.minimum(t, M - 1), axis=0)
        x_t = jnp.where(t < M, x_t, zero)
        inp = jnp.where(stage == 0, x_t, buf)
        y = stage_fn(stage_params, inp)
        nxt = lax.ppermute(y, axis_name, [(i, (i + 1) % S) for i in range(S)])
        return nxt, y

    _, ys = lax.scan(tick, zero, jnp.arange(M + S - 1))
    outs = ys[S - 1:]  # [M, mb, ...]; real values live on the last stage
    # where-mask (not multiply) so NaN/inf from fill/drain garbage ticks
    # on earlier stages cannot leak through the psum broadcast
    outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis_name)  # broadcast to the group


def one_f_one_b(
    stage_fn: Callable,
    stage_params,
    x_mb: jax.Array,
    last_fn: Callable,
    last_params,
    targets_mb: jax.Array,
    *,
    axis_name: str = "pp",
    num_stages: int,
    num_microbatches: int,
):
    """1F1B schedule over one pipeline group: forward and backward
    interleave, capping in-flight saved activations at O(S) per stage
    instead of GPipe's O(M).  Call INSIDE shard_map.

    Unlike `gpipe` (plain forward; jax.grad derives the reverse
    schedule), 1F1B cannot be expressed through outer autodiff — the
    whole point is running microbatch j's backward before microbatch
    j+k's forward — so this function computes the gradients ITSELF with
    per-tick jax.vjp and returns them.  The loss head must live on the
    last stage (that is what lets cotangents exist mid-schedule):

      stage_fn(stage_params, act) -> act        homogeneous block chunk
      last_fn(last_params, act, target) -> loss  one microbatch's head+loss

    Timing (lockstep SPMD, everything masked): stage s runs microbatch
    f's forward at tick s+f and microbatch j's backward at tick
    2(S-1)-s+j; the last stage's backward of mb j lands the same tick
    as its forward, the classic 1F1B cadence.  Saved boundary
    activations live in a [2S-1]-slot ring (residency 2(S-1-s) ticks).
    Total ticks M+2S-2 vs GPipe's 2(M+S-1) fwd+bwd — same steady-state
    compute (each tick does one fwd + one vjp), 2(S-1) extra warmup/
    drain tick-halves, O(S/M) of the schedule.

    Returns (mean loss, stage_params grads, last_params grads) — loss
    and last-grads are psum-broadcast to the group; stage grads are the
    LOCAL stage's (pp-sharded like stage_params).
    """
    S, M = num_stages, num_microbatches
    R = 2 * S - 1  # ring slots: max residency + 1
    stage = lax.axis_index(axis_name)
    zero_act = jnp.zeros_like(x_mb[0])
    zero_tgt = jnp.zeros_like(targets_mb[0])

    def masked_add(acc, upd, valid):
        return jax.tree.map(
            lambda a, u: a + jnp.where(valid, u, jnp.zeros_like(u)), acc, upd
        )

    def tick(carry, t):
        fwd_buf, bwd_buf, ring, g_stage, g_last, loss_acc = carry
        # ---- forward half: stage s runs microbatch f = t - s --------
        f = t - stage
        valid_f = (f >= 0) & (f < M)
        x_t = jnp.take(x_mb, jnp.clip(f, 0, M - 1), axis=0)
        a_in = jnp.where(stage == 0, x_t, fwd_buf)
        a_in = jnp.where(valid_f, a_in, zero_act)
        y = stage_fn(stage_params, a_in)
        ring = ring.at[t % R].set(jnp.where(valid_f, a_in, ring[t % R]))
        # last stage: this microbatch's head + loss, cotangent NOW
        tgt = jnp.take(targets_mb, jnp.clip(f, 0, M - 1), axis=0)
        tgt = jnp.where(valid_f, tgt, zero_tgt)
        loss_f, head_vjp = jax.vjp(
            lambda lp, a: last_fn(lp, a, tgt), last_params, y
        )
        d_last, dy_here = head_vjp(jnp.ones_like(loss_f) / M)
        is_last = stage == S - 1
        loss_acc = loss_acc + jnp.where(is_last & valid_f, loss_f / M, 0.0)
        g_last = masked_add(g_last, d_last, is_last & valid_f)
        # ---- backward half: stage s runs microbatch j ---------------
        j = t - (2 * (S - 1) - stage)
        valid_b = (j >= 0) & (j < M)
        a_saved = ring[(stage + j) % R]
        dy = jnp.where(is_last, dy_here, bwd_buf)
        dy = jnp.where(valid_b, dy, zero_act)
        _, stage_vjp = jax.vjp(stage_fn, stage_params, a_saved)
        d_stage, dx = stage_vjp(dy)
        g_stage = masked_add(g_stage, d_stage, valid_b)
        # ---- shift: activations right, cotangents left --------------
        fwd_buf = lax.ppermute(
            y, axis_name, [(i, (i + 1) % S) for i in range(S)]
        )
        bwd_buf = lax.ppermute(
            dx, axis_name, [(i, (i - 1) % S) for i in range(S)]
        )
        return (fwd_buf, bwd_buf, ring, g_stage, g_last, loss_acc), None

    ring0 = jnp.zeros((R,) + x_mb.shape[1:], x_mb.dtype)
    g_stage0 = jax.tree.map(jnp.zeros_like, stage_params)
    g_last0 = jax.tree.map(jnp.zeros_like, last_params)
    carry = (zero_act, zero_act, ring0, g_stage0, g_last0, jnp.zeros(()))
    carry, _ = lax.scan(tick, carry, jnp.arange(M + 2 * S - 2))
    _, _, _, g_stage, g_last, loss = carry
    # loss/head grads were accumulated on the last stage only
    return (
        lax.psum(loss, axis_name),
        g_stage,
        jax.tree.map(lambda g: lax.psum(g, axis_name), g_last),
    )


def _split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches {num_microbatches}"
        )
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def pipelined_apply(
    block_fn: Callable,
    stacked_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    pp_axis: str = "pp",
    dp_axis: str = "data",
    remat: bool = False,
):
    """Apply a stack of identical blocks as a dp x pp pipelined SPMD
    computation.

    block_fn(params_i, act) -> act: ONE block (e.g. a transformer
    layer).  stacked_params: pytree with leading dim L = num blocks,
    sharded over ``pp`` (L % pp == 0).  x: [batch, ...] sharded over
    ``data``.  Differentiable end to end.

    remat=True checkpoints each block: autodiff through the schedule
    then stores only per-(tick, block) boundary activations instead of
    every block's internals (attention scores, ffn hiddens) for every
    in-flight microbatch — the activation-memory lever that lets deep
    pipelines raise num_microbatches (smaller bubble) without raising
    peak HBM.  Same schedule, same collectives; backward recomputes
    block internals.  Boundary storage still grows O(M); when that is
    the binding constraint, `one_f_one_b` caps residency at O(S)
    (measured: temp bytes flat in M vs linear here — docs/PERF.md).
    """
    pp = mesh.shape[pp_axis]
    layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if layers % pp:
        raise ValueError(f"{layers} blocks not divisible by pp={pp}")
    stage_fn = _make_stage_fn(block_fn, remat)

    def spmd(params, xb):
        x_mb = _split_microbatches(xb, num_microbatches)
        y_mb = gpipe(stage_fn, params, x_mb, axis_name=pp_axis,
                     num_stages=pp, num_microbatches=num_microbatches)
        return y_mb.reshape((-1,) + y_mb.shape[2:])

    param_specs = jax.tree.map(
        lambda a: P(pp_axis, *([None] * (a.ndim - 1))), stacked_params
    )
    in_x = P(dp_axis, *([None] * (x.ndim - 1)))
    return _shard_map(
        spmd,
        mesh=mesh,
        in_specs=(param_specs, in_x),
        out_specs=in_x,
        check_vma=False,
    )(stacked_params, x)


def _make_stage_fn(block_fn: Callable, remat: bool) -> Callable:
    """One stage = scan over this device's local block chunk (shared by
    the GPipe and 1F1B schedules so their numerics cannot diverge)."""
    body_block = jax.checkpoint(block_fn) if remat else block_fn

    def stage_fn(local_params, act):
        def body(a, p):
            return body_block(p, a), None

        out, _ = lax.scan(body, act, local_params)
        return out

    return stage_fn


def stacked_param_sharding(mesh: Mesh, a, pp_axis: str = "pp"):
    """NamedSharding for a [L, ...] stacked block-parameter array."""
    return NamedSharding(mesh, P(pp_axis, *([None] * (a.ndim - 1))))


# ----------------------------------------------------------------------
# Reference-parity demo model: a pipelined transformer-encoder train
# step used by tests and the driver's multichip dryrun.
# ----------------------------------------------------------------------

def _init_block_params(key, layers, hidden, ffn, dtype=jnp.float32):
    ks = jax.random.split(key, 4)
    scale = 1.0 / jnp.sqrt(hidden)
    return {
        "w_qkv": jax.random.normal(ks[0], (layers, hidden, 3 * hidden), dtype) * scale,
        "w_o": jax.random.normal(ks[1], (layers, hidden, hidden), dtype) * scale,
        "w_in": jax.random.normal(ks[2], (layers, hidden, ffn), dtype) * scale,
        "w_out": jax.random.normal(ks[3], (layers, ffn, hidden), dtype) * scale,
    }


def _encoder_block(p, x, *, num_heads: int):
    b, s, h = x.shape
    hd = h // num_heads
    qkv = x @ p["w_qkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, num_heads, hd).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    a = jax.nn.softmax(q @ k.transpose(0, 1, 3, 2) / jnp.sqrt(hd), axis=-1)
    o = (a @ v).transpose(0, 2, 1, 3).reshape(b, s, h)
    x = _ln(x + o @ p["w_o"])
    y = jax.nn.relu(x @ p["w_in"]) @ p["w_out"]
    return _ln(x + y)


def _ln(x, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps)


def make_pipelined_transformer_step(
    mesh: Mesh,
    *,
    layers: int,
    hidden: int,
    ffn: int,
    num_heads: int,
    num_classes: int,
    num_microbatches: int,
    lr: float = 0.01,
    pp_axis: str = "pp",
    dp_axis: str = "data",
    schedule: str = "gpipe",
    remat: bool = False,
):
    """(init_fn, step_fn): a full SGD train step (fwd+loss+bwd+update)
    for a block-stacked encoder pipelined over `pp` and batch-sharded
    over `data`.

    schedule: "gpipe" (forward scan, jax.grad derives the reverse
    schedule; O(M) saved boundaries, remat=True shrinks each to the
    block boundary) or "1f1b" (interleaved fwd/bwd via `one_f_one_b`;
    O(S) in-flight activations — the deep-pipeline memory lever).
    Both compute identical gradients (test_pipeline.py asserts it)."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    pp = mesh.shape[pp_axis]
    if layers % pp:
        raise ValueError(f"{layers} blocks not divisible by pp={pp}")

    def init_fn(seed: int):
        key = jax.random.key(seed)
        kb, kh = jax.random.split(key)
        params = {
            "blocks": _init_block_params(kb, layers, hidden, ffn),
            "head": jax.random.normal(kh, (hidden, num_classes)) / jnp.sqrt(hidden),
        }
        shardings = {
            "blocks": jax.tree.map(
                lambda a: stacked_param_sharding(mesh, a, pp_axis),
                params["blocks"],
            ),
            "head": NamedSharding(mesh, P(None, None)),
        }
        return jax.tree.map(jax.device_put, params, shardings)

    block = functools.partial(_encoder_block, num_heads=num_heads)

    def loss_fn(params, x, y):
        h = pipelined_apply(block, params["blocks"], x, mesh=mesh,
                            num_microbatches=num_microbatches,
                            pp_axis=pp_axis, dp_axis=dp_axis, remat=remat)
        logits = h.mean(axis=1) @ params["head"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    @jax.jit
    def gpipe_step(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    # ---- 1f1b: grads computed inside the schedule ---------------------
    stage_fn = _make_stage_fn(block, remat)

    def last_fn(head, act, tgt):
        logits = act.mean(axis=1) @ head
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, tgt[:, None], axis=-1).mean()

    def spmd_1f1b(params, x, y):
        x_mb = _split_microbatches(x, num_microbatches)
        y_mb = _split_microbatches(y, num_microbatches)
        loss, g_blocks, g_head = one_f_one_b(
            stage_fn, params["blocks"], x_mb, last_fn, params["head"],
            y_mb, axis_name=pp_axis, num_stages=pp,
            num_microbatches=num_microbatches,
        )
        # dp: average grads (and loss) over the data axis
        dp = mesh.shape.get(dp_axis, 1)
        if dp > 1:
            g_blocks = jax.tree.map(
                lambda g: lax.pmean(g, dp_axis), g_blocks)
            g_head = jax.tree.map(lambda g: lax.pmean(g, dp_axis), g_head)
            loss = lax.pmean(loss, dp_axis)
        return loss, {"blocks": g_blocks, "head": g_head}

    block_shapes = jax.eval_shape(
        lambda: _init_block_params(jax.random.key(0), layers, hidden, ffn)
    )
    block_specs = jax.tree.map(lambda _: P(pp_axis, None, None),
                               block_shapes)
    param_specs = {"blocks": block_specs, "head": P(None, None)}
    in_x, in_y = P(dp_axis, None, None), P(dp_axis)

    @jax.jit
    def ofob_step(params, x, y):
        loss, grads = _shard_map(
            spmd_1f1b, mesh=mesh,
            in_specs=(param_specs, in_x, in_y),
            out_specs=(P(), param_specs),
            check_vma=False,
        )(params, x, y)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return init_fn, (gpipe_step if schedule == "gpipe" else ofob_step)
