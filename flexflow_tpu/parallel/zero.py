"""ZeRO ladder spec helpers (the scattered update/resident layout).

References: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., arXiv:2004.13336 — stage 1) and
"ZeRO: Memory Optimizations Toward Training Trillion Parameter Models"
(Rajbhandari et al., arXiv:1910.02054 — stages 2/3).  On data-parallel
legs every replica redundantly runs the full optimizer update and keeps
full replicated copies of grads, slots (Adam m/v) and master weights.
The ladder sheds them rung by rung, all expressed through ONE scattered
layout (this module's spec arithmetic):

  * stage 1 — reduce-scatter the gradient over the replica (wus) axis,
    update a 1/N shard of the weight + slots (slots live scattered
    permanently — 1/N per-device HBM), all-gather the updated weights
    back to their strategy sharding;
  * stage 2 — the gradient BUFFER also lives scattered through the
    update (grad HBM / N; executor.grad_shardings);
  * stage 3 — master weights live scattered too (weight-resident
    HBM / N; executor.master_weight_shardings), gathered
    just-in-time per layer on use with double-buffered prefetch — the
    post-update all-gather disappears.

At stage 1 total ring bytes equal the all-reduce the replicated path
pays (all-reduce == reduce-scatter + all-gather); stage 3 trades extra
per-layer gather traffic for the resident-memory drop — the simulator
costs every rung so the search picks the trade-off per model
(sim/simulator.py zero_stage).  The executor expresses all of it with
`with_sharding_constraint` re-specs — XLA SPMD then emits the
reduce-scatter/all-gather collectives — so the update body itself
stays the plain functional optimizer.

This module owns the spec arithmetic: given a weight's strategy
PartitionSpec, fold the wus axis into its first free, evenly-divisible
logical dim.  Weights with no such dim (a 10-way bias on an 8-way axis)
keep their strategy sharding and fall back to the replicated update —
per leaf, not per model (counted and logged:
executor.zero_fallback_leaves -> parallel/zero_fallback_leaves).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _used_axes(spec: PartitionSpec):
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            yield from entry
        else:
            yield entry


def shard_update_spec(
    spec: PartitionSpec,
    shape: Sequence[int],
    axis: str,
    axis_size: int,
) -> Optional[PartitionSpec]:
    """The ZeRO-1 update-layout spec for one weight, or None when the
    weight cannot shard over `axis` (axis already used, no free dim
    whose size divides evenly, or a trivial axis)."""
    if axis_size <= 1 or axis in set(_used_axes(spec)):
        return None
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, dim in enumerate(shape):
        if entries[i] is None and dim % axis_size == 0 and dim > 0:
            entries[i] = axis
            return PartitionSpec(*entries)
    return None


def shard_update_sharding(
    sharding: NamedSharding,
    shape: Sequence[int],
    mesh: Mesh,
    axis: str,
) -> NamedSharding:
    """NamedSharding for the update layout; the strategy sharding when
    the leaf cannot shard."""
    sizes: Dict[str, int] = dict(zip(mesh.axis_names, mesh.devices.shape))
    z = shard_update_spec(sharding.spec, shape, axis, sizes.get(axis, 1))
    return sharding if z is None else NamedSharding(mesh, z)
