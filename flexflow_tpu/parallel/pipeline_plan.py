"""Pipeline-parallel strategy planning: PCG -> (prefix, blocks, suffix).

The reference treats pipeline parallelism as a to-build-fresh strategy
(vestigial PIPELINE_INIT/FWD/BWD task ids, model.h:190-192; SURVEY
§2.3).  Here a `Strategy.pipeline` entry makes PP first-class: this
module validates and plans the lowering of a strategy-annotated PCG
onto `parallel/pipeline.py`'s GPipe schedule —

  * `find_repeated_blocks` (pcg/segments.py) locates the homogeneous
    block stack (e.g. a transformer's encoder layers);
  * the plan splits the topo order into prefix ops (run normally,
    replicated over the pp axis), the pipelined region (blocks stacked
    on a leading dim, sharded over `pp`, executed via `pipelined_apply`
    inside shard_map with per-tick ppermute over ICI), and suffix ops;
  * validation rejects regions the GPipe schedule cannot host: stateful
    ops (BatchNorm running stats), aux-loss ops (MoE load balance),
    non-trivial ShardConfigs (tp-inside-pp is a later extension), and
    microbatch counts that don't divide the per-dp-shard batch.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..fftype import OperatorType
from ..ops.op import Op, trainable_weight_count
from ..pcg.graph import Graph
from ..pcg.segments import find_repeated_blocks

#: op types whose forward has side state/aux the scanned block body
#: cannot thread (BatchNorm is excluded by the state check already)
_EXCLUDED_TYPES = {
    OperatorType.CACHE,
    OperatorType.GROUP_BY,
    OperatorType.AGGREGATE,
    OperatorType.AGGREGATE_SPEC,
}


@dataclasses.dataclass
class PipelinePlan:
    prefix: List[Op]
    blocks: List[List[Op]]  # L homogeneous blocks, topo order each
    suffix: List[Op]
    region_in_guid: int   # tensor entering block 0 == the template
    #                       block's external input (single by validation)
    region_out_guid: int  # tensor leaving the last block
    template_out_guid: int  # block 0's boundary-output tensor guid
    num_stages: int
    num_microbatches: int
    pp_axis: str
    dp_axis: Optional[str]

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)


def plan_pipeline(
    graph: Graph, pipeline: Dict, mesh_axes: Dict[str, int]
) -> PipelinePlan:
    """Build and validate the pipeline plan for a strategy-applied PCG.

    pipeline: {"degree": S, "num_microbatches": M, "axis": "pipe",
    "dp_axis": "data"|None} — the Strategy.pipeline payload."""
    S = int(pipeline["degree"])
    M = int(pipeline["num_microbatches"])
    pp_axis = pipeline.get("axis", "pipe")
    dp_axis = pipeline.get("dp_axis")
    if pp_axis not in mesh_axes or mesh_axes[pp_axis] != S:
        raise ValueError(
            f"pipeline degree {S} does not match mesh axis "
            f"{pp_axis!r}={mesh_axes.get(pp_axis)}"
        )
    if dp_axis is not None and dp_axis not in mesh_axes:
        raise ValueError(f"pipeline dp_axis {dp_axis!r} not in mesh")
    blocks = find_repeated_blocks(graph)
    L = len(blocks)
    if L < 2:
        raise ValueError(
            "no repeated homogeneous block stack found to pipeline "
            "(need >= 2 structurally identical single-tensor-boundary "
            "blocks)"
        )
    if L % S != 0:
        raise ValueError(f"{L} blocks not divisible by pipeline degree {S}")

    block_guids = {op.guid for blk in blocks for op in blk}
    for blk in blocks:
        for op in blk:
            if op.op_type in _EXCLUDED_TYPES:
                raise ValueError(
                    f"op {op.name} ({op.op_type.value}) cannot run inside "
                    f"a pipelined block"
                )
            if trainable_weight_count(op) != len(op.weight_specs):
                raise ValueError(
                    f"stateful op {op.name} cannot run inside a pipelined "
                    f"block (running stats don't thread through the GPipe "
                    f"scan)"
                )
            if not op.shard.is_trivial():
                raise ValueError(
                    f"op {op.name} has a non-trivial ShardConfig; "
                    f"tensor parallelism inside pipeline stages is not "
                    f"supported"
                )

    topo = graph.topo_order()
    first_pos = min(i for i, op in enumerate(topo) if op.guid in block_guids)
    last_pos = max(i for i, op in enumerate(topo) if op.guid in block_guids)
    prefix = [op for op in topo[:first_pos]]
    suffix = [op for op in topo[last_pos + 1:]]
    interleaved = [
        op for op in topo[first_pos:last_pos + 1] if op.guid not in block_guids
    ]
    if interleaved:
        raise ValueError(
            f"ops interleaved with the pipelined region: "
            f"{[op.name for op in interleaved]}"
        )

    def external_in(blk: List[Op]) -> int:
        from ..pcg.segments import external_inputs

        ext = external_inputs(blk)
        if len(ext) != 1:
            raise ValueError(
                f"pipelined block has {len(ext)} external inputs, need 1"
            )
        return ext[0]

    region_in = external_in(blocks[0])
    template_out = external_in(blocks[1])  # block0's boundary output
    produced_last = [t.guid for op in blocks[-1] for t in op.outputs]
    if suffix:
        consumed_by_suffix = {t.guid for op in suffix for t in op.inputs}
        region_out = [g for g in produced_last if g in consumed_by_suffix]
    else:
        consumed = {t.guid for op in graph.ops for t in op.inputs}
        region_out = [g for g in produced_last if g not in consumed]
    if len(region_out) != 1:
        raise ValueError(
            f"pipelined region must hand exactly one tensor to the "
            f"suffix, found {len(region_out)}"
        )

    # microbatch divisibility on the per-dp-shard batch
    in_t = None
    for op in graph.ops:
        for t in op.outputs:
            if t.guid == region_in:
                in_t = t
    assert in_t is not None
    b = in_t.shape.logical_shape[0]
    dp = mesh_axes.get(dp_axis, 1) if dp_axis else 1
    if b % dp or (b // dp) % M:
        raise ValueError(
            f"batch {b} not divisible by dp={dp} x microbatches={M}"
        )
    return PipelinePlan(
        prefix=prefix,
        blocks=blocks,
        suffix=suffix,
        region_in_guid=region_in,
        region_out_guid=region_out[0],
        template_out_guid=template_out,
        num_stages=S,
        num_microbatches=M,
        pp_axis=pp_axis,
        dp_axis=dp_axis,
    )
