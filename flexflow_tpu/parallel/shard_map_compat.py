"""shard_map across jax versions.

`jax.shard_map` is the stable top-level API on newer jax; on older
releases (e.g. the 0.4.x line) it lives at
`jax.experimental.shard_map.shard_map` with `check_rep` in place of
`check_vma`.  Every shard_map lowering in this repo (GPipe pipeline,
ring/flash attention) routes through this one shim so the kernels run
on whichever jax the host ships instead of dying on an AttributeError.
"""
from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy

    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
