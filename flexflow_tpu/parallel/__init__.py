from .machine import MachineView, assign_axes, make_mesh, view_to_sharding, view_to_spec
