"""Parallelization operators — the parallelism IR of the PCG.

Reference: src/parallel_ops/{partition,combine,replicate,reduction,
fused_parallel_op}.cc — four primitives whose Legion partition copies
perform all inter-device data movement (SURVEY §2.3).  TPU-first: these
ops are **semantic identities** on the global logical array; what they
change is the tensor's parallel shape (degrees/replica dims).  Lowering
(view assignment in flexflow_tpu/parallel/machine.py, applied by the
executor) realizes each as a `lax.with_sharding_constraint` boundary,
so XLA SPMD emits the actual collective:

  Repartition -> sharding change (slice/all-to-all as needed)
  Combine     -> all-gather on the combined dim
  Replicate   -> broadcast (all-gather of the replica axis)
  Reduction   -> psum of partial sums (XLA inserts it when the producer's
                 contraction dim was sharded)
  AllToAll    -> degree moved between dims (Ulysses-style resharding) —
                 a TPU-native addition the reference lacks; lowers to an
                 ICI all-to-all.

There is deliberately no per-op communication code here — that is the
entire point of the XLA SPMD design (SURVEY §2.4 "TPU equivalent").
"""
from __future__ import annotations

import dataclasses
from typing import List, Tuple

from ..fftype import OperatorType
from ..ops.op import Op, ShapeError
from ..tensor import ParallelDim, ParallelTensorShape


def _replace_dim(shape: ParallelTensorShape, logical_idx: int, new: ParallelDim):
    dims = []
    li = 0
    for d in shape.dims:
        if d.is_replica_dim:
            dims.append(d)
        else:
            dims.append(new if li == logical_idx else d)
            li += 1
    return ParallelTensorShape(tuple(dims), shape.dtype)


def _with_replica(shape: ParallelTensorShape, degree: int):
    dims = tuple(
        dataclasses.replace(d, degree=degree) if d.is_replica_dim else d
        for d in shape.dims
    )
    return ParallelTensorShape(dims, shape.dtype)


class ParallelOpBase(Op):
    def forward(self, inputs, weights, *, training=False, rng=None):
        return [inputs[0]]


@dataclasses.dataclass(frozen=True)
class RepartitionParams:
    dim: int
    degree: int


class Repartition(ParallelOpBase):
    op_type = OperatorType.REPARTITION

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: RepartitionParams = self.params
        dd = [d for d in ishape.dims if not d.is_replica_dim]
        dim = dd[p.dim % len(dd)]
        new_degree = dim.degree * p.degree
        if dim.size % new_degree != 0:
            raise ShapeError(
                f"{self.name}: dim size {dim.size} not divisible by {new_degree}"
            )
        return [_replace_dim(ishape, p.dim % len(dd), dim.with_degree(new_degree))]


@dataclasses.dataclass(frozen=True)
class CombineParams:
    dim: int
    degree: int


class Combine(ParallelOpBase):
    op_type = OperatorType.COMBINE

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: CombineParams = self.params
        dd = [d for d in ishape.dims if not d.is_replica_dim]
        dim = dd[p.dim % len(dd)]
        if dim.degree % p.degree != 0:
            raise ShapeError(f"{self.name}: degree {dim.degree} not divisible by {p.degree}")
        return [
            _replace_dim(ishape, p.dim % len(dd), dim.with_degree(dim.degree // p.degree))
        ]


@dataclasses.dataclass(frozen=True)
class ReplicateParams:
    degree: int


class Replicate(ParallelOpBase):
    op_type = OperatorType.REPLICATE

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        return [_with_replica(ishape, ishape.replica_degree * self.params.degree)]


@dataclasses.dataclass(frozen=True)
class ReductionParams:
    degree: int


class Reduction(ParallelOpBase):
    op_type = OperatorType.REDUCTION

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        rd = ishape.replica_degree
        if rd % self.params.degree != 0:
            raise ShapeError(f"{self.name}: replica degree {rd} not divisible")
        return [_with_replica(ishape, rd // self.params.degree)]


@dataclasses.dataclass(frozen=True)
class AllToAllParams:
    from_dim: int  # dim currently sharded
    to_dim: int  # dim to move the degree onto
    degree: int


class AllToAll(ParallelOpBase):
    """Move `degree` of parallelism from one dim to another in one
    collective (Ulysses SP <-> TP head resharding; EP dispatch)."""

    op_type = OperatorType.ALLTOALL

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: AllToAllParams = self.params
        dd = [d for d in ishape.dims if not d.is_replica_dim]
        src = dd[p.from_dim % len(dd)]
        dst = dd[p.to_dim % len(dd)]
        if src.degree % p.degree != 0:
            raise ShapeError(f"{self.name}: from-dim degree {src.degree} not divisible")
        new_dst_degree = dst.degree * p.degree
        if dst.size % new_dst_degree != 0:
            raise ShapeError(f"{self.name}: to-dim size not divisible")
        s = _replace_dim(ishape, p.from_dim % len(dd), src.with_degree(src.degree // p.degree))
        return [_replace_dim(s, p.to_dim % len(dd), dst.with_degree(new_dst_degree))]


@dataclasses.dataclass(frozen=True)
class StackReplicateParams:
    axis: int  # row-major logical axis
    degree: int


class StackReplicate(Op):
    """`degree` copies of the input stacked (concatenated) along `axis`
    — the reference Replicate's actual logical semantics
    (replicate.cc:74-75: dims[replicate_dim].size *= degree).  A compute
    op, not a sharding annotation: when the stacked axis is sharded at
    `degree`, each shard holds one copy, which is physical replication.
    Used by the TASO substitution catalog (pcg/taso.py)."""

    op_type = OperatorType.REPLICATE_STACK

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: StackReplicateParams = self.params
        dd = [d for d in ishape.dims if not d.is_replica_dim]
        ax = p.axis % len(dd)
        dim = dd[ax]
        new_size = dim.size * p.degree
        if new_size % dim.degree != 0:
            raise ShapeError(f"{self.name}: stacked size not shardable")
        return [_replace_dim(ishape, ax, dataclasses.replace(dim, size=new_size))]

    def forward(self, inputs, weights, *, training=False, rng=None):
        import jax.numpy as jnp

        (x,) = inputs
        ax = self.params.axis % x.ndim
        return [jnp.concatenate([x] * self.params.degree, axis=ax)]

    def flops(self):
        return 0.0


@dataclasses.dataclass(frozen=True)
class FoldReduceParams:
    axis: int  # row-major logical axis
    degree: int


class FoldReduce(Op):
    """Sum of `degree` equal slices along `axis` — the reference
    Reduction's logical semantics (reduction.cc:74-77:
    dims[reduction_dim].size /= degree): partial sums laid out along a
    dim are folded.  Inverse-composes with StackReplicate and with
    Concat (a concat axis is a stack of partials — what lets the TASO
    catalog trade elementwise adds for concat+reduce)."""

    op_type = OperatorType.REDUCTION_FOLD

    def infer_output_shapes(self, input_shapes):
        (ishape,) = input_shapes
        p: FoldReduceParams = self.params
        dd = [d for d in ishape.dims if not d.is_replica_dim]
        ax = p.axis % len(dd)
        dim = dd[ax]
        if dim.size % p.degree != 0:
            raise ShapeError(f"{self.name}: size {dim.size} not divisible by fold {p.degree}")
        new_size = dim.size // p.degree
        if new_size % dim.degree != 0:
            raise ShapeError(f"{self.name}: folded size not shardable")
        return [_replace_dim(ishape, ax, dataclasses.replace(dim, size=new_size))]

    def forward(self, inputs, weights, *, training=False, rng=None):
        import jax.numpy as jnp

        (x,) = inputs
        p: FoldReduceParams = self.params
        ax = p.axis % x.ndim
        parts = jnp.split(x, p.degree, axis=ax)
        out = parts[0]
        for part in parts[1:]:
            out = out + part
        return [out]

    def flops(self):
        return float(self.inputs[0].shape.num_elements())


@dataclasses.dataclass(frozen=True)
class FusedParallelParams:
    ops: Tuple = ()  # tuple of (kind, params) pairs


class FusedParallelOp(ParallelOpBase):
    """A chain of parallel ops collapsed into one resharding boundary
    (reference fused_parallel_op.cc) — one constraint, one collective."""

    op_type = OperatorType.FUSED_PARALLEL

    _KINDS = None

    def infer_output_shapes(self, input_shapes):
        shape = input_shapes[0]
        for kind, params in self.params.ops:
            cls = PARALLEL_OP_KINDS[kind]
            shape = cls.infer_output_shapes_static(shape, params)
        return [shape]


def _static(cls):
    def fn(shape, params):
        dummy = object.__new__(cls)
        dummy.params = params
        dummy.name = cls.__name__
        return cls.infer_output_shapes(dummy, [shape])[0]

    return fn


for _cls in (Repartition, Combine, Replicate, Reduction, AllToAll):
    _cls.infer_output_shapes_static = staticmethod(_static(_cls))

PARALLEL_OP_KINDS = {
    "repartition": Repartition,
    "combine": Combine,
    "replicate": Replicate,
    "reduction": Reduction,
    "all_to_all": AllToAll,
    "fused": FusedParallelOp,
}
