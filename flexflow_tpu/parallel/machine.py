"""Machine views and their lowering to JAX shardings.

This replaces three layers of the reference at once:
  - `MachineView` (/root/reference/include/flexflow/machine_view.h:14-96) —
    the (ndims, dims, start, stride) device-grid a Legion index launch maps
    onto;
  - `FFMapper` (/root/reference/src/mapper/mapper.cc) — the Legion mapper
    that turns a MachineView hash into task placement;
  - per-op `create_input_partition` Legion partitions.

TPU-first design: there is ONE global `jax.sharding.Mesh` with named axes
(e.g. ("data", "model") or ("dp", "fsdp", "tp") — chosen by the strategy
search).  A MachineView for a parallel tensor is the assignment of mesh
axes to that tensor's parallel dims.  Lowering a view is just building a
`NamedSharding`; XLA SPMD then inserts all communication.  Views that the
reference would express with stride/offset device sets are normalized to
mesh-aligned shardings (the search only generates mesh-realizable views —
the reference similarly filters views, graph.h:205-210).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


@dataclasses.dataclass(frozen=True)
class MachineView:
    """Assignment of mesh axes to a parallel tensor's dims.

    axes[i] is the tuple of mesh-axis names sharding dims[i] (the full
    dims tuple, replica dim included).  Empty tuple = dim not sharded.
    Axes on the replica dim mean the tensor is replicated across them
    (for weights this is the data-parallel axis).
    """

    axes: Tuple[Tuple[str, ...], ...]

    def used_axes(self) -> Tuple[str, ...]:
        out = []
        for a in self.axes:
            out.extend(a)
        return tuple(out)

    def __str__(self) -> str:
        return "View(" + ",".join("+".join(a) if a else "_" for a in self.axes) + ")"


def validate_view(view: MachineView, shape, mesh_axis_sizes: Dict[str, int]) -> None:
    """Check the view is consistent with the shape's degrees and the mesh."""
    if len(view.axes) != len(shape.dims):
        raise ValueError(
            f"view rank {len(view.axes)} != tensor rank {len(shape.dims)}"
        )
    seen = set()
    for dim, axes in zip(shape.dims, view.axes):
        prod = 1
        for ax in axes:
            if ax in seen:
                raise ValueError(f"mesh axis {ax!r} used twice in {view}")
            seen.add(ax)
            if ax not in mesh_axis_sizes:
                raise ValueError(f"unknown mesh axis {ax!r}")
            prod *= mesh_axis_sizes[ax]
        if prod != dim.degree:
            raise ValueError(
                f"axes {axes} (size {prod}) != degree {dim.degree} for dim {dim}"
            )


def assign_axes(shape, mesh_axis_sizes: Dict[str, int]) -> MachineView:
    """Normalize per-dim degrees onto named mesh axes (the view normalizer).

    Axis-preference heuristic keeps producer/consumer views aligned on
    the canonical (data, model, ...) mesh:
      - the leading data dim (logical dim 0) and replica dims consume
        axes in declaration order (the "data" axis first — replica dims
        on weights ARE data-parallel replication);
      - all other dims (channel/attribute/expert) consume axes in
        REVERSE declaration order, so a weight's out-channel dim lands
        on the same trailing "model" axis as the matching activation dim.
    The strategy search can always override views explicitly.
    """
    available = dict(mesh_axis_sizes)
    decl_order = list(mesh_axis_sizes.keys())

    def take(need: int, order) -> Tuple[str, ...]:
        order = list(order)
        # pass 1: a single axis of exactly this size (most views are
        # one-axis-per-dim; exact match avoids eating an axis another
        # dim needs)
        for ax in order:
            if ax in available and available[ax] == need:
                del available[ax]
                return (ax,)
        # pass 2: greedy multi-axis factoring
        chosen = []
        for ax in order:
            if ax not in available:
                continue
            size = available[ax]
            if need % size == 0:
                chosen.append(ax)
                del available[ax]
                need //= size
                if need == 1:
                    break
        if need != 1:
            for ax in chosen:
                available[ax] = mesh_axis_sizes[ax]
            raise ValueError(
                f"cannot factor degree onto mesh axes {mesh_axis_sizes} "
                f"(remaining {available}, still need {need})"
            )
        return tuple(chosen)

    axes_out = []
    logical_idx = 0
    for dim in shape.dims:
        if dim.degree <= 1:
            axes_out.append(())
            if not dim.is_replica_dim:
                logical_idx += 1
            continue
        if dim.is_replica_dim or logical_idx == 0:
            axes_out.append(take(dim.degree, decl_order))
        else:
            axes_out.append(take(dim.degree, reversed(decl_order)))
        if not dim.is_replica_dim:
            logical_idx += 1
    return MachineView(tuple(axes_out))


def view_to_spec(pt) -> PartitionSpec:
    """PartitionSpec over the *logical* dims (replica dims dropped —
    replication is expressed by omitting axes)."""
    view: Optional[MachineView] = pt.machine_view
    if view is None:
        return PartitionSpec()
    entries = []
    for dim, axes in zip(pt.shape.dims, view.axes):
        if dim.is_replica_dim:
            continue
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def view_to_sharding(pt, mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, view_to_spec(pt))


# ---------------------------------------------------------------------------
# Mesh construction
# ---------------------------------------------------------------------------

def make_mesh(
    axis_sizes: Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh over the given devices (default: all).

    On real TPU slices `jax.experimental.mesh_utils` picks an ICI-friendly
    device order; on CPU test meshes plain reshape is fine.
    """
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes)) if sizes else 1
    if devices is None:
        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(f"need {n} devices for mesh {axis_sizes}, have {len(devices)}")
    devices = list(devices)[:n]
    if devices and devices[0].platform == "tpu" and n > 1:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
            return Mesh(dev_array, names)
        except Exception:
            pass
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def mesh_axis_sizes(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
