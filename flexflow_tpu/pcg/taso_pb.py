"""Reader for the reference's BINARY substitution catalog (.pb).

The reference ships its 640-rule TASO catalog twice: as
`substitutions/graph_subst_3_v2.pb` (proto2 wire bytes, what
`GraphSearchHelper` actually loads) and as a JSON twin produced by
`tools/protobuf_to_json/protobuf_to_json.cc`.  This module reads the
binary form directly with the vendored protobuf wire codec
(onnx_frontend/protowire.py) — no protobuf dependency — and emits the
EXACT dict structure the reference's converter emits, so the two forms
parse to identical rules.

Schema (reference tools/protobuf_to_json/rules.proto):
  Parameter.key=1/.value=2; Tensor.opId=1/.tsId=2;
  Operator.type=1/.input=2/.para=3;
  MapOutput.srcOpId=1/.dstOpId=2/.srcTsId=3/.dstTsId=4;
  Rule.srcOp=1/.dstOp=2/.mappedOutput=3; RuleCollection.rule=1.

Enum name tables mirror protobuf_to_json.cc:14-119 — including its
"OP_CONSTANT_POOl" typo (line 74), kept verbatim so a .pb parse is
byte-for-byte the converter's JSON output.  PM_ACTI/PM_PAD values stay
raw ints: the converter casts them to enums it never registers a
serializer for, so nlohmann emits the underlying int.
"""
from __future__ import annotations

from typing import List, Union

from ..onnx_frontend.protowire import _fields, _signed

# protobuf_to_json.cc:14-46 (OpType), index == enum value
OP_TYPE_NAMES: List[str] = [
    "OP_INPUT", "OP_WEIGHT", "OP_ANY", "OP_CONV2D", "OP_DROPOUT",
    "OP_LINEAR", "OP_POOL2D_MAX", "OP_POOL2D_AVG", "OP_RELU",
    "OP_SIGMOID", "OP_TANH", "OP_BATCHNORM", "OP_CONCAT", "OP_SPLIT",
    "OP_RESHAPE", "OP_TRANSPOSE", "OP_EW_ADD", "OP_EW_MUL", "OP_MATMUL",
    "OP_MUL", "OP_ENLARGE", "OP_MERGE_GCONV", "OP_CONSTANT_IMM",
    "OP_CONSTANT_ICONV", "OP_CONSTANT_ONE", "OP_CONSTANT_POOl",
    "OP_PARTITION", "OP_COMBINE", "OP_REPLICATE", "OP_REDUCE",
    "OP_EMBEDDING",
]

# protobuf_to_json.cc:81-99 (ParamType)
PARAM_NAMES: List[str] = [
    "PM_OP_TYPE", "PM_NUM_INPUTS", "PM_NUM_OUTPUTS", "PM_GROUP",
    "PM_KERNEL_H", "PM_KERNEL_W", "PM_STRIDE_H", "PM_STRIDE_W",
    "PM_PAD", "PM_ACTI", "PM_NUMDIM", "PM_AXIS", "PM_PERM",
    "PM_OUTSHUFFLE", "PM_MERGE_GCONV_COUNT", "PM_PARALLEL_DIM",
    "PM_PARALLEL_DEGREE",
]


def _enum_name(table: List[str], value: int, what: str) -> str:
    if 0 <= value < len(table):
        return table[value]
    raise ValueError(f"catalog .pb: unknown {what} enum value {value}")


def _msg(v, wt, what: str) -> bytes:
    """Embedded messages must be length-delimited; anything else means
    the stream isn't this schema (raise the clean not-a-catalog error
    instead of letting _fields choke on an int)."""
    if wt != 2:
        raise ValueError(f"catalog .pb: {what} field is not a message")
    return v


def _parse_tensor(buf: bytes) -> dict:
    t = {"_t": "Tensor", "opId": 0, "tsId": 0}
    for field, _wt, v in _fields(buf):
        if field == 1:
            t["opId"] = _signed(v)
        elif field == 2:
            t["tsId"] = v
    return t


def _parse_param(buf: bytes) -> dict:
    key = value = 0
    for field, _wt, v in _fields(buf):
        if field == 1:
            key = v
        elif field == 2:
            value = _signed(v)
    return {"_t": "Parameter",
            "key": _enum_name(PARAM_NAMES, key, "ParamType"),
            "value": value}


def _parse_operator(buf: bytes) -> dict:
    o = {"_t": "Operator", "type": "OP_ANY", "input": [], "para": []}
    for field, wt, v in _fields(buf):
        if field == 1:
            o["type"] = _enum_name(OP_TYPE_NAMES, v, "OpType")
        elif field == 2:
            o["input"].append(_parse_tensor(_msg(v, wt, "Operator.input")))
        elif field == 3:
            o["para"].append(_parse_param(_msg(v, wt, "Operator.para")))
    return o


def _parse_map_output(buf: bytes) -> dict:
    m = {"_t": "MapOutput", "srcOpId": 0, "dstOpId": 0,
         "srcTsId": 0, "dstTsId": 0}
    names = {1: "srcOpId", 2: "dstOpId", 3: "srcTsId", 4: "dstTsId"}
    for field, _wt, v in _fields(buf):
        if field in names:
            m[names[field]] = v
    return m


def _parse_rule(buf: bytes) -> dict:
    r = {"_t": "Rule", "srcOp": [], "dstOp": [], "mappedOutput": []}
    for field, wt, v in _fields(buf):
        if field == 1:
            r["srcOp"].append(_parse_operator(_msg(v, wt, "Rule.srcOp")))
        elif field == 2:
            r["dstOp"].append(_parse_operator(_msg(v, wt, "Rule.dstOp")))
        elif field == 3:
            r["mappedOutput"].append(
                _parse_map_output(_msg(v, wt, "Rule.mappedOutput")))
    return r


def pb_to_dict(src: Union[str, bytes]) -> dict:
    """Parse a serialized GraphSubst.RuleCollection (path or bytes)
    into the converter's JSON-schema dict, rules named taso_rule_{i}
    (protobuf_to_json.cc:209-213)."""
    if isinstance(src, str):
        with open(src, "rb") as fh:
            src = fh.read()
    rules = []
    for field, wt, v in _fields(src):
        if field == 1:
            rules.append(_parse_rule(_msg(v, wt, "RuleCollection.rule")))
    for i, r in enumerate(rules):
        r["name"] = f"taso_rule_{i}"
    return {"_t": "RuleCollection", "rule": rules}


def looks_like_pb(path: str) -> bool:
    """Binary-vs-JSON sniff on the RAW first byte: a RuleCollection
    wire stream opens with the field-1 length-delimited key 0x0A.
    0x0A is also '\\n', so a JSON file led by a newline sniffs as pb —
    parse_rule_collection therefore falls back to JSON when the pb
    parse fails, rather than trusting this sniff as final."""
    try:
        with open(path, "rb") as fh:
            head = fh.read(1)
    except OSError:
        return False
    return head == b"\x0a"
