"""Graph-substitution (xfer) catalog for the Unity search.

Reference: src/runtime/substitution.cc — `GraphXfer` rewrite rules built
by `generate_all_pcg_xfers` (substitution.cc:1726-1868): for every
parallel degree, rules like `create_partition_linear_combine`
(:1755-1760), `create_replicate_linear_combine`, the attention pair
`create_partition_attention_combine` / `create_replicate_attention_reduce`
(:1762-1770), conv/embedding partitions, plus JSON-loaded TASO-style
rules (substitution_loader.h:143-179).

TPU-native redesign: a reference xfer rewrites the PCG by inserting
Repartition/Combine/Replicate/Reduction nodes around an op.  Under XLA
SPMD those resharding boundaries are implicit (with_sharding_constraint
on every op output), so an xfer here is the *semantic payload* of the
reference rule: "op X may run with ShardConfig kind=k degree=d on mesh
axis a".  Applying a set of xfers to a graph yields a Strategy; the
collectives the reference's inserted parallel ops would perform are
emitted by the SPMD partitioner and *costed* by the simulator's
partial-sum/xfer/grad-sync estimators.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

from ..fftype import OperatorType
from ..ops.op import Op, ShardConfig

# which FFConfig gate each shard kind sits behind (reference:
# --enable-parameter-parallel / --enable-attribute-parallel,
# config.h:135-136; channel/expert TP rules are always generated)
_KIND_GATE = {
    "channel": None,
    "reduction": "parameter",
    "attribute": "attribute",
    "expert": None,
}

# which mesh axis a shard kind's degree maps onto
KIND_AXIS = {
    "channel": "model",
    "reduction": "model",
    "attribute": "model",
    "expert": "expert",
}

_OP_TYPE_NAMES = {t.value: t for t in OperatorType}


@dataclasses.dataclass(frozen=True)
class GraphXfer:
    """One rewrite rule: ops of `op_type` may shard `kind`.

    `name` mirrors the reference constructor that builds the analogous
    rule (substitution.cc:1726-1868) so parity is auditable.
    """

    name: str
    op_type: OperatorType
    kind: str  # "channel" | "reduction" | "attribute" | "expert"

    def gate(self) -> Optional[str]:
        return _KIND_GATE[self.kind]


def generate_all_pcg_xfers() -> List[GraphXfer]:
    """The built-in rule catalog (reference substitution.cc:1726-1868)."""
    X = GraphXfer
    T = OperatorType
    return [
        X("create_partition_linear_combine", T.LINEAR, "channel"),
        X("create_replicate_linear_reduce", T.LINEAR, "reduction"),
        X("create_partition_attention_combine", T.MULTIHEAD_ATTENTION, "channel"),
        X("create_partition_conv2d_combine", T.CONV2D, "channel"),
        X("create_partition_embedding_combine", T.EMBEDDING, "attribute"),
        X("create_partition_experts_combine", T.GROUP_BY, "expert"),
    ]


def load_substitution_rules(path: str) -> List[GraphXfer]:
    """JSON rule collection (reference substitution_loader.cc + TASO
    schema substitutions/graph_subst_3_v2.json).  Schema:
      {"rules": [{"name": str, "op_type": "linear", "kind": "channel"}]}
    TASO RuleCollection files (JSON or binary .pb) carry no per-op
    shard-option xfers — they load through pcg/taso.py instead — so
    they resolve to [] here.
    """
    try:
        with open(path) as f:
            d = json.load(f)
    except (UnicodeDecodeError, json.JSONDecodeError):
        from .taso_pb import looks_like_pb

        if looks_like_pb(path):
            return []  # binary TASO catalog
        raise
    out = []
    for r in d.get("rules", []):
        t = _OP_TYPE_NAMES.get(r["op_type"])
        if t is None:
            raise ValueError(f"unknown op_type in substitution rule: {r['op_type']}")
        if r["kind"] not in _KIND_GATE:
            raise ValueError(f"unknown shard kind: {r['kind']}")
        out.append(GraphXfer(r.get("name", f"json_{r['op_type']}_{r['kind']}"),
                             t, r["kind"]))
    return out


def _shard_limit(op: Op, kind: str) -> int:
    """Max legal degree for a shard kind on this op (divisibility source)."""
    t = op.op_type
    p = op.params
    if kind == "channel":
        if t == OperatorType.LINEAR:
            return p.out_channels
        if t == OperatorType.CONV2D:
            return p.out_channels
        if t == OperatorType.MULTIHEAD_ATTENTION:
            return p.num_heads
    elif kind == "reduction":
        if t == OperatorType.LINEAR:
            ishape = op.inputs[0].shape if op.inputs else None
            return ishape.logical_shape[-1] if ishape is not None else 0
    elif kind == "attribute":
        if t == OperatorType.EMBEDDING:
            return p.num_entries
    elif kind == "expert":
        if t == OperatorType.GROUP_BY:
            return p.n
    return 0


@dataclasses.dataclass(frozen=True)
class XferChoice:
    """One applicable xfer on an op: the ShardConfig plus an optional
    parallel-op chain on the op's (first) output.

    The chain is the reference rules' trailing Combine/Reduction — e.g.
    `create_partition_linear_combine` shards out-channels AND gathers the
    output back (substitution.cc:1755-1760); the chain-free variant keeps
    the tensor sharded for the next op to consume (Megatron-style
    alternating column/row parallelism, which the reference reaches by
    cancelling adjacent combine+partition pairs during rewrite search).
    Chain params are stored as hashable item-tuples.
    """

    shard: ShardConfig = ShardConfig()
    out_chain: tuple = ()  # ((kind, ((param, value), ...)), ...)

    def chain_as_lists(self):
        return [(kind, dict(items)) for kind, items in self.out_chain]


def _channel_dim_index(op: Op) -> Optional[int]:
    """Logical index of the output dim a channel shard partitions."""
    if op.op_type == OperatorType.LINEAR:
        return op.outputs[0].shape.logical_rank - 1 if op.outputs else -1
    if op.op_type == OperatorType.CONV2D:
        return 1  # NCHW channel dim
    return None  # attention: heads contract away (partials, not a dim)


def axis_degrees(mesh_axes: Dict[str, int], axis_name: str) -> List[int]:
    """All shard degrees realizable on a logical axis family: products
    of subsets of mesh axes named `axis_name` or `axis_name<digit>`.

    A factored mesh ({"model0": 2, "model1": 2}) is the TPU-native
    expression of the reference's per-op MachineViews
    (machine_view.h:31): different ops may shard at different degrees —
    i.e. live on different submeshes — within one SPMD program."""
    sizes = [
        v for k, v in mesh_axes.items()
        if k == axis_name
        or (k.startswith(axis_name) and k[len(axis_name):].isdigit())
    ]
    degs = {1}
    for s in sizes:
        degs |= {d * s for d in degs}
    return sorted(d for d in degs if d > 1)


def op_options(
    op: Op,
    mesh_axes: Dict[str, int],
    xfers: Sequence[GraphXfer],
    enable_parameter_parallel: bool = False,
    enable_attribute_parallel: bool = False,
) -> List[XferChoice]:
    """All XferChoices the catalog allows for `op` on this mesh, always
    including the trivial (unsharded) choice first."""
    gates = {"parameter": enable_parameter_parallel,
             "attribute": enable_attribute_parallel}
    opts = [XferChoice()]
    seen = {opts[0]}

    def add(choice: XferChoice):
        if choice not in seen:
            seen.add(choice)
            opts.append(choice)

    for xf in xfers:
        if xf.op_type != op.op_type:
            continue
        g = xf.gate()
        if g is not None and not gates.get(g, False):
            continue
        limit = _shard_limit(op, xf.kind)
        for degree in axis_degrees(mesh_axes, KIND_AXIS[xf.kind]):
            if limit <= 0 or limit % degree != 0:
                continue
            cfg = ShardConfig(**{xf.kind: degree})
            add(XferChoice(cfg))
            if xf.kind == "channel":
                ci = _channel_dim_index(op)
                if ci is not None:
                    # the reference rule's trailing Combine: gather the
                    # channel-sharded output back to degree 1
                    add(XferChoice(cfg, (
                        ("combine", (("dim", ci), ("degree", degree))),
                    )))
                else:
                    # attention: head contraction leaves partial sums
                    # (replica degree) — Reduction collapses them, the
                    # create_replicate_attention_reduce shape
                    add(XferChoice(cfg, (
                        ("reduction", (("degree", degree),)),
                    )))
            elif xf.kind in ("reduction", "attribute"):
                # partial-sum output -> optional explicit Reduction
                add(XferChoice(cfg, (
                    ("reduction", (("degree", degree),)),
                )))
    return opts
