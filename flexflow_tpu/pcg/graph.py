"""Parallel Computation Graph structure.

Reference: src/runtime/graph.cc (2753 LoC) — Graph over `Node`s with
in/out edge maps, split algorithms for the DP search, and Legion-buffer
strategy serialization (graph.cc:2164-2400).  Fresh design: ops hold
their producer links via ParallelTensor.owner_op, so the graph is the op
list + derived edge maps; strategy serialization is JSON
(flexflow_tpu/strategy.py) instead of a Legion serializer.
"""
from __future__ import annotations

import collections
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..fftype import OperatorType
from ..ops.op import Op


class Graph:
    def __init__(self, ops: Optional[Sequence[Op]] = None):
        self.ops: List[Op] = list(ops) if ops else []

    def add_op(self, op: Op):
        self.ops.append(op)
        return op

    # -- structure -------------------------------------------------------
    def producers(self, op: Op) -> List[Op]:
        out = []
        for t in op.inputs:
            if t.owner_op is not None and t.owner_op is not op:
                out.append(t.owner_op)
        return out

    def consumers(self, op: Op) -> List[Op]:
        out = []
        for other in self.ops:
            if other is op:
                continue
            for t in other.inputs:
                if t.owner_op is op:
                    out.append(other)
                    break
        return out

    def in_edges(self) -> Dict[Op, List[Op]]:
        return {op: self.producers(op) for op in self.ops}

    def topo_order(self) -> List[Op]:
        indeg: Dict[int, int] = {}
        by_guid = {op.guid: op for op in self.ops}
        edges = collections.defaultdict(list)  # producer guid -> consumer guids
        for op in self.ops:
            preds = {p.guid for p in self.producers(op) if p.guid in by_guid}
            indeg[op.guid] = len(preds)
            for p in preds:
                edges[p].append(op.guid)
        # stable: seed queue in insertion order
        queue = [op.guid for op in self.ops if indeg[op.guid] == 0]
        order: List[Op] = []
        qi = 0
        while qi < len(queue):
            g = queue[qi]
            qi += 1
            order.append(by_guid[g])
            for c in edges[g]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    queue.append(c)
        if len(order) != len(self.ops):
            raise RuntimeError("PCG has a cycle")
        return order

    def source_ops(self) -> List[Op]:
        return [op for op in self.ops if op.op_type == OperatorType.INPUT]

    def sink_op(self) -> Op:
        consumed: Set[int] = set()
        for op in self.ops:
            for t in op.inputs:
                consumed.add(t.guid)
        sinks = [
            op
            for op in self.ops
            if op.op_type != OperatorType.INPUT
            and not any(t.guid in consumed for t in op.outputs)
        ]
        if not sinks:
            raise RuntimeError("no sink op")
        return sinks[-1]

    def compute_ops(self) -> List[Op]:
        return [op for op in self.ops if op.op_type != OperatorType.INPUT]

    # -- hashing (search cache key; reference dp_state_hash graph.h:149) --
    def hash_key(self) -> Tuple:
        return tuple(op.node_key() for op in self.topo_order())

    # -- dot export (reference --compgraph/--taskgraph, utils/dot) --------
    def export_dot(self, path: str, include_costs: bool = False, cost_fn=None):
        lines = ["digraph PCG {"]
        for op in self.ops:
            label = f"{op.name}\\n{op.op_type.value}"
            for t in op.outputs:
                label += f"\\n{t.shape}"
            if include_costs and cost_fn is not None:
                label += f"\\ncost={cost_fn(op):.3g}"
            shape = "ellipse" if op.is_parallel_op() else "box"
            lines.append(f'  n{op.guid} [label="{label}", shape={shape}];')
        for op in self.ops:
            for t in op.inputs:
                if t.owner_op is not None:
                    lines.append(f"  n{t.owner_op.guid} -> n{op.guid};")
        lines.append("}")
        with open(path, "w") as f:
            f.write("\n".join(lines))
