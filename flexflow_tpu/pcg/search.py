"""Strategy search entry points (MCMC + Unity DP).

Reference: FFModel::mcmc_optimize (src/runtime/model.cc:3285-3356) and
the Unity GraphSearchHelper (src/runtime/substitution.cc:1898-2320).
The full implementations live in flexflow_tpu/pcg/mcmc.py and
flexflow_tpu/pcg/unity.py as they land; this module is the stable entry
point used by FFModel.compile.
"""
from __future__ import annotations

from ..strategy import Strategy, data_parallel_strategy


def mcmc_search(model, num_devices: int) -> Strategy:
    from .mcmc import mcmc_optimize  # implemented in the search milestone

    return mcmc_optimize(model, num_devices)


def unity_search(model, num_devices: int,
                 enable_pipeline: bool = True) -> Strategy:
    from .unity import unity_optimize

    return unity_optimize(model, num_devices,
                          enable_pipeline=enable_pipeline)
