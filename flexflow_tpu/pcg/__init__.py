from .graph import Graph
