"""Unity search: substitution-guided DP over graph splits.

Reference: the Unity (OSDI'22) search stack —
`GraphSearchHelper::graph_optimize` (substitution.cc:1898-1945),
`generic_sequence_optimize` (DP over sequence splits at bottleneck
nodes, cached by graph hash, substitution.cc:2430+), `base_optimize`
(budget-bounded rewrite enumeration :2229-2320), `find_split_node`
(:2094), the machine-view assignment DP (`SearchHelper`,
graph.h:170-284 with cached_graph_costs graph.h:280), and the
memory-aware lambda binary search (graph.cc:2056-2131).

TPU-native redesign.  The reference enumerates PCG rewrites (inserting
Repartition/Combine/... nodes) and assigns MachineViews by DP.  Here the
mesh-realizable strategy space is (mesh factorization) x (per-op
ShardConfig from the xfer catalog), and the DP decomposes the graph at
single-tensor bottleneck cuts exactly like generic_sequence_optimize:

  * a DP state at a cut is the crossing tensor's ParallelTensorShape
    (which encodes partition degrees + replica degree — the analogue of
    the reference's possible_split_output_tensor_shapes);
  * each segment is evaluated for every (in-state, assignment of xfer
    options to its ops) with a per-(segment-structure, in-state) cache —
    so the 12 identical BERT layers are costed once, the analogue of
    Unity's cached_graph_costs keyed by subgraph hash;
  * segment cost = sharded compute (roofline/measured OpCostModel)
    + partial-sum collectives + weight-gradient sync, i.e. the same
    terms the SPMD simulator charges;
  * the memory objective enters as `time + lambda * bytes` with the
    reference's 10-iteration binary search on lambda when the best
    strategy exceeds the per-device HBM budget.

The outer loop enumerates mesh factorizations (data x model x expert),
runs the DP for each, and ranks the resulting Strategies with the full
simulator.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

from ..fftype import OperatorType
from ..ops.op import Op, ShapeError, ShardConfig
from ..parallel.machine import assign_axes
from ..strategy import _PARAM_CLASSES, Strategy, apply_strategy, assign_views
from ..tensor import ParallelTensor, ParallelTensorShape
from .graph import Graph
from .mcmc import _factorizations
from .substitution import (
    GraphXfer,
    XferChoice,
    generate_all_pcg_xfers,
    load_substitution_rules,
    op_options,
)

_MAX_SEGMENT_ASSIGNMENTS = 4096


@dataclasses.dataclass
class _SegResult:
    assignment: Tuple[Tuple[int, XferChoice], ...]  # (local op idx, choice)
    time: float
    memory: int
    out_shapes: Tuple[ParallelTensorShape, ...]


class UnitySearch:
    def __init__(
        self,
        graph: Graph,
        num_devices: int,
        machine,
        cost_model,
        xfers: Optional[Sequence[GraphXfer]] = None,
        enable_parameter_parallel: bool = False,
        enable_attribute_parallel: bool = False,
        budget: int = 0,
        memory_budget: Optional[int] = None,
        optimizer_slots: int = 2,
        overlap_fraction: float = 0.3,
        rewrite_rules: Optional[Sequence] = None,
        rewrite_depth: int = 2,
        rewrite_max_variants: int = 8,
    ):
        self.graph = graph
        self._base_graph = graph
        self.rewrite_rules = rewrite_rules  # None -> built-in catalog
        self.rewrite_depth = rewrite_depth
        self.rewrite_max_variants = rewrite_max_variants
        self._variants_memo = None
        self.n = num_devices
        self.machine = machine
        self.cost_model = cost_model
        self.xfers = list(xfers) if xfers is not None else generate_all_pcg_xfers()
        self.enable_parameter_parallel = enable_parameter_parallel
        self.enable_attribute_parallel = enable_attribute_parallel
        self.budget = budget  # 0 = unbounded; else cap on segment evaluations
        self.memory_budget = memory_budget
        self.optimizer_slots = optimizer_slots
        self.overlap = overlap_fraction
        self.evals = 0  # segment-assignment evaluations (budget counter)
        self.cache_hits = 0
        # (segment structural sig, in-shapes sig) -> List[_SegResult]
        self._seg_cache: Dict[Tuple, List[_SegResult]] = {}
        self._segments_memo = None
        self._options_memo: Dict[Tuple, Dict[int, List[XferChoice]]] = {}
        from ..sim.simulator import Simulator

        self._sim = Simulator(machine, cost_model,
                              overlap_fraction=overlap_fraction,
                              optimizer_slots=optimizer_slots)

    # ------------------------------------------------------------------
    # graph splitting (reference find_split_node substitution.cc:2094)
    # ------------------------------------------------------------------
    def _segments(self) -> Tuple[List[List[Op]], List[Optional[int]]]:
        """Split topo order at single-tensor cuts (cached — the graph is
        immutable for the lifetime of a search); pcg/segments.py holds
        the shared implementation."""
        if self._segments_memo is None:
            from .segments import split_segments

            self._segments_memo = split_segments(self.graph)
        return self._segments_memo

    # ------------------------------------------------------------------
    # segment evaluation (reference SearchHelper::graph_cost + simulator)
    # ------------------------------------------------------------------
    def _seg_sig(self, seg: List[Op], boundary_in: List[int]) -> Tuple:
        """Structural signature: identical stacked layers share it."""
        from .segments import segment_signature

        return segment_signature(seg, boundary_in)

    def _comm_time(self, kind: str, size: int, group: int) -> float:
        from ..sim.machine_model import TpuPodModel

        m = self.machine
        if isinstance(m, TpuPodModel):
            if kind == "allreduce":
                return m.axis_allreduce_time(size, group)
            return m.axis_allgather_time(size, group)
        g = list(range(group))
        if kind == "allreduce":
            return m.allreduce_time(size, g)
        return m.allgather_time(size, g)

    def _op_cost(self, op: Op, training: bool = True) -> Tuple[float, int]:
        """(time, per-device bytes) for one instantiated op — the same
        terms Simulator.simulate charges per op."""
        cm = self.cost_model.cost(op)
        t = cm.forward_time + (cm.backward_time if training else 0.0)
        comm = 0.0
        if op.outputs:
            out_rep = op.outputs[0].shape.replica_degree
            in_rep = max((x.shape.replica_degree for x in op.inputs), default=1)
            if out_rep > in_rep:  # contraction-dim partials -> psum
                k = out_rep // max(1, in_rep)
                c = self._comm_time("allreduce", op.outputs[0].shape.shard_bytes(), k)
                comm += 2.0 * c if training else c
        mem = 0
        for w in op.weights:
            rep = w.shape.replica_degree
            if training and rep > 1 and w.create_gradients:
                comm += self._comm_time("allreduce", w.shape.shard_bytes(), rep)
            mem += w.shape.shard_bytes() * ((2 + self.optimizer_slots) if training else 1)
        for o in op.outputs:
            mem += o.shape.shard_bytes()
        return t + comm * (1.0 - self.overlap), mem

    def _realizable(self, shapes, mesh_axes: Dict[str, int]) -> bool:
        """Every shape's degrees must factor onto the mesh axes — the
        reference's get_valid_machine_views filter (graph.h:205-210)."""
        try:
            for s in shapes:
                assign_axes(s, mesh_axes)
            return True
        except ValueError:
            return False

    def _chain_apply(
        self, shape: ParallelTensorShape, chain, mesh_axes: Dict[str, int],
        training: bool,
    ) -> Tuple[ParallelTensorShape, float]:
        """Propagate + cost a parallel-op chain on an output tensor."""
        from ..parallel.parallel_op import PARALLEL_OP_KINDS

        time = 0.0
        for kind, items in chain:
            params = _PARAM_CLASSES[kind](**dict(items))
            pop = PARALLEL_OP_KINDS[kind](params, [ParallelTensor(shape)])
            c = self._sim.xfer_cost(pop, mesh_axes)
            time += (2.0 * c if training else c) * (1.0 - self.overlap)
            shape = pop.outputs[0].shape
        return shape, time

    def _options_by_op(self, mesh_axes: Dict[str, int]) -> Dict[int, List[XferChoice]]:
        key = (id(self.graph), tuple(sorted(mesh_axes.items())))
        memo = self._options_memo.get(key)
        if memo is not None:
            return memo
        out = {}
        for op in self.graph.ops:
            opts = op_options(
                op, mesh_axes, self.xfers,
                self.enable_parameter_parallel, self.enable_attribute_parallel,
            )
            if len(opts) > 1:
                out[op.guid] = opts
        self._options_memo[key] = out
        return out

    def _enumerate_assignments(
        self, seg: List[Op], options: Dict[int, List[XferChoice]]
    ) -> List[Tuple[Tuple[int, XferChoice], ...]]:
        cand = [(j, options[op.guid]) for j, op in enumerate(seg) if op.guid in options]
        if not cand:
            return [()]
        total = 1
        for _, opts in cand:
            total *= len(opts)
        if total > _MAX_SEGMENT_ASSIGNMENTS:
            # group identical (type, params) ops: uniform choice per group
            groups: Dict[Tuple, List[int]] = {}
            for j, _ in cand:
                key = (seg[j].op_type, seg[j].params)
                groups.setdefault(key, []).append(j)
            gkeys = list(groups)
            gopts = [options[seg[groups[k][0]].guid] for k in gkeys]
            out = []
            for combo in itertools.product(*gopts):
                a = []
                for k, cfg in zip(gkeys, combo):
                    a.extend((j, cfg) for j in groups[k])
                out.append(tuple(a))
            return out
        return [
            tuple(zip((j for j, _ in cand), combo))
            for combo in itertools.product(*(opts for _, opts in cand))
        ]

    def _eval_segment(
        self,
        seg: List[Op],
        boundary_in: List[int],  # guids of tensors entering the segment
        in_shapes: Tuple[ParallelTensorShape, ...],
        out_guids: List[int],  # guids of tensors leaving the segment
        options: Dict[int, List[ShardConfig]],
        input_dp: int,
        axes_sig: Tuple,
    ) -> List[_SegResult]:
        sig = (self._seg_sig(seg, boundary_in), in_shapes, input_dp, axes_sig)
        cached = self._seg_cache.get(sig)
        if cached is not None:
            self.cache_hits += 1
            return cached
        mesh_axes = dict(axes_sig)
        results: List[_SegResult] = []
        shape_in = dict(zip(boundary_in, in_shapes))
        for assignment in self._enumerate_assignments(seg, options):
            if self.budget and self.evals >= self.budget:
                if results:
                    break
            self.evals += 1
            choice_of = dict(assignment)
            shapes: Dict[int, ParallelTensorShape] = dict(shape_in)
            time = 0.0
            mem = 0
            ok = True
            for j, op in enumerate(seg):
                if op.op_type == OperatorType.INPUT:
                    s = op.outputs[0].shape
                    if input_dp > 1:
                        if s.logical_shape and s.logical_shape[0] % input_dp == 0:
                            s = s.data_parallel(input_dp)
                        else:
                            ok = False
                            break
                    shapes[op.outputs[0].guid] = s
                    continue
                choice = choice_of.get(j, XferChoice())
                try:
                    new_inputs = [ParallelTensor(shapes[t.guid]) for t in op.inputs]
                    new_op = type(op)(
                        op.params, new_inputs, name=op.name, shard=choice.shard,
                    )
                except (ShapeError, ValueError):
                    ok = False
                    break
                out_shapes = [pt.shape for pt in new_op.outputs]
                chain_time = 0.0
                if choice.out_chain:
                    try:
                        out_shapes[0], chain_time = self._chain_apply(
                            out_shapes[0], choice.out_chain, mesh_axes, True
                        )
                    except (ShapeError, ValueError):
                        ok = False
                        break
                if not self._realizable(
                    out_shapes + [w.shape for w in new_op.weights], mesh_axes
                ):
                    ok = False
                    break
                t, m = self._op_cost(new_op)
                time += t + chain_time
                mem += m
                for pt, s in zip(op.outputs, out_shapes):
                    shapes[pt.guid] = s
            if not ok:
                continue
            results.append(
                _SegResult(
                    assignment=assignment,
                    time=time,
                    memory=mem,
                    out_shapes=tuple(shapes[g] for g in out_guids),
                )
            )
        self._seg_cache[sig] = results
        return results

    # ------------------------------------------------------------------
    # sequence DP (reference generic_sequence_optimize substitution.cc:2430)
    # ------------------------------------------------------------------
    def _dp(self, mesh_axes: Dict[str, int], dp_degree: int,
            lam: float) -> Optional[Tuple[Dict[str, ShardConfig], Dict, float, int]]:
        options = self._options_by_op(mesh_axes)
        axes_sig = tuple(sorted(mesh_axes.items()))
        segments, boundaries = self._segments()
        # states: in-shapes tuple -> (objective, time, mem,
        #         {opname: ShardConfig}, {tensor name: edge chain})
        states: Dict[Tuple, Tuple] = {(): (0.0, 0.0, 0, {}, {})}
        incoming: List[int] = []  # guids crossing into current segment
        for seg, out_guid in zip(segments, boundaries):
            out_guids = [out_guid] if out_guid is not None else []
            new_states: Dict[Tuple, Tuple] = {}
            for in_shapes, (obj0, t0, m0, asg0, edges0) in states.items():
                for res in self._eval_segment(
                    seg, incoming, in_shapes, out_guids, options, dp_degree,
                    axes_sig,
                ):
                    obj = obj0 + res.time + lam * res.memory
                    key = res.out_shapes
                    cur = new_states.get(key)
                    if cur is None or obj < cur[0]:
                        asg = dict(asg0)
                        edges = dict(edges0)
                        for j, choice in res.assignment:
                            if not choice.shard.is_trivial():
                                asg[seg[j].name] = choice.shard
                            if choice.out_chain:
                                edges[seg[j].outputs[0].name] = (
                                    choice.chain_as_lists()
                                )
                        new_states[key] = (
                            obj, t0 + res.time, m0 + res.memory, asg, edges
                        )
            if not new_states:
                return None
            states = new_states
            incoming = out_guids
        best = min(states.values(), key=lambda v: v[0])
        return best[3], best[4], best[1], best[2]

    # ------------------------------------------------------------------
    # top level (reference graph_optimize_task graph.cc:2046-2160)
    # ------------------------------------------------------------------
    def _mesh_axes(self, dp: int, tp: int, ep: int) -> Dict[str, int]:
        axes = {}
        if dp > 1:
            axes["data"] = dp
        if tp > 1:
            axes["model"] = tp
        if ep > 1:
            axes["expert"] = ep
        if not axes:
            axes["data"] = 1
        return axes

    def _build_strategy(self, mesh_axes: Dict[str, int], dp: int,
                        shard_configs: Dict[str, ShardConfig],
                        edges: Optional[Dict] = None) -> Strategy:
        s = Strategy(mesh_axes=mesh_axes, shard_configs=dict(shard_configs))
        if dp > 1:
            s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": dp})]
        for tname, chain in (edges or {}).items():
            s.edge_ops[tname] = chain
        return s

    def _variants(self):
        """Rewritten-graph candidates (reference base_optimize's bounded
        rewrite enumeration, substitution.cc:2229-2320); [(graph, trace)]
        with the original graph first."""
        if self._variants_memo is None:
            from .rewrite import enumerate_variants, generate_rewrite_rules

            rules = (list(self.rewrite_rules) if self.rewrite_rules is not None
                     else generate_rewrite_rules())
            if self.rewrite_max_variants <= 1 or not rules:
                self._variants_memo = [(self._base_graph, [])]
            else:
                self._variants_memo = enumerate_variants(
                    self._base_graph, rules,
                    max_depth=self.rewrite_depth,
                    max_variants=self.rewrite_max_variants,
                )
        return self._variants_memo

    def _set_graph(self, graph: Graph):
        if graph is self.graph:
            return
        self.graph = graph
        self._segments_memo = None

    def _optimize_graph(self, lam: float):
        """Best (strategy, obj) for the CURRENT self.graph across mesh
        factorizations and sp candidates."""
        from ..logger import search_logger as slog

        has_moe = any(op.op_type == OperatorType.GROUP_BY for op in self.graph.ops)
        best: Optional[Strategy] = None
        best_obj = math.inf
        for dp, tp, ep in _factorizations(self.n, allow_expert=has_moe):
            mesh_axes = self._mesh_axes(dp, tp, ep)
            if tp > 1 and not self._options_by_op(mesh_axes):
                continue  # no op can use the model axis
            r = self._dp(mesh_axes, dp, lam)
            if r is None:
                continue
            shard_configs, edges, time, mem = r
            strategy = self._build_strategy(mesh_axes, dp, shard_configs, edges)
            # validate + final rank with the strategy actually applied
            try:
                g = apply_strategy(self.graph, strategy)
                assign_views(g, strategy.mesh_axes)
            except (ShapeError, ValueError):
                continue
            obj = self._objective(time, mem, lam)
            slog.debug(
                "candidate dp=%d tp=%d ep=%d: time=%.3gms mem=%.1fMB obj=%.3g%s",
                dp, tp, ep, time * 1e3, mem / 2**20, obj,
                " *best*" if obj < best_obj else "",
            )
            if obj < best_obj:
                best, best_obj = strategy, obj
        for strategy, obj, label in self._sp_candidates(lam):
            slog.debug(
                "candidate %s: obj=%.3g%s", label, obj,
                " *best*" if obj < best_obj else "",
            )
            if obj < best_obj:
                best, best_obj = strategy, obj
        return best, best_obj

    def optimize(self, lam: float = 0.0) -> Optional[Strategy]:
        from ..logger import search_logger as slog

        best: Optional[Strategy] = None
        best_obj = math.inf
        with slog.enter(f"unity optimize n={self.n} lambda={lam:g}"):
            for graph, trace in self._variants():
                self._set_graph(graph)
                if trace:
                    slog.debug("rewritten variant: %s",
                               "+".join(f"{n}[{i}]" for n, i in trace))
                strategy, obj = self._optimize_graph(lam)
                if strategy is not None and obj < best_obj:
                    strategy.rewrites = [list(r) for r in trace]
                    if trace:
                        slog.debug(
                            "rewrite %s improves obj to %.3g",
                            "+".join(n for n, _ in trace), obj,
                        )
                    best, best_obj = strategy, obj
        self._set_graph(self._base_graph)
        return best

    def _objective(self, time: float, mem: int, lam: float) -> float:
        """Single ranking formula for ALL candidate families (dp/tp/ep
        and sp): time + lambda*mem, with an over-budget penalty in the
        lam=0 pass."""
        obj = time + lam * mem
        if (
            self.memory_budget is not None
            and lam == 0.0
            and mem > self.memory_budget
        ):
            obj *= 1.0 + (mem / self.memory_budget - 1.0)
        return obj

    def _sp_candidates(self, lam: float):
        """Sequence-parallel (context-parallel) candidates: dp x sp
        meshes where activations are seq-sharded and attention lowers to
        ring attention over ICI (parallel/ring_attention.py) — the
        long-context strategy slot the reference leaves empty (SURVEY
        §5).  Costed with the same Simulator terms as the DP search plus
        the ring's KV-rotation traffic."""
        has_attn = any(
            op.op_type == OperatorType.MULTIHEAD_ATTENTION for op in self.graph.ops
        )
        if not has_attn:
            return
        sources = [op for op in self.graph.ops
                   if op.op_type == OperatorType.INPUT]
        seq_ok = all(
            op.outputs[0].shape.logical_rank >= 3 for op in sources
        )
        if not seq_ok:
            return
        training = True
        for sp in range(2, self.n + 1):
            if self.n % sp:
                continue
            dp = self.n // sp
            if any(
                op.outputs[0].shape.logical_shape[1] % sp
                for op in sources
            ):
                continue
            mesh_axes = {"seq": sp}
            if dp > 1:
                mesh_axes["data"] = dp
            s = Strategy(mesh_axes=dict(mesh_axes))
            chain = []
            if dp > 1:
                chain.append(("repartition", {"dim": 0, "degree": dp}))
            chain.append(("repartition", {"dim": 1, "degree": sp}))
            s.edge_ops["__inputs__"] = chain
            try:
                g = apply_strategy(self.graph, s)
                assign_views(g, s.mesh_axes)
            except (ShapeError, ValueError):
                continue
            res = self._sim.simulate(g, mesh_axes, training=training)
            # ring attention KV rotation: ~an allgather of the group's
            # K+V per attention forward; backward re-rotates KV and
            # rotates dK/dV (~2x more); comm overlaps blockwise compute
            ring = 0.0
            for op in g.topo_order():
                if op.op_type != OperatorType.MULTIHEAD_ATTENTION:
                    continue
                kv_bytes = (
                    op.inputs[1].shape.shard_bytes()
                    + op.inputs[2].shape.shard_bytes()
                ) * sp
                ring += 3.0 * self._comm_time("allgather", kv_bytes, sp)
            time = res.total_time + ring * (1.0 - self.overlap)
            mem = res.per_device_memory
            obj = self._objective(time, mem, lam)
            yield s, obj, f"dp={dp} sp={sp} (ring attention)"

    def optimize_with_memory(self) -> Optional[Strategy]:
        """Lambda binary search (reference try_one_lambda + binary search,
        graph.cc:2056-2131): smallest lambda whose best strategy fits the
        per-device memory budget, 10 iterations."""
        best = self.optimize(0.0)
        if best is None or self.memory_budget is None:
            return best
        if self._strategy_memory(best) <= self.memory_budget:
            return best
        lo, hi = 0.0, self._lambda_hi()
        chosen = best
        for _ in range(10):
            mid = (lo + hi) / 2.0
            cand = self.optimize(mid)
            if cand is not None and self._strategy_memory(cand) <= self.memory_budget:
                chosen, hi = cand, mid
            else:
                lo = mid
        return chosen

    def _lambda_hi(self) -> float:
        # scale so the memory term can dominate: time-per-byte at HBM speed
        dev = self.machine.device()
        return 100.0 / dev.hbm_bandwidth

    def _strategy_memory(self, strategy: Strategy) -> int:
        from ..sim.simulator import Simulator

        base = self._base_graph
        if strategy.rewrites:
            from .rewrite import apply_rewrites, generate_rewrite_rules

            rules = (list(self.rewrite_rules) if self.rewrite_rules is not None
                     else generate_rewrite_rules())
            base = apply_rewrites(base, strategy.rewrites, rules)
        g = apply_strategy(base, strategy)
        assign_views(g, strategy.mesh_axes)
        sim = Simulator(self.machine, self.cost_model,
                        optimizer_slots=self.optimizer_slots)
        return sim.per_device_memory(g, training=True)


def unity_optimize(model, num_devices: int) -> Strategy:
    """Entry used by FFModel.compile (reference GRAPH_OPTIMIZE_TASK_ID ->
    Graph::graph_optimize_task graph.cc:2046)."""
    from ..sim.machine_model import make_machine_model
    from ..sim.simulator import make_cost_model

    cfg = model.config
    machine = make_machine_model(cfg, num_devices)
    cost_model = make_cost_model(cfg, machine)
    from .rewrite import rules_for_config

    xfers = generate_all_pcg_xfers()
    if cfg.substitution_json:
        xfers = xfers + load_substitution_rules(cfg.substitution_json)
    rewrite_rules = rules_for_config(cfg)
    search = UnitySearch(
        model.layers,
        num_devices,
        machine,
        cost_model,
        xfers=xfers,
        enable_parameter_parallel=cfg.enable_parameter_parallel,
        enable_attribute_parallel=cfg.enable_attribute_parallel,
        budget=max(0, cfg.search_budget),
        memory_budget=cfg.memory_per_device if cfg.memory_search else None,
        rewrite_rules=rewrite_rules,
    )
    best = search.optimize_with_memory() if cfg.memory_search else search.optimize()
    cost_model.save_persistent()
    if best is None:
        from ..strategy import data_parallel_strategy

        return data_parallel_strategy(num_devices)
    return best
