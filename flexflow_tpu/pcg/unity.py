"""Unity search: substitution-guided DP over graph splits.

Reference: the Unity (OSDI'22) search stack —
`GraphSearchHelper::graph_optimize` (substitution.cc:1898-1945),
`generic_sequence_optimize` (DP over sequence splits at bottleneck
nodes, cached by graph hash, substitution.cc:2430+), `base_optimize`
(budget-bounded rewrite enumeration :2229-2320), `find_split_node`
(:2094), the machine-view assignment DP (`SearchHelper`,
graph.h:170-284 with cached_graph_costs graph.h:280), and the
memory-aware lambda binary search (graph.cc:2056-2131).

TPU-native redesign.  The reference enumerates PCG rewrites (inserting
Repartition/Combine/... nodes) and assigns MachineViews by DP.  Here the
mesh-realizable strategy space is (mesh factorization) x (per-op
ShardConfig from the xfer catalog), and the DP decomposes the graph at
single-tensor bottleneck cuts exactly like generic_sequence_optimize:

  * a DP state at a cut is the crossing tensor's ParallelTensorShape
    (which encodes partition degrees + replica degree — the analogue of
    the reference's possible_split_output_tensor_shapes);
  * each segment is evaluated for every (in-state, assignment of xfer
    options to its ops) with a per-(segment-structure, in-state) cache —
    so the 12 identical BERT layers are costed once, the analogue of
    Unity's cached_graph_costs keyed by subgraph hash;
  * segment cost = sharded compute (roofline/measured OpCostModel)
    + partial-sum collectives + weight-gradient sync, i.e. the same
    terms the SPMD simulator charges;
  * the memory objective enters as `time + lambda * bytes` with the
    reference's 10-iteration binary search on lambda when the best
    strategy exceeds the per-device HBM budget.

The outer loop enumerates mesh factorizations (data x model x expert),
runs the DP for each, and ranks the resulting Strategies with the full
simulator.
"""
from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

from ..fftype import OperatorType
from ..ops.op import Op, ShapeError, ShardConfig
from ..parallel.machine import assign_axes
from ..strategy import _PARAM_CLASSES, Strategy, apply_strategy, assign_views
from ..tensor import ParallelTensor, ParallelTensorShape
from ..sim.simulator import Z3_PREFETCH_OVERLAP
from .evaluator import IncrementalEvaluator
from .graph import Graph
from .mcmc import (
    _factorizations,
    search_remat_enabled,
    search_stage_candidates,
)
from .substitution import (
    GraphXfer,
    XferChoice,
    generate_all_pcg_xfers,
    load_substitution_rules,
    op_options,
)

_MAX_SEGMENT_ASSIGNMENTS = 4096


@dataclasses.dataclass
class _SegResult:
    # (region-local topo index, choice): structural, NOT guid-keyed —
    # cached results are reused across structurally-identical regions
    # (stacked BERT layers, rewritten graph variants) whose ops differ
    assignment: Tuple[Tuple[int, XferChoice], ...]
    time: float
    memory: int
    out_shapes: Tuple[ParallelTensorShape, ...]


#: max states a region evaluation hands back to its parent (best per
#: out-shape signature first, then scalarized-cost beam)
_MAX_REGION_STATES = 64


class UnitySearch:
    def __init__(
        self,
        graph: Graph,
        num_devices: int,
        machine,
        cost_model,
        xfers: Optional[Sequence[GraphXfer]] = None,
        enable_parameter_parallel: bool = False,
        enable_attribute_parallel: bool = False,
        budget: int = 0,
        memory_budget: Optional[int] = None,
        optimizer_slots: int = 2,
        overlap_fraction: float = 0.3,
        rewrite_rules: Optional[Sequence] = None,
        rewrite_depth: int = 2,
        rewrite_max_variants: int = 8,
        event_rerank: bool = True,
        # r04: 8 (was 4) — a mis-ranked analytic #5 was never
        # re-examined by the event re-rank (VERDICT r03 Weak #4)
        event_topk: int = 8,
        sync_overlap_fraction: Optional[float] = None,
        parameter_sync: str = "allreduce",
        max_assignments: Optional[int] = None,
        enable_sample_parallel: bool = False,
        remat: bool = False,
        compute_scale: float = 1.0,
        eval_cache: bool = True,
        weight_update_sharding: bool = False,
        wus_axis: str = "data",
        zero_stage: Optional[int] = None,
        zero_stages: Optional[Sequence[int]] = None,
        registry=None,
        enable_pipeline: bool = True,
        remat_search: bool = False,
        dcn_bucket_bytes: Optional[float] = None,
    ):
        # obs.metrics.MetricsRegistry (or None): final counters also
        # land in run telemetry, not just the log line
        self.registry = registry
        self.event_rerank = event_rerank
        self.event_topk = event_topk
        self.sync_overlap = (
            sync_overlap_fraction if sync_overlap_fraction is not None
            else overlap_fraction
        )
        self.parameter_sync = parameter_sync
        # reference --simulator-segment-size: bounds per-region search
        # work; never raises the built-in cap
        self.max_assignments = max_assignments
        self.enable_sample_parallel = enable_sample_parallel
        self.graph = graph
        self._base_graph = graph
        self.rewrite_rules = rewrite_rules  # None -> built-in catalog
        self.rewrite_depth = rewrite_depth
        self.rewrite_max_variants = rewrite_max_variants
        self._variants_memo = None
        self.n = num_devices
        self.machine = machine
        self.cost_model = cost_model
        self.xfers = list(xfers) if xfers is not None else generate_all_pcg_xfers()
        self.enable_parameter_parallel = enable_parameter_parallel
        self.enable_attribute_parallel = enable_attribute_parallel
        # pipeline-parallel candidates (_pp_candidates) can be switched
        # off by callers whose carried state cannot map onto the GPipe
        # stacked weight layout (the supervisor's elastic re-search —
        # checkpoint reshard-restore is per-op-keyed)
        self.enable_pipeline = enable_pipeline
        self.budget = budget  # 0 = unbounded; else cap on segment evaluations
        self.memory_budget = memory_budget
        self.optimizer_slots = optimizer_slots
        self.overlap = overlap_fraction
        self.evals = 0  # segment-assignment evaluations (budget counter)
        self.cache_hits = 0
        # (segment structural sig, in-shapes sig) -> List[_SegResult]
        self._seg_cache: Dict[Tuple, List[_SegResult]] = {}
        self._segments_memo = None
        self._options_memo: Dict[Tuple, Dict[int, List[XferChoice]]] = {}
        from ..sim.simulator import Simulator

        self.remat = remat
        # ZeRO ladder: zero_stage is the BASE stage the DP costs every
        # segment under; zero_stages (when longer than one) are the
        # rungs each collected candidate is additionally re-scored at
        # through the memoized evaluator (_stage_variants), so the
        # search — not the user — picks the memory/comm trade-off.
        # weight_update_sharding=True is the deprecated stage-1 alias.
        self.zero_stage = (
            int(zero_stage) if zero_stage is not None
            else (1 if weight_update_sharding else 0)
        )
        self.zero_stages = (
            tuple(zero_stages) if zero_stages else (self.zero_stage,)
        )
        self.weight_update_sharding = self.zero_stage >= 1
        self.wus_axis = wus_axis
        # searched remat (docs/PERF.md): each collected candidate is
        # additionally re-scored at a bounded family of per-segment
        # remat plans (_remat_variants) — the _stage_variants shape for
        # the activation term of the memory ladder
        self.remat_search = remat_search
        from ..sim.simulator import DEFAULT_DCN_BUCKET_BYTES

        sim_kw = {}
        if dcn_bucket_bytes is not None:
            sim_kw["dcn_bucket_bytes"] = dcn_bucket_bytes
        else:
            sim_kw["dcn_bucket_bytes"] = DEFAULT_DCN_BUCKET_BYTES
        self._sim = Simulator(machine, cost_model,
                              overlap_fraction=overlap_fraction,
                              optimizer_slots=optimizer_slots,
                              sync_overlap_fraction=sync_overlap_fraction,
                              parameter_sync=parameter_sync,
                              remat=remat,
                              compute_scale=compute_scale,
                              zero_stage=self.zero_stage,
                              wus_axis=wus_axis,
                              **sim_kw)
        # multi-slice hierarchy (topology/, docs/TOPOLOGY.md): each
        # collected candidate is additionally re-scored at every legal
        # placement (which mesh axis spans the DCN boundary) through
        # the memoized evaluator — the exact shape of the ZeRO-stage
        # variants.  Flat machines skip the expansion entirely.
        self.slices = max(1, int(getattr(machine, "slices", 1) or 1))
        self._hier = (
            self.slices > 1 and hasattr(machine, "collective_cost")
        )
        # memoized whole-strategy evaluator per (possibly rewritten)
        # graph variant: the sp/sample candidate families and the
        # memory-aware lambda binary search revisit identical strategies
        # across optimize() passes — those re-evaluations become memo
        # lookups (pcg/evaluator.py)
        self.eval_cache = eval_cache
        self._evaluators: Dict[Graph, "IncrementalEvaluator"] = {}

    # ------------------------------------------------------------------
    # graph splitting (reference find_split_node substitution.cc:2094)
    # ------------------------------------------------------------------
    def _segments(self) -> Tuple[List[List[Op]], List[Optional[int]]]:
        """Split topo order at single-tensor cuts (cached — the graph is
        immutable for the lifetime of a search); pcg/segments.py holds
        the shared implementation."""
        if self._segments_memo is None:
            from .segments import split_segments

            self._segments_memo = split_segments(self.graph)
        return self._segments_memo

    # ------------------------------------------------------------------
    # segment evaluation (reference SearchHelper::graph_cost + simulator)
    # ------------------------------------------------------------------
    def _seg_sig(self, seg: List[Op], boundary_in: List[int]) -> Tuple:
        """Structural signature: identical stacked layers share it."""
        from .segments import segment_signature

        return segment_signature(seg, boundary_in)

    def _comm_time(self, kind: str, size: int, group: int) -> float:
        from ..sim.machine_model import TpuPodModel

        m = self.machine
        if isinstance(m, TpuPodModel):
            if kind == "allreduce":
                return m.axis_allreduce_time(size, group)
            return m.axis_allgather_time(size, group)
        g = list(range(group))
        if kind == "allreduce":
            return m.allreduce_time(size, g)
        return m.allgather_time(size, g)

    def _op_cost(self, op: Op, training: bool = True) -> Tuple[float, int]:
        """(time, per-device bytes) for one instantiated op — the same
        terms Simulator.simulate charges per op."""
        cm = self.cost_model.cost(op)
        t = cm.forward_time + (cm.backward_time if training else 0.0)
        comm = 0.0
        sync = 0.0
        if op.outputs:
            out_rep = op.outputs[0].shape.replica_degree
            in_rep = max((x.shape.replica_degree for x in op.inputs), default=1)
            if out_rep > in_rep:  # contraction-dim partials -> psum
                k = out_rep // max(1, in_rep)
                c = self._comm_time("allreduce", op.outputs[0].shape.shard_bytes(), k)
                comm += 2.0 * c if training else c
        gather = 0.0
        mem = 0
        stage = self.zero_stage
        for w in op.weights:
            rep = w.shape.replica_degree
            sb = w.shape.shard_bytes()
            # Simulator.wus_group carries every guard (knob, sync mode,
            # per-leaf divisibility); no mesh context at this DP stage,
            # so the group falls back to the replica degree — exact on
            # pure-dp meshes, and the authoritative evaluator re-scores
            # with mesh_axes
            g = self._sim.wus_group(w) if w.create_gradients else 1
            if training and rep > 1 and w.create_gradients:
                if g > 1:
                    # reduce-scatter + the stage's gathers (the
                    # post-update gather takes the generic comm credit
                    # like Simulator.simulate_ops; the stage-3
                    # per-layer gathers take the prefetch credit)
                    s, x, gx = self._sim.weight_update_comm(sb, g)
                    sync += s
                    comm += x
                    gather += gx
                else:
                    sync += self._sim.sync_time(sb, rep)
            if not training:
                mem += sb
            elif g > 1:
                # ZeRO ladder residency: slots 1/g (stage 1+), grads
                # 1/g (stage 2+), master 1/g (stage 3; the 2-layer
                # gather window is charged by the authoritative
                # evaluator, not per-op here)
                master = sb // g if stage >= 3 else sb
                grads = sb // g if stage >= 2 else sb
                mem += master + grads + self.optimizer_slots * (sb // g)
            else:
                mem += sb * (2 + self.optimizer_slots)
        for o in op.outputs:
            mem += o.shape.shard_bytes()
        time = (t + comm * (1.0 - self.overlap)
                + sync * (1.0 - self.sync_overlap)
                + gather * (1.0 - Z3_PREFETCH_OVERLAP))
        return time, mem

    def _realizable(self, shapes, mesh_axes: Dict[str, int]) -> bool:
        """Every shape's degrees must factor onto the mesh axes — the
        reference's get_valid_machine_views filter (graph.h:205-210)."""
        try:
            for s in shapes:
                assign_axes(s, mesh_axes)
            return True
        except ValueError:
            return False

    def _chain_apply(
        self, shape: ParallelTensorShape, chain, mesh_axes: Dict[str, int],
        training: bool,
    ) -> Tuple[ParallelTensorShape, float]:
        """Propagate + cost a parallel-op chain on an output tensor."""
        from ..parallel.parallel_op import PARALLEL_OP_KINDS

        time = 0.0
        for kind, items in chain:
            params = _PARAM_CLASSES[kind](**dict(items))
            pop = PARALLEL_OP_KINDS[kind](params, [ParallelTensor(shape)])
            c = self._sim.xfer_cost(pop, mesh_axes)
            time += (2.0 * c if training else c) * (1.0 - self.overlap)
            shape = pop.outputs[0].shape
        return shape, time

    def _options_by_op(self, mesh_axes: Dict[str, int]) -> Dict[int, List[XferChoice]]:
        key = (id(self.graph), tuple(sorted(mesh_axes.items())))
        memo = self._options_memo.get(key)
        if memo is not None:
            return memo
        out = {}
        for op in self.graph.ops:
            opts = op_options(
                op, mesh_axes, self.xfers,
                self.enable_parameter_parallel, self.enable_attribute_parallel,
            )
            if len(opts) > 1:
                out[op.guid] = opts
        self._options_memo[key] = out
        return out

    # -- region evaluation: enumerate / horizontal / vertical ----------
    #
    # Reference: SearchHelper::graph_cost's DP over sequential, vertical
    # and horizontal graph splits (graph.h:170-284, split_at_node /
    # split_horizontal graph.h:346-349).  A region whose joint
    # assignment space exceeds _MAX_SEGMENT_ASSIGNMENTS is decomposed:
    # horizontally into independent branch components (Inception-style
    # parallel branches get per-branch choices, combined only through
    # their output shapes at the join), else vertically at a
    # multi-tensor topo cut; only irreducible single-op regions fall
    # back to exhaustive/grouped enumeration.

    def _boundary_in(self, seg: List[Op]) -> List[int]:
        """External input tensor guids, ordered by first consumption."""
        from .segments import external_inputs

        return external_inputs(seg)

    def _out_refs(self, seg: List[Op], out_guids: List[int]) -> Tuple:
        """Structural refs of exported tensors (cache-key component)."""
        ref = {}
        for j, op in enumerate(seg):
            for oi, t in enumerate(op.outputs):
                ref[t.guid] = (j, oi)
        return tuple(ref[g] for g in out_guids)

    def _n_assignments(self, seg, options) -> int:
        total = 1
        for op in seg:
            opts = options.get(op.guid)
            if opts:
                total *= len(opts)
        return total

    def _cap(self) -> int:
        """Per-region assignment cap; --simulator-segment-size can only
        lower the built-in bound (its reference role: limit per-segment
        simulation work)."""
        cap = _MAX_SEGMENT_ASSIGNMENTS
        if self.max_assignments is not None:
            cap = min(cap, max(1, self.max_assignments))
        return cap

    def _prune_states(self, results: List[_SegResult], lam: float) -> List[_SegResult]:
        """Best result per out-shape signature, then a scalarized-cost
        beam of _MAX_REGION_STATES (the analogue of the reference's
        bounded per-subgraph state sets)."""
        best: Dict[Tuple, _SegResult] = {}
        for r in results:
            cur = best.get(r.out_shapes)
            if cur is None or (r.time + lam * r.memory) < (cur.time + lam * cur.memory):
                best[r.out_shapes] = r
        out = sorted(best.values(), key=lambda r: r.time + lam * r.memory)
        return out[:_MAX_REGION_STATES]

    def _eval_region(
        self,
        seg: List[Op],
        shape_env: Dict[int, ParallelTensorShape],
        out_guids: List[int],
        options: Dict[int, List[XferChoice]],
        input_dp: int,
        axes_sig: Tuple,
        lam: float,
    ) -> List[_SegResult]:
        boundary_in = self._boundary_in(seg)
        in_shapes = tuple(shape_env[g] for g in boundary_in)
        sig = (
            self._seg_sig(seg, boundary_in),
            self._out_refs(seg, out_guids),
            in_shapes, input_dp, axes_sig, lam,
        )
        cached = self._seg_cache.get(sig)
        if cached is not None:
            self.cache_hits += 1
            return cached
        n = self._n_assignments(seg, options)
        results: Optional[List[_SegResult]] = None
        if n > self._cap() and len(seg) >= 2:
            results = self._eval_horizontal(
                seg, shape_env, out_guids, options, input_dp, axes_sig, lam
            )
            if results is None:
                results = self._eval_vertical(
                    seg, shape_env, out_guids, options, input_dp, axes_sig, lam
                )
        if results is None:
            results = self._eval_enumerate(
                seg, shape_env, out_guids, options, input_dp, axes_sig
            )
        results = self._prune_states(results, lam)
        self._seg_cache[sig] = results
        return results

    def _components(self, seg: List[Op]) -> List[List[Op]]:
        """Weakly-connected components of the region's INTERNAL dataflow
        (edges through externally-produced tensors don't connect)."""
        parent = {op.guid: op.guid for op in seg}

        def find(g):
            while parent[g] != g:
                parent[g] = parent[parent[g]]
                g = parent[g]
            return g

        producer = {t.guid: op.guid for op in seg for t in op.outputs}
        for op in seg:
            for t in op.inputs:
                p = producer.get(t.guid)
                if p is not None:
                    ra, rb = find(p), find(op.guid)
                    if ra != rb:
                        parent[ra] = rb
        comps: Dict[int, List[Op]] = {}
        for op in seg:
            comps.setdefault(find(op.guid), []).append(op)
        return list(comps.values())

    def _eval_horizontal(
        self, seg, shape_env, out_guids, options, input_dp, axes_sig, lam
    ) -> Optional[List[_SegResult]]:
        """Peel the join op and evaluate independent branch components
        separately (reference split_horizontal, graph.h:346-349)."""
        sink, rest = seg[-1], seg[:-1]
        comps = self._components(rest)
        if len(comps) <= 1:
            return None
        sink_in = {t.guid for t in sink.inputs}
        out_set = set(out_guids)
        parent_pos = {op.guid: j for j, op in enumerate(seg)}
        combos: List[Tuple[Tuple, float, int, Dict[int, ParallelTensorShape]]] = [
            ((), 0.0, 0, {})
        ]
        for comp in comps:
            comp_outs = [
                t.guid
                for op in comp
                for t in op.outputs
                if t.guid in sink_in or t.guid in out_set
            ]
            rs = self._eval_region(
                comp, shape_env, comp_outs, options, input_dp, axes_sig, lam
            )
            if not rs:
                return []
            # child indices are local to the component; lift to parent
            lift = [parent_pos[op.guid] for op in comp]
            new_combos = []
            for asg0, t0, m0, env0 in combos:
                for r in rs:
                    env = dict(env0)
                    env.update(zip(comp_outs, r.out_shapes))
                    asg = tuple((lift[j], c) for j, c in r.assignment)
                    new_combos.append(
                        (asg0 + asg, t0 + r.time, m0 + r.memory, env)
                    )
            # keep the combination frontier bounded
            new_combos.sort(key=lambda c: c[1] + lam * c[2])
            combos = new_combos[:_MAX_REGION_STATES]
        sink_idx = len(seg) - 1
        results: List[_SegResult] = []
        for asg0, t0, m0, env0 in combos:
            env = dict(shape_env)
            env.update(env0)
            sink_outs = [g for g in out_guids if g not in env0]
            for r in self._eval_region(
                [sink], env, sink_outs, options, input_dp, axes_sig, lam
            ):
                env2 = dict(env)
                env2.update(zip(sink_outs, r.out_shapes))
                asg = tuple((sink_idx, c) for _, c in r.assignment)
                results.append(
                    _SegResult(
                        assignment=asg0 + asg,
                        time=t0 + r.time,
                        memory=m0 + r.memory,
                        out_shapes=tuple(env2[g] for g in out_guids),
                    )
                )
        return results

    def _eval_vertical(
        self, seg, shape_env, out_guids, options, input_dp, axes_sig, lam
    ) -> List[_SegResult]:
        """Split at a mid topo position; the crossing state is the tuple
        of ALL crossing tensor shapes (reference split_at_node's
        non-dominator generalization)."""
        k = len(seg) // 2
        first, second = seg[:k], seg[k:]
        consumed2 = {t.guid for op in second for t in op.inputs}
        out_set = set(out_guids)
        first_out = [
            t.guid
            for op in first
            for t in op.outputs
            if t.guid in consumed2 or t.guid in out_set
        ]
        results: List[_SegResult] = []
        for r1 in self._eval_region(
            first, shape_env, first_out, options, input_dp, axes_sig, lam
        ):
            env = dict(shape_env)
            env.update(zip(first_out, r1.out_shapes))
            second_out = [g for g in out_guids if g not in env]
            for r2 in self._eval_region(
                second, env, second_out, options, input_dp, axes_sig, lam
            ):
                env2 = dict(env)
                env2.update(zip(second_out, r2.out_shapes))
                asg2 = tuple((j + k, c) for j, c in r2.assignment)
                results.append(
                    _SegResult(
                        assignment=r1.assignment + asg2,
                        time=r1.time + r2.time,
                        memory=r1.memory + r2.memory,
                        out_shapes=tuple(env2[g] for g in out_guids),
                    )
                )
        return results

    def _enumerate_assignments(
        self, seg: List[Op], options: Dict[int, List[XferChoice]]
    ) -> List[Tuple[Tuple[int, XferChoice], ...]]:
        cand = [(j, options[op.guid]) for j, op in enumerate(seg) if op.guid in options]
        if not cand:
            return [()]
        total = 1
        for _, opts in cand:
            total *= len(opts)
        if total > self._cap():
            # irreducible over-cap region: group identical (type, params)
            # ops and force a uniform choice per group
            from ..logger import search_logger as slog

            slog.debug(
                "assignment cap hit on irreducible region (%d ops, %d "
                "assignments > %d): grouping identical ops",
                len(seg), total, self._cap(),
            )
            groups: Dict[Tuple, List[int]] = {}
            for j, _ in cand:
                key = (seg[j].op_type, seg[j].params)
                groups.setdefault(key, []).append(j)
            gkeys = list(groups)
            gopts = [options[seg[groups[k][0]].guid] for k in gkeys]
            out = []
            for combo in itertools.product(*gopts):
                a = []
                for k, cfg in zip(gkeys, combo):
                    a.extend((j, cfg) for j in groups[k])
                out.append(tuple(a))
            return out
        return [
            tuple(zip((j for j, _ in cand), combo))
            for combo in itertools.product(*(opts for _, opts in cand))
        ]

    def _eval_enumerate(
        self,
        seg: List[Op],
        shape_env: Dict[int, ParallelTensorShape],
        out_guids: List[int],
        options: Dict[int, List[XferChoice]],
        input_dp: int,
        axes_sig: Tuple,
    ) -> List[_SegResult]:
        mesh_axes = dict(axes_sig)
        results: List[_SegResult] = []
        for assignment in self._enumerate_assignments(seg, options):
            if self.budget and self.evals >= self.budget:
                if results:
                    break
            self.evals += 1
            choice_of = dict(assignment)
            shapes: Dict[int, ParallelTensorShape] = dict(shape_env)
            time = 0.0
            mem = 0
            ok = True
            for j, op in enumerate(seg):
                if op.op_type == OperatorType.INPUT:
                    s = op.outputs[0].shape
                    if input_dp > 1:
                        if s.logical_shape and s.logical_shape[0] % input_dp == 0:
                            s = s.data_parallel(input_dp)
                        else:
                            ok = False
                            break
                    shapes[op.outputs[0].guid] = s
                    continue
                choice = choice_of.get(j, XferChoice())
                try:
                    new_inputs = [ParallelTensor(shapes[t.guid]) for t in op.inputs]
                    new_op = type(op)(
                        op.params, new_inputs, name=op.name,
                        shard=choice.shard, **op.ctor_kwargs(),
                    )
                except (ShapeError, ValueError):
                    ok = False
                    break
                out_shapes = [pt.shape for pt in new_op.outputs]
                chain_time = 0.0
                if choice.out_chain:
                    try:
                        out_shapes[0], chain_time = self._chain_apply(
                            out_shapes[0], choice.out_chain, mesh_axes, True
                        )
                    except (ShapeError, ValueError):
                        ok = False
                        break
                if not self._realizable(
                    out_shapes + [w.shape for w in new_op.weights], mesh_axes
                ):
                    ok = False
                    break
                t, m = self._op_cost(new_op)
                time += t + chain_time
                mem += m
                for pt, s in zip(op.outputs, out_shapes):
                    shapes[pt.guid] = s
            if not ok:
                continue
            results.append(
                _SegResult(
                    assignment=assignment,
                    time=time,
                    memory=mem,
                    out_shapes=tuple(shapes[g] for g in out_guids),
                )
            )
        return results

    # ------------------------------------------------------------------
    # sequence DP (reference generic_sequence_optimize substitution.cc:2430)
    # ------------------------------------------------------------------
    def _dp(self, mesh_axes: Dict[str, int], dp_degree: int,
            lam: float) -> Optional[Tuple[Dict[str, ShardConfig], Dict, float, int]]:
        options = self._options_by_op(mesh_axes)
        axes_sig = tuple(sorted(mesh_axes.items()))
        segments, boundaries = self._segments()
        # states: in-shapes tuple -> (objective, time, mem,
        #         {opname: ShardConfig}, {tensor name: edge chain})
        states: Dict[Tuple, Tuple] = {(): (0.0, 0.0, 0, {}, {})}
        incoming: List[int] = []  # guids crossing into current segment
        for seg, out_guid in zip(segments, boundaries):
            out_guids = [out_guid] if out_guid is not None else []
            new_states: Dict[Tuple, Tuple] = {}
            for in_shapes, (obj0, t0, m0, asg0, edges0) in states.items():
                shape_env = dict(zip(incoming, in_shapes))
                for res in self._eval_region(
                    seg, shape_env, out_guids, options, dp_degree,
                    axes_sig, lam,
                ):
                    obj = obj0 + res.time + lam * res.memory
                    key = res.out_shapes
                    cur = new_states.get(key)
                    if cur is None or obj < cur[0]:
                        asg = dict(asg0)
                        edges = dict(edges0)
                        for j, choice in res.assignment:
                            op = seg[j]
                            if not choice.shard.is_trivial():
                                asg[op.name] = choice.shard
                            if choice.out_chain:
                                edges[op.outputs[0].name] = (
                                    choice.chain_as_lists()
                                )
                        new_states[key] = (
                            obj, t0 + res.time, m0 + res.memory, asg, edges
                        )
            if not new_states:
                return None
            states = new_states
            incoming = out_guids
        best = min(states.values(), key=lambda v: v[0])
        return best[3], best[4], best[1], best[2]

    # ------------------------------------------------------------------
    # top level (reference graph_optimize_task graph.cc:2046-2160)
    # ------------------------------------------------------------------
    def _mesh_axes(self, dp: int, tp: int, ep: int) -> Dict[str, int]:
        axes = {}
        if dp > 1:
            axes["data"] = dp
        if tp > 1:
            axes["model"] = tp
        if ep > 1:
            axes["expert"] = ep
        if not axes:
            axes["data"] = 1
        return axes

    def _mesh_variants(self, dp: int, tp: int, ep: int):
        """Mesh-axes candidates for one (dp, tp, ep) factorization: the
        plain mesh, plus — for composite tp — a FACTORED model axis
        ({"model0": a, "model1": b}) under which ops may shard at
        different degrees, i.e. per-op submesh machine views (reference
        machine_view.h:31; SURVEY §7 hard-part 4's mesh-realizable
        subset)."""
        yield self._mesh_axes(dp, tp, ep)
        if tp > 3:
            a = next((p for p in range(2, tp) if tp % p == 0), tp)
            if a < tp:
                axes = {}
                if dp > 1:
                    axes["data"] = dp
                axes["model0"] = tp // a
                axes["model1"] = a
                if ep > 1:
                    axes["expert"] = ep
                yield axes

    def _build_strategy(self, mesh_axes: Dict[str, int], dp: int,
                        shard_configs: Dict[str, ShardConfig],
                        edges: Optional[Dict] = None) -> Strategy:
        s = Strategy(mesh_axes=mesh_axes, shard_configs=dict(shard_configs))
        if dp > 1:
            s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": dp})]
        for tname, chain in (edges or {}).items():
            s.edge_ops[tname] = chain
        return s

    def _variants(self):
        """Rewritten-graph candidates (reference base_optimize's bounded
        rewrite enumeration, substitution.cc:2229-2320); [(graph, trace)]
        with the original graph first."""
        if self._variants_memo is None:
            from .rewrite import enumerate_variants, generate_rewrite_rules

            rules = (list(self.rewrite_rules) if self.rewrite_rules is not None
                     else generate_rewrite_rules())
            if self.rewrite_max_variants <= 1 or not rules:
                self._variants_memo = [(self._base_graph, [])]
            else:
                self._variants_memo = enumerate_variants(
                    self._base_graph, rules,
                    max_depth=self.rewrite_depth,
                    max_variants=self.rewrite_max_variants,
                )
        return self._variants_memo

    def _set_graph(self, graph: Graph):
        if graph is self.graph:
            return
        self.graph = graph
        self._segments_memo = None

    def _evaluator(self) -> IncrementalEvaluator:
        """Memoized evaluator for the CURRENT self.graph (keyed by the
        Graph object itself — identity hash — which also pins the graph
        alive for the evaluator's cached records)."""
        ev = self._evaluators.get(self.graph)
        if ev is None:
            ev = IncrementalEvaluator(self.graph, self._sim, training=True,
                                      use_cache=self.eval_cache)
            self._evaluators[self.graph] = ev
        return ev

    def eval_stats(self) -> Dict[str, float]:
        """Aggregate evaluator counters across graph variants, plus the
        segment-DP cache counters — the search-observability payload
        attached to returned strategies."""
        agg: Dict[str, float] = {}
        for ev in self._evaluators.values():
            for k, v in ev.stats.as_dict().items():
                agg[k] = agg.get(k, 0) + v
        n_evals = agg.get("evals", 0)
        agg["evals_per_sec"] = (
            n_evals / agg["eval_seconds"] if agg.get("eval_seconds") else 0.0
        )
        agg["mean_dirty_frontier"] = (
            agg.get("dirty_ops", 0) / agg["delta_evals"]
            if agg.get("delta_evals") else 0.0
        )
        agg["segment_evals"] = self.evals
        agg["segment_cache_hits"] = self.cache_hits
        agg["term_hits"] = self._sim.term_hits
        agg["term_misses"] = self._sim.term_misses
        agg["op_cost_hits"] = getattr(self.cost_model, "cost_hits", 0)
        return agg

    def _stage_variants(self, strategy: Strategy, time: float,
                        mem: int) -> List[Tuple[Strategy, float, int]]:
        """The candidate scored at every allowed ZeRO stage:
        [(strategy', time', mem')].  The base stage keeps the caller's
        analytic (time, mem); other rungs correct them by the memoized
        evaluator's stage delta (the applied graph is stage-invariant,
        so the delta is exactly the ladder's update/residency terms).
        Ascending stage order + strict objective comparison downstream
        keep ties on the LOWEST stage."""
        out = [(strategy, time, mem)]
        extra = [s for s in self.zero_stages if s != self.zero_stage]
        if not extra:
            return out
        base = self._evaluator().evaluate(strategy)
        if base is None:
            return out
        bt, bm = base.total_time, base.per_device_memory
        for s in sorted(extra):
            cand = dataclasses.replace(strategy, zero_stage=s)
            res = self._evaluator().evaluate(cand)
            if res is None:
                continue
            out.append((cand, time + res.total_time - bt,
                        mem + res.per_device_memory - bm))
        return out

    def _remat_variants(self, strategy: Strategy, time: float, mem: int,
                        lam: float) -> List[Tuple[Strategy, float, int]]:
        """The candidate re-scored at a bounded family of per-segment
        remat plans (docs/PERF.md "Searched rematerialization"):
        [(strategy', time', mem')].  Per pure segment, a single-ON plan
        prices its marginal (recompute seconds vs activation bytes);
        segments then stack in objective-ascending order (each prefix
        plan evaluated through the memoized evaluator — a zero-frontier
        delta re-sum, the applied graph is plan-invariant), plus the
        all-ON plan (the legacy --remat shape).  No plan = the dense
        base, which always stays in the family, so remat is only ever
        chosen when the objective says it wins."""
        out = [(strategy, time, mem)]
        if not self.remat_search or strategy.pipeline:
            return out
        base = self._evaluator().evaluate(strategy)
        if base is None:
            return out
        from ..sim.simulator import MAX_REMAT_SEGMENTS, remat_segments

        idx = [
            i for i, (_, pure) in enumerate(remat_segments(base.ops))
            if pure
        ][:MAX_REMAT_SEGMENTS]
        if not idx:
            return out
        bt, bm = base.total_time, base.per_device_memory

        def scored(plan):
            cand = dataclasses.replace(strategy, remat=sorted(plan))
            res = self._evaluator().evaluate(cand)
            if res is None:
                return None
            return (cand, time + res.total_time - bt,
                    mem + res.per_device_memory - bm)

        marginals = []
        for i in idx:
            r = scored([i])
            if r is not None:
                marginals.append((self._objective(r[1], r[2], lam), i))
        marginals.sort()
        prefix: List[int] = []
        for _, i in marginals:
            prefix.append(i)
            r = scored(prefix)
            if r is not None:
                out.append(r)
        if len(prefix) != len(idx):
            r = scored(idx)  # all-ON even when some marginals pruned
            if r is not None:
                out.append(r)
        return out

    def _placement_variants(self, strategy: Strategy, time: float,
                            mem: int) -> List[Tuple[Strategy, float, int]]:
        """The candidate re-scored at every legal multi-slice placement:
        [(strategy', time', mem')].  The default placement keeps the
        caller's analytic (time, mem); alternatives correct them by the
        memoized evaluator's placement delta (the applied graph is
        placement-invariant, so the delta is exactly the tier re-cost).
        Flat machines return the candidate unchanged."""
        out = [(strategy, time, mem)]
        if not self._hier or strategy.pipeline:
            return out
        from ..topology.hierarchy import legal_placements, resolve_placement

        legal = legal_placements(strategy.mesh_axes, self.slices)
        default = resolve_placement(strategy.mesh_axes, self.slices)
        extra = [p for p in legal if p != default]
        if not extra:
            return out
        base = self._evaluator().evaluate(strategy)
        if base is None:
            return out
        bt, bm = base.total_time, base.per_device_memory
        for p in extra:
            cand = dataclasses.replace(strategy, placement=p)
            res = self._evaluator().evaluate(cand)
            if res is None:
                continue
            out.append((cand, time + res.total_time - bt,
                        mem + res.per_device_memory - bm))
        return out

    def _optimize_graph(self, lam: float, collector: List[Tuple]):
        """Append every valid (obj, strategy, graph) for the CURRENT
        self.graph to collector (mesh factorizations, sp, pp) — each
        non-pipeline candidate expanded across the allowed ZeRO
        stages and (on hierarchy machines) the legal placements."""
        from ..logger import search_logger as slog

        has_moe = any(op.op_type == OperatorType.GROUP_BY for op in self.graph.ops)
        best_obj = math.inf

        def collect(strategy, time, mem, label):
            nonlocal best_obj
            for pcand, pt, pm in self._placement_variants(strategy, time,
                                                          mem):
                for scand, st, sm in self._stage_variants(pcand, pt, pm):
                    for cand, ct, cm in self._remat_variants(scand, st, sm,
                                                             lam):
                        obj = self._objective(ct, cm, lam)
                        slog.debug(
                            "candidate %s%s%s%s: obj=%.3g%s", label,
                            (f" zero{cand.zero_stage}"
                             if cand.zero_stage is not None else ""),
                            (f" place={cand.placement}"
                             if cand.placement is not None else ""),
                            (f" remat={len(cand.remat)}on"
                             if cand.remat else ""),
                            obj, " *best*" if obj < best_obj else "",
                        )
                        best_obj = min(best_obj, obj)
                        collector.append((obj, cand, self.graph))

        for dp, tp, ep in _factorizations(self.n, allow_expert=has_moe):
            for mesh_axes in self._mesh_variants(dp, tp, ep):
                if tp > 1 and not self._options_by_op(mesh_axes):
                    continue  # no op can use the model axis
                r = self._dp(mesh_axes, dp, lam)
                if r is None:
                    continue
                shard_configs, edges, time, mem = r
                strategy = self._build_strategy(
                    mesh_axes, dp, shard_configs, edges
                )
                # validate with the strategy actually applied — through
                # the memoized evaluator, so the lambda binary search's
                # repeat passes validate revisited candidates by lookup
                if self._evaluator().evaluate(strategy) is None:
                    continue
                collect(strategy, time, mem,
                        f"{mesh_axes} time={time * 1e3:.3g}ms "
                        f"mem={mem / 2**20:.1f}MB")
        for strategy, time, mem, label in self._sp_candidates():
            collect(strategy, time, mem, label)
        # pipeline candidates stay on the base stage: their memory
        # model scales block terms by 1/S, which the evaluator's stage
        # delta cannot see (docs/SEARCH.md)
        for strategy, obj, label in self._pp_candidates(lam):
            slog.debug(
                "candidate %s: obj=%.3g%s", label, obj,
                " *best*" if obj < best_obj else "",
            )
            best_obj = min(best_obj, obj)
            collector.append((obj, strategy, self.graph))
        for strategy, time, mem, label in self._sample_candidates():
            collect(strategy, time, mem, label)

    def _event_objective(
        self, strategy: Strategy, graph: Graph, lam: float
    ) -> Optional[float]:
        """Contention-aware objective from the event-driven taskgraph
        simulator (reference simulate_runtime, simulator.cc:822-1250;
        ring expansion :1690-1800) — replaces the analytic model's flat
        overlap credit for the final top-K ranking.

        Pipeline candidates stay on the same scale: the event sim runs
        the applied graph WITHOUT the GPipe schedule (it cannot express
        it), then the block region's share of the makespan is scaled by
        the bubble factor (M+S-1)/(M*S) — so pp is never compared via
        its optimistic analytic number against others' event numbers."""
        from ..logger import search_logger as slog

        try:
            from ..sim.taskgraph import TaskGraphSimulator

            g = apply_strategy(graph, strategy)
            assign_views(g, strategy.mesh_axes)
            res = TaskGraphSimulator(self.machine, self.cost_model).simulate(
                g, strategy.mesh_axes, training=True
            )
            time = res.total_time
            op_scale = None
            if strategy.pipeline:
                from ..parallel.pipeline_plan import plan_pipeline

                plan = plan_pipeline(g, strategy.pipeline, strategy.mesh_axes)
                block_guids = {
                    op.guid for blk in plan.blocks for op in blk
                }
                t_block = t_rest = 0.0
                for op in g.topo_order():
                    if op.op_type == OperatorType.INPUT or op.is_parallel_op():
                        continue
                    t, _ = self._op_cost(op)
                    if op.guid in block_guids:
                        t_block += t
                    else:
                        t_rest += t
                total = t_block + t_rest
                frac = t_block / total if total > 0 else 0.0
                S = plan.num_stages
                M = plan.num_microbatches
                factor = (M + S - 1) / (M * S)
                time = time * ((1.0 - frac) + frac * factor)

                def op_scale(op, _g=block_guids, _s=S):  # noqa: E731
                    return 1.0 / _s if op.guid in _g else 1.0

            # the event simulator models none of the ladder's stage
            # terms (sharded update, opt_xfer, per-layer gather_xfer)
            # nor the hierarchy's tiered comm, while the memory below
            # IS stage/placement-aware — uncorrected, the highest stage
            # of a mesh would always win the rerank (same event time,
            # less memory).  Correct the makespan with the analytic
            # stage+placement delta from the memoized evaluator, the
            # same delta the variant expansions priced the candidate
            # with.
            if ((strategy.zero_stage is not None
                    and strategy.zero_stage != self.zero_stage)
                    or strategy.placement is not None
                    or strategy.remat is not None):
                prev = self.graph
                try:
                    self._set_graph(graph)
                    rb = self._evaluator().evaluate(dataclasses.replace(
                        strategy, zero_stage=self.zero_stage,
                        placement=None, remat=None))
                    rs = self._evaluator().evaluate(strategy)
                finally:
                    self._set_graph(prev)
                if rb is not None and rs is not None:
                    time += rs.total_time - rb.total_time
            if strategy.remat is not None and op_scale is None:
                # plan-carrying candidates (never pipeline) price the
                # remat-aware activation accounting
                mem = self._sim.remat_memory_from_terms(
                    g.topo_order(), strategy.mesh_axes, strategy.remat,
                    training=True, zero_stage=strategy.zero_stage,
                    placement=strategy.placement,
                )
            else:
                mem = self._sim.per_device_memory(
                    g, training=True, op_scale=op_scale,
                    mesh_axes=strategy.mesh_axes,
                    zero_stage=strategy.zero_stage,
                    placement=strategy.placement,
                )
            return self._objective(time, mem, lam)
        except Exception as e:  # noqa: BLE001
            slog.debug(
                "event rerank unavailable for %s: %s: %s",
                strategy.mesh_axes, type(e).__name__, e,
            )
            return None

    def optimize(self, lam: float = 0.0) -> Optional[Strategy]:
        from ..logger import search_logger as slog

        collector: List[Tuple] = []
        with slog.enter(f"unity optimize n={self.n} lambda={lam:g}"):
            for graph, trace in self._variants():
                self._set_graph(graph)
                if trace:
                    slog.debug("rewritten variant: %s",
                               "+".join(f"{n}[{i}]" for n, i in trace))
                before = len(collector)
                self._optimize_graph(lam, collector)
                for i in range(before, len(collector)):
                    collector[i][1].rewrites = [list(r) for r in trace]
            self._set_graph(self._base_graph)
            if not collector:
                return None
            collector.sort(key=lambda c: c[0])
            # diagnostic: winning analytic objective, read by tests and
            # search reporting (not serialized with the strategy)
            for obj, strategy, _g in collector:
                strategy.search_cost = obj
            if not self.event_rerank:
                return self._finish(collector[0][1])
            # re-rank the analytic top-K with the event simulator's
            # contention-aware makespan (reference: candidates are
            # ultimately judged by simulate_runtime, not the analytic
            # estimators)
            # distinct (mesh, zero stage, placement, remat on-count)
            # only — pp candidates differing solely in microbatch count
            # (or remat prefixes differing by one segment) would
            # otherwise crowd the top-K, while stage/placement/remat
            # variants of one mesh are genuinely different trade-offs
            seen_keys = set()
            top: List[Tuple] = []
            for c in collector:
                key = (tuple(sorted(c[1].mesh_axes.items())),
                       c[1].pipeline is None, c[1].zero_stage,
                       c[1].placement,
                       len(c[1].remat) if c[1].remat is not None else None)
                if key in seen_keys:
                    continue
                seen_keys.add(key)
                top.append(c)
                if len(top) >= self.event_topk:
                    break
            best, best_obj = None, math.inf
            for obj, strategy, graph in top:
                e = self._event_objective(strategy, graph, lam)
                final = e if e is not None else obj
                slog.debug(
                    "event rerank %s: analytic=%.3g event=%s%s",
                    strategy.mesh_axes, obj,
                    f"{e:.3g}" if e is not None else "n/a",
                    " *best*" if final < best_obj else "",
                )
                if final < best_obj:
                    best, best_obj = strategy, final
            return self._finish(best if best is not None else collector[0][1])

    def _finish(self, strategy: Strategy) -> Strategy:
        """Attach the observability counters to the winning strategy and
        log them (identical line format to the pre-registry call); with
        a registry wired they also land in run telemetry."""
        from ..logger import search_logger as slog
        from ..obs.metrics import emit_counters
        from ..topology.hierarchy import placement_stats

        strategy.search_stats = self.eval_stats()
        # the winner's multi-slice placement ("" on flat machines) and
        # whether its grad reduction lowers hierarchically — gated on
        # _hier: a slices>1 TpuPodModel that is NOT a SliceHierarchy
        # never searched placements and must not claim one
        strategy.search_stats.update(placement_stats(
            strategy, self.slices if self._hier else 1
        ))
        from .mcmc import remat_stats

        # the winner's per-segment remat plan ("" when no plan chosen)
        strategy.search_stats.update(remat_stats(strategy))
        emit_counters(slog, "unity eval stats", strategy.search_stats,
                      registry=self.registry, group="search/unity")
        return strategy

    def _objective(self, time: float, mem: int, lam: float) -> float:
        """Single ranking formula for ALL candidate families (dp/tp/ep
        and sp): time + lambda*mem, with an over-budget penalty in the
        lam=0 pass."""
        obj = time + lam * mem
        if (
            self.memory_budget is not None
            and lam == 0.0
            and mem > self.memory_budget
        ):
            obj *= 1.0 + (mem / self.memory_budget - 1.0)
        return obj

    def _sp_candidates(self):
        """Sequence-parallel (context-parallel) candidates: dp x sp
        meshes where activations are seq-sharded and attention lowers to
        ring attention over ICI (parallel/ring_attention.py) — the
        long-context strategy slot the reference leaves empty (SURVEY
        §5).  Costed with the same Simulator terms as the DP search plus
        the ring's KV-rotation traffic."""
        has_attn = any(
            op.op_type == OperatorType.MULTIHEAD_ATTENTION for op in self.graph.ops
        )
        if not has_attn:
            return
        sources = [op for op in self.graph.ops
                   if op.op_type == OperatorType.INPUT]
        seq_ok = all(
            op.outputs[0].shape.logical_rank >= 3 for op in sources
        )
        if not seq_ok:
            return
        training = True
        for sp in range(2, self.n + 1):
            if self.n % sp:
                continue
            dp = self.n // sp
            if any(
                op.outputs[0].shape.logical_shape[1] % sp
                for op in sources
            ):
                continue
            mesh_axes = {"seq": sp}
            if dp > 1:
                mesh_axes["data"] = dp
            s = Strategy(mesh_axes=dict(mesh_axes))
            chain = []
            if dp > 1:
                chain.append(("repartition", {"dim": 0, "degree": dp}))
            chain.append(("repartition", {"dim": 1, "degree": sp}))
            s.edge_ops["__inputs__"] = chain
            res = self._evaluator().evaluate(s)
            if res is None:
                continue
            # ring attention KV rotation: ~an allgather of the group's
            # K+V per attention forward; backward re-rotates KV and
            # rotates dK/dV (~2x more); comm overlaps blockwise compute
            ring = 0.0
            for op in res.ops:
                if op.op_type != OperatorType.MULTIHEAD_ATTENTION:
                    continue
                kv_bytes = (
                    op.inputs[1].shape.shard_bytes()
                    + op.inputs[2].shape.shard_bytes()
                ) * sp
                ring += 3.0 * self._comm_time("allgather", kv_bytes, sp)
            time = res.total_time + ring * (1.0 - self.overlap)
            mem = res.per_device_memory
            yield s, time, mem, f"dp={dp} sp={sp} (ring attention)"

    def _sample_candidates(self):
        """Sample parallelism (reference --enable-sample-parallel,
        config.h:134: partition along non-batch sample dims): shard
        inputs' dim 1 (sequence rows / flattened spatial) over a
        'sample' axis.  Attention graphs get this via the richer
        ring-attention sp candidates instead."""
        if not self.enable_sample_parallel:
            return
        if any(op.op_type == OperatorType.MULTIHEAD_ATTENTION
               for op in self.graph.ops):
            return
        sources = [op for op in self.graph.ops
                   if op.op_type == OperatorType.INPUT]
        if not sources or any(
            op.outputs[0].shape.logical_rank < 3 for op in sources
        ):
            return
        for sp in range(2, self.n + 1):
            if self.n % sp:
                continue
            dp = self.n // sp
            if any(
                op.outputs[0].shape.logical_shape[1] % sp
                or op.outputs[0].shape.logical_shape[0] % max(1, dp)
                for op in sources
            ):
                continue
            mesh_axes = {"sample": sp}
            if dp > 1:
                mesh_axes = {"data": dp, "sample": sp}
            s = Strategy(mesh_axes=dict(mesh_axes))
            chain = []
            if dp > 1:
                chain.append(("repartition", {"dim": 0, "degree": dp}))
            chain.append(("repartition", {"dim": 1, "degree": sp}))
            s.edge_ops["__inputs__"] = chain
            res = self._evaluator().evaluate(s)
            if res is None:
                continue
            yield (s, res.total_time, res.per_device_memory,
                   f"dp={dp} sample={sp} (sample parallel)")

    def _pp_candidates(self, lam: float):
        """Pipeline-parallel candidates: dp x pp meshes over the graph's
        homogeneous block stack (parallel/pipeline_plan.py), ranked with
        the standard GPipe terms — bubble fraction (S-1)/(M+S-1) on the
        block region plus per-tick ppermute traffic over ICI.  The
        reference's vestigial PIPELINE_* hooks (model.h:190-192) made a
        searchable strategy per SURVEY §2.3."""
        from ..parallel.pipeline_plan import plan_pipeline
        from .segments import find_repeated_blocks

        if not self.enable_pipeline:
            return
        blocks = find_repeated_blocks(self.graph)
        L = len(blocks)
        if L < 2:
            return
        block_names = {op.name for blk in blocks for op in blk}
        sources = [op for op in self.graph.ops
                   if op.op_type == OperatorType.INPUT]
        if not sources:
            return
        b = sources[0].outputs[0].shape.logical_shape[0]
        # boundary activation: block 1's single external input tensor
        from .segments import external_inputs

        ext = external_inputs(blocks[1])
        if len(ext) != 1:
            return  # plan_pipeline would reject this region too
        by_guid = {t.guid: t for op in self.graph.ops for t in op.outputs}
        boundary_t = by_guid[ext[0]]
        for pp in range(2, min(self.n, L) + 1):
            if self.n % pp or L % pp:
                continue
            dp = self.n // pp
            if b % dp:
                continue
            local_b = b // dp
            mbs = sorted({m for m in (pp, 2 * pp, 4 * pp, local_b)
                          if 1 < m <= local_b and local_b % m == 0})
            if not mbs:
                continue
            s0 = Strategy(mesh_axes={"data": dp})
            if dp > 1:
                s0.edge_ops["__inputs__"] = [
                    ("repartition", {"dim": 0, "degree": dp})
                ]
            try:
                g = apply_strategy(self.graph, s0)
            except (ShapeError, ValueError):
                continue
            t_block = t_rest = 0.0
            mem_block = mem_rest = 0
            dp_axes = {"data": dp}
            for op in g.topo_order():
                if op.op_type == OperatorType.INPUT:
                    continue
                if op.is_parallel_op():
                    t = (2.0 * self._sim.xfer_cost(op, dp_axes)
                         * (1.0 - self.overlap))
                    m = 0
                else:
                    t, m = self._op_cost(op)
                if op.name in block_names:
                    t_block += t
                    mem_block += m
                else:
                    t_rest += t
                    mem_rest += m
            act_bytes = max(1, boundary_t.shape.size_bytes() // dp)

            def mk_strategy(M: int) -> Strategy:
                mesh_axes = {"data": dp, "pipe": pp} if dp > 1 else {"pipe": pp}
                s = Strategy(
                    mesh_axes=mesh_axes,
                    pipeline={
                        "degree": pp,
                        "num_microbatches": M,
                        "axis": "pipe",
                        "dp_axis": "data" if dp > 1 else None,
                    },
                )
                if dp > 1:
                    s.edge_ops["__inputs__"] = [
                        ("repartition", {"dim": 0, "degree": dp})
                    ]
                return s

            # validate once per pp degree — the applied graph and plan
            # are independent of M (mbs already guarantees divisibility)
            probe = mk_strategy(mbs[0])
            try:
                gg = apply_strategy(self.graph, probe)
                assign_views(gg, probe.mesh_axes)
                plan_pipeline(gg, probe.pipeline, probe.mesh_axes)
            except (ShapeError, ValueError):
                continue
            for M in mbs:
                # region wall time: (M+S-1)/(M*S) of the dp-sharded
                # block total (compute+sync), i.e. /S with GPipe bubble
                region = t_block * (M + pp - 1) / (M * pp)
                # fwd activation shift + bwd grad shift per tick
                ring = 2.0 * (M + pp - 2) * self._comm_time(
                    "allgather", max(1, act_bytes // M), 2
                )
                time = t_rest + region + ring * (1.0 - self.overlap)
                mem = mem_rest + mem_block // pp
                obj = self._objective(time, mem, lam)
                yield mk_strategy(M), obj, f"dp={dp} pp={pp} M={M} (gpipe)"

    def optimize_with_memory(self) -> Optional[Strategy]:
        """Lambda binary search (reference try_one_lambda + binary search,
        graph.cc:2056-2131): smallest lambda whose best strategy fits the
        per-device memory budget, 10 iterations."""
        best = self.optimize(0.0)
        if best is None or self.memory_budget is None:
            return best
        if self._strategy_memory(best) <= self.memory_budget:
            return best
        lo, hi = 0.0, self._lambda_hi()
        chosen = best
        for _ in range(10):
            mid = (lo + hi) / 2.0
            cand = self.optimize(mid)
            if cand is not None and self._strategy_memory(cand) <= self.memory_budget:
                chosen, hi = cand, mid
            else:
                lo = mid
        # the winner's stats snapshot dates from the pass that found it;
        # re-attach the whole-search cumulative counters
        return self._finish(chosen)

    def _lambda_hi(self) -> float:
        # scale so the memory term can dominate: time-per-byte at HBM speed
        dev = self.machine.device()
        return 100.0 / dev.hbm_bandwidth

    def _strategy_memory(self, strategy: Strategy) -> int:
        from ..sim.simulator import Simulator

        base = self._base_graph
        if strategy.rewrites:
            from .rewrite import apply_rewrites, generate_rewrite_rules

            rules = (list(self.rewrite_rules) if self.rewrite_rules is not None
                     else generate_rewrite_rules())
            base = apply_rewrites(base, strategy.rewrites, rules)
        g = apply_strategy(base, strategy)
        assign_views(g, strategy.mesh_axes)
        # mirror the cost simulator's gating exactly (parameter_sync
        # and the candidate's own ZeRO stage included) so the memory
        # the lambda search constrains is the memory the time model
        # believes in
        sim = Simulator(self.machine, self.cost_model,
                        optimizer_slots=self.optimizer_slots,
                        remat=self.remat,
                        parameter_sync=self.parameter_sync,
                        zero_stage=(
                            strategy.zero_stage
                            if strategy.zero_stage is not None
                            else self.zero_stage
                        ),
                        wus_axis=self.wus_axis)
        op_scale = None
        if strategy.pipeline:
            # each device holds only its stage's 1/S of the block stack
            from ..parallel.pipeline_plan import plan_pipeline

            plan = plan_pipeline(g, strategy.pipeline, strategy.mesh_axes)
            block_guids = {op.guid for blk in plan.blocks for op in blk}
            S = plan.num_stages

            def op_scale(op, _g=block_guids, _s=S):  # noqa: E731
                return 1.0 / _s if op.guid in _g else 1.0

        if getattr(strategy, "remat", None) is not None and op_scale is None:
            # a searched per-segment plan prices the remat-aware
            # activation accounting — the same model the variants were
            # ranked with, so the budget check and the ranking agree
            return sim.remat_memory_from_terms(
                g.topo_order(), strategy.mesh_axes, strategy.remat,
                training=True, placement=strategy.placement,
            )
        return sim.per_device_memory(g, training=True, op_scale=op_scale,
                                     mesh_axes=strategy.mesh_axes,
                                     placement=strategy.placement)


def _sync_mode(pst) -> str:
    """ParameterSyncType -> Simulator.parameter_sync string."""
    from ..fftype import ParameterSyncType

    if pst == ParameterSyncType.PS:
        return "ps"
    if pst == ParameterSyncType.NONE:
        return "none"
    return "allreduce"


def unity_optimize(model, num_devices: int,
                   enable_pipeline: bool = True) -> Strategy:
    """Entry used by FFModel.compile (reference GRAPH_OPTIMIZE_TASK_ID ->
    Graph::graph_optimize_task graph.cc:2046)."""
    from ..sim.machine_model import make_machine_model
    from ..sim.simulator import make_cost_model

    cfg = model.config
    machine = make_machine_model(cfg, num_devices)
    cost_model = make_cost_model(cfg, machine)
    from .rewrite import catalog_for_config, rules_for_config

    xfers = generate_all_pcg_xfers()
    catalog = catalog_for_config(cfg)
    if catalog:
        xfers = xfers + load_substitution_rules(catalog)
    rewrite_rules = rules_for_config(cfg)
    # fitted overlap constants (sim/calibrate.py) take precedence over
    # the hand-set priors when a calibration has been persisted
    from ..sim.calibrate import load_overlap_constants

    fitted = load_overlap_constants()
    overlap_kw = {}
    if fitted is not None:
        overlap_kw["overlap_fraction"] = fitted["overlap_fraction"]
        overlap_kw["compute_scale"] = fitted.get("compute_scale", 1.0)
    search = UnitySearch(
        model.layers,
        num_devices,
        machine,
        cost_model,
        xfers=xfers,
        enable_parameter_parallel=cfg.enable_parameter_parallel,
        enable_attribute_parallel=cfg.enable_attribute_parallel,
        budget=max(0, cfg.search_budget),
        memory_budget=cfg.memory_per_device if cfg.memory_search else None,
        rewrite_rules=rewrite_rules,
        # backward/update overlap: credit gradient sync as mostly hidden
        # behind remaining backward compute (reference config.h:130);
        # a fitted constant replaces the 0.7 prior
        sync_overlap_fraction=(
            fitted["sync_overlap_fraction"] if fitted is not None
            else (0.7 if cfg.search_overlap_backward_update else None)
        ),
        **overlap_kw,
        parameter_sync=_sync_mode(cfg.parameter_sync),
        max_assignments=cfg.simulator_segment_size,
        enable_sample_parallel=cfg.enable_sample_parallel,
        remat=cfg.remat,
        rewrite_depth=cfg.rewrite_depth,
        rewrite_max_variants=cfg.rewrite_max_variants,
        eval_cache=cfg.search_eval_cache,
        zero_stage=cfg.zero_stage,
        zero_stages=search_stage_candidates(cfg),
        wus_axis=cfg.wus_axis,
        registry=getattr(
            getattr(model, "telemetry", None), "metrics", None
        ),
        enable_pipeline=enable_pipeline,
        remat_search=search_remat_enabled(cfg),
        dcn_bucket_bytes=float(getattr(cfg, "dcn_bucket_mb", 25.0)) * 2**20,
    )
    best = search.optimize_with_memory() if cfg.memory_search else search.optimize()
    cost_model.save_persistent()
    if best is None:
        from ..strategy import data_parallel_strategy

        return data_parallel_strategy(num_devices)
    # surface the ZeRO stage the winner was scored under (and the
    # legacy bool it subsumes)
    chosen = best.zero_stage if best.zero_stage is not None else cfg.zero_stage
    best.search_stats["zero_stage"] = int(chosen)
    best.search_stats["weight_update_sharding"] = chosen >= 1
    return best
