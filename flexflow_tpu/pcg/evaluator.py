"""Incremental strategy evaluator: memoization + delta simulation.

Reference: the FlexFlow simulator's headline trick is *delta simulation*
(simulate_runtime / mcmc_optimize lineage) — after an MCMC substitution
it re-simulates only the tasks affected by the changed op, not the
whole task graph.  The SPMD rewrite re-casts that at strategy
granularity on top of sim/simulator.py's per-op term decomposition:

  * **strategy memo** — a canonical signature of (mesh_axes,
    shard_configs, edge_ops, rewrites, pipeline) keys a SimResult cache,
    so revisited states (common under Metropolis rejection and propagate
    moves) cost a dict lookup instead of a simulation;
  * **delta apply** — when a candidate differs from the last applied
    state only in per-op ShardConfigs, only the *dirty frontier* (the
    changed ops plus downstream ops whose input parallel shapes changed)
    is re-instantiated, re-propagated and re-viewed; every clean op
    reuses its applied record — and its cached OpTerms — from the base;
  * **exactness invariant** — delta_eval(state) == full_eval(state)
    bit-for-bit: both paths hand the same topo-ordered op sequence to
    Simulator.simulate_ops, which sums identical cached OpTerms in
    identical order (tests/test_search_cache.py enforces this).

Both searches (pcg/mcmc.py, pcg/unity.py) evaluate through this class;
EvalStats carries the observability counters they log and return.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, FrozenSet, List, Optional, Tuple

from ..fftype import OperatorType
from ..ops.op import Op, ShardConfig
from ..sim.simulator import SimResult, Simulator
from ..strategy import (
    Strategy,
    assign_op_views,
    build_edge_chain,
    edge_chain_for,
    reapply_op,
)
from .graph import Graph


def _freeze(v):
    """Recursively hashable form of JSON-ish strategy payloads."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    return v


def _shard_key(sc: ShardConfig) -> Tuple[int, int, int, int]:
    return (sc.channel, sc.reduction, sc.attribute, sc.expert)


def _shard_map(strategy: Strategy) -> Dict[str, Tuple[int, int, int, int]]:
    """Non-trivial configs only: a trivial ShardConfig entry is
    indistinguishable from an absent one under apply_strategy."""
    return {
        name: _shard_key(sc)
        for name, sc in strategy.shard_configs.items()
        if not sc.is_trivial()
    }


def strategy_signature(strategy: Strategy) -> Tuple:
    """Canonical memo key.  mesh_axes keeps its insertion ORDER (axis
    order steers how assign_axes factors degrees onto axes of equal
    size); shard_configs and edge_ops are order-normalized.  The ZeRO
    stage is part of the key: the same sharding costed at different
    rungs of the ladder is a different candidate — and so is the same
    sharding under a different per-segment remat plan."""
    remat = getattr(strategy, "remat", None)
    return (
        tuple(strategy.mesh_axes.items()),
        tuple(sorted(_shard_map(strategy).items())),
        _freeze(strategy.edge_ops),
        _freeze(strategy.rewrites),
        _freeze(strategy.pipeline),
        getattr(strategy, "zero_stage", None),
        getattr(strategy, "placement", None),
        tuple(remat) if remat is not None else None,
    )


@dataclasses.dataclass
class EvalStats:
    """Search-evaluation observability counters (tentpole part 3)."""

    evals: int = 0          # evaluate() calls
    memo_hits: int = 0      # answered by the strategy memo
    full_evals: int = 0     # full apply + simulate
    delta_evals: int = 0    # dirty-frontier apply + cached-term re-sum
    illegal_evals: int = 0  # candidates pruned by Shape/ValueError
    dirty_ops: int = 0      # Σ dirty-frontier sizes over delta evals
    eval_seconds: float = 0.0

    @property
    def evals_per_sec(self) -> float:
        return self.evals / self.eval_seconds if self.eval_seconds > 0 else 0.0

    @property
    def mean_dirty_frontier(self) -> float:
        return self.dirty_ops / self.delta_evals if self.delta_evals else 0.0

    def as_dict(self) -> Dict[str, float]:
        d = dataclasses.asdict(self)
        d["evals_per_sec"] = self.evals_per_sec
        d["mean_dirty_frontier"] = self.mean_dirty_frontier
        return d

    def summary(self) -> str:
        return (
            f"evals={self.evals} memo_hits={self.memo_hits} "
            f"full={self.full_evals} delta={self.delta_evals} "
            f"illegal={self.illegal_evals} "
            f"mean_frontier={self.mean_dirty_frontier:.1f} "
            f"evals/s={self.evals_per_sec:.0f}"
        )


@dataclasses.dataclass
class _OpRecord:
    """One frontend op's applied unit: the re-instantiated op plus its
    edge-chain parallel ops, in insertion order."""

    applied: List[Op]
    out_map: Dict[int, object]  # frontend out guid -> applied tensor
    in_shapes: Tuple


@dataclasses.dataclass
class _AppliedState:
    """The last successfully applied strategy — the delta base."""

    mesh_items: Tuple
    edges_key: Tuple
    trace_key: Tuple
    shard_map: Dict[str, Tuple[int, int, int, int]]
    records: Dict[int, _OpRecord]  # frontend op guid -> record
    order: List[Op]                # simulation order (applied ops)


class IncrementalEvaluator:
    """Memoized + delta evaluator for one frontend graph.

    evaluate(strategy) returns the strategy's SimResult (with `ops`, the
    applied topo-ordered op sequence, attached) or None when the
    candidate is illegal (ShapeError / unfactorable view).  The applied
    graphs it builds are cost-model shadows: weight initializers and
    gradient flags are NOT carried over from the frontend (the simulator
    never reads them) — use strategy.apply_strategy for execution.

    Memo retention is bounded by sharing: a delta state's op sequence
    reuses every clean op of its base, so distinct memoized states
    retain roughly their dirty frontiers (a few ops each), not whole
    graphs; fresh full graphs only accumulate one per distinct
    (mesh, edge-chain) structure visited.
    """

    def __init__(self, graph: Graph, simulator: Simulator,
                 training: bool = True, use_cache: bool = True):
        self.graph = graph
        self.topo = graph.topo_order()
        self.sim = simulator
        self.training = training
        self.use_cache = use_cache
        self.stats = EvalStats()
        self._memo: Dict[Tuple, Optional[SimResult]] = {}
        self._base: Optional[_AppliedState] = None

    # -- public ----------------------------------------------------------
    def evaluate(self, strategy: Strategy) -> Optional[SimResult]:
        t0 = time.perf_counter()
        self.stats.evals += 1
        sig = strategy_signature(strategy) if self.use_cache else None
        if sig is not None and sig in self._memo:
            self.stats.memo_hits += 1
            self.stats.eval_seconds += time.perf_counter() - t0
            return self._memo[sig]
        try:
            res = self._evaluate_uncached(strategy)
        except ValueError:  # ShapeError / unfactorable view -> illegal
            self.stats.illegal_evals += 1
            res = None
        if sig is not None:
            self._memo[sig] = res
        self.stats.eval_seconds += time.perf_counter() - t0
        return res

    # -- construction ----------------------------------------------------
    def _build_record(self, op: Op, in_pts: List, in_shapes: Tuple,
                      strategy: Strategy, input_chain: List) -> _OpRecord:
        applied: List[Op] = []
        new_op = reapply_op(op, in_pts, strategy)
        applied.append(new_op)
        out_map: Dict[int, object] = {}
        for old_out, new_out in zip(op.outputs, new_op.outputs):
            chain = edge_chain_for(op, old_out, strategy, input_chain)
            out_map[old_out.guid] = build_edge_chain(new_out, chain,
                                                     applied.append)
        return _OpRecord(applied=applied, out_map=out_map, in_shapes=in_shapes)

    def _apply(
        self, strategy: Strategy, base: Optional[_AppliedState],
        dirty: FrozenSet[str],
    ) -> Tuple[Dict[int, _OpRecord], List[Op], List[Tuple[int, _OpRecord]]]:
        """Walk the frontend topo order building applied records; under a
        delta (base given), reuse the base record of every op that is
        config-clean AND sees unchanged input shapes — the rebuilt list
        is exactly the dirty frontier."""
        input_chain = strategy.edge_ops.get("__inputs__", [])
        records: Dict[int, _OpRecord] = {}
        tensor_map: Dict[int, object] = {}
        new_ops: List[Op] = []
        rebuilt: List[Tuple[int, _OpRecord]] = []
        for op in self.topo:
            if op.op_type == OperatorType.INPUT:
                in_pts: List = []
                in_shapes: Tuple = ()
            else:
                in_pts = [tensor_map[t.guid] for t in op.inputs]
                in_shapes = tuple(pt.shape for pt in in_pts)
            rec = None
            if base is not None and op.name not in dirty:
                brec = base.records.get(op.guid)
                if brec is not None and brec.in_shapes == in_shapes:
                    rec = brec
            if rec is None:
                rec = self._build_record(op, in_pts, in_shapes, strategy,
                                         input_chain)
                rebuilt.append((op.guid, rec))
            records[op.guid] = rec
            tensor_map.update(rec.out_map)
            new_ops.extend(rec.applied)
        return records, new_ops, rebuilt

    def _dirty_set(self, strategy: Strategy,
                   base: _AppliedState) -> Optional[FrozenSet[str]]:
        """Op names whose ShardConfig changed vs the base, or None when
        the candidate is not delta-eligible (different mesh / edge
        chains / rewrite trace — or a memory model that needs
        whole-graph structure)."""
        if not self.training:
            return None  # inference liveness memory needs full wiring
        if self.sim.remat and getattr(strategy, "remat", None) is None:
            # legacy bool remat prices memory via the whole-graph
            # _remat_peak; a strategy-carried PLAN instead uses the
            # order-based accounting, which delta-evaluates fine
            return None
        if tuple(strategy.mesh_axes.items()) != base.mesh_items:
            return None
        if _freeze(strategy.edge_ops) != base.edges_key:
            return None
        if (_freeze(strategy.rewrites), _freeze(strategy.pipeline)) != base.trace_key:
            return None
        new_map = _shard_map(strategy)
        dirty = {
            name
            for name in set(new_map) | set(base.shard_map)
            if new_map.get(name) != base.shard_map.get(name)
        }
        return frozenset(dirty)

    def _evaluate_uncached(self, strategy: Strategy) -> SimResult:
        # use_cache=False is the reference path: every evaluation is a
        # full apply+simulate (the invariant tests diff against it)
        base = self._base if self.use_cache else None
        dirty = self._dirty_set(strategy, base) if base is not None else None
        if dirty is not None:
            records, new_ops, rebuilt = self._apply(strategy, base, dirty)
        else:
            records, new_ops, rebuilt = self._apply(strategy, None,
                                                    frozenset())
        for _, rec in rebuilt:  # clean reused ops keep their base views
            for op_ in rec.applied:
                assign_op_views(op_, strategy.mesh_axes)
        if dirty is not None:
            # positional substitution preserves the base's simulation
            # order: the graphs are isomorphic, so a fresh topo sort
            # would produce the same permutation anyway
            repl = {}
            for guid, rec in rebuilt:
                for old_op, new_op in zip(base.records[guid].applied,
                                          rec.applied):
                    repl[id(old_op)] = new_op
            order = [repl.get(id(o), o) for o in base.order]
            graph = None
            self.stats.delta_evals += 1
            self.stats.dirty_ops += len(rebuilt)
        else:
            graph = Graph(new_ops)
            order = graph.topo_order()
            self.stats.full_evals += 1
        mesh_axes = strategy.mesh_axes
        # the strategy's search-chosen ZeRO stage and multi-slice
        # placement override the simulator defaults per evaluation; the
        # applied graph depends on neither, so delta bases stay valid
        # across both (OpTerms are cached per stage AND placement)
        stage = getattr(strategy, "zero_stage", None)
        placement = getattr(strategy, "placement", None)
        plan = getattr(strategy, "remat", None)
        if self.training and plan is not None:
            # searched per-segment remat: the order-based accounting
            # works on the delta path (no Graph needed)
            memory_fn = lambda: self.sim.remat_memory_from_terms(  # noqa: E731
                order, mesh_axes, plan, self.training, zero_stage=stage,
                placement=placement,
            )
        elif self.training and not self.sim.remat:
            memory_fn = lambda: self.sim.memory_from_terms(  # noqa: E731
                order, mesh_axes, self.training, zero_stage=stage,
                placement=placement,
            )
        else:
            memory_fn = lambda: self.sim.per_device_memory(  # noqa: E731
                graph, self.training, mesh_axes=mesh_axes, zero_stage=stage,
                placement=placement,
            )
        res = self.sim.simulate_ops(order, mesh_axes, training=self.training,
                                    memory_fn=memory_fn, zero_stage=stage,
                                    placement=placement, remat_plan=plan)
        res.ops = order  # applied op sequence, for callers needing shapes
        self._base = _AppliedState(
            mesh_items=tuple(mesh_axes.items()),
            edges_key=_freeze(strategy.edge_ops),
            trace_key=(_freeze(strategy.rewrites), _freeze(strategy.pipeline)),
            shard_map=_shard_map(strategy),
            records=records,
            order=order,
        )
        return res
