"""TASO pattern-graph substitution rules: loader + generic match/apply.

Reference: the substitution JSON schema (substitution_loader.h:143-179,
substitution_loader.cc), rule -> GraphXfer conversion at a concrete
parallel degree (create_xfer/create_xfers, substitution.cc:1456-1680),
GraphXfer matching (can_match/match, substitution.cc:235-414) and dst
instantiation (create_new_operator, substitution.cc:832-1120).  The
shipped catalog `substitutions/graph_subst_3_v2.json` holds 640
srcOp->dstOp pattern rules over {Partition, Combine, Replicate,
Reduction, Linear, Relu, EwAdd, EwMul, Concat, Split}.

This module parses that exact file format into a neutral rule IR
(`TasoRule`) and compiles each rule into a generic `PatternRule` — a
`RewriteRule` (pcg/rewrite.py) whose src pattern is matched by
backtracking subgraph isomorphism and whose dst subgraph is built from
the pattern, so the whole catalog participates in `enumerate_variants`
/ the Unity search like any built-in rewrite.

Deliberate divergences from the reference, all load-bearing:

  * PM_ACTI values in the catalog are TASO-native (0=NONE, 1=SIGMOID,
    2=RELU, 3=TANH); the reference compares them raw against ffconst
    AC_MODE_* (10..14, ffconst.h:4-10) so its linear rules can never
    match (can_match substitution.cc:252 vs linear.cc:746-754).  We
    remap so they can fire.
  * PM_NUMDIM is answered by no reference op's get_int_parameter
    (model.cc:1043-1057), so every concat rule asserts/never matches
    there.  Here it is the tensor's logical rank.
  * Catalog dims are TASO/Legion column-major (0 = innermost); our
    tensors are row-major logical (tensor.py), converted per-match via
    the concrete tensor's rank.
  * Catalog OP_REPLICATE / OP_REDUCE carry the reference's
    size-changing semantics (replicate.cc:74-75: size *= degree;
    reduction.cc:74-77: size /= degree — d stacked copies / fold-sum of
    d slices), which is what lets the catalog trade an elementwise add
    for concat+reduce.  They map to the first-class StackReplicate /
    FoldReduce compute ops (parallel/parallel_op.py), NOT to our
    replica-dim Replicate/Reduction (which are size-preserving
    annotations with different semantics).
  * Like the reference (get_num_inputs substitution.cc:1416: OP_LINEAR
    -> 1), a linear's declared weight input is dropped; rules whose src
    pattern becomes disconnected by that truncation are rejected
    (`convert_rules` reports them) instead of matching arbitrary
    unrelated subgraphs.

Compute-restructuring rules (linear/concat reassociation) are exact
function-family equivalences up to weight re-packing — the weight
tensors are per-op here (as in the reference), so the rewritten model
trains the same function class at the same FLOPs; parallel-op-only
rules are exact numerical identities.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

from ..fftype import ActiMode, OperatorType, OpBinary, OpUnary
from ..ops.op import Op
from ..tensor import ParallelTensor
from .graph import Graph
from .rewrite import Match, RewriteRule, clone_op


# --------------------------------------------------------------------------
# Rule IR + parser (reference substitution_loader.{h,cc})
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TensorRef:
    """A pattern tensor: output `ts_id` of pattern op `op_id`, or an
    external input when op_id < 0 (reference sl::Tensor)."""

    op_id: int
    ts_id: int


@dataclasses.dataclass(frozen=True)
class TasoOp:
    """One pattern operator (reference sl::Operator)."""

    type: str  # catalog name, e.g. "OP_PARTITION"
    inputs: Tuple[TensorRef, ...]
    params: Tuple[Tuple[str, int], ...]  # ordered (key, value)

    def at(self, key: str) -> Optional[int]:
        for k, v in self.params:
            if k == key:
                return v
        return None


@dataclasses.dataclass(frozen=True)
class MapOutput:
    src_op_id: int
    src_ts_id: int
    dst_op_id: int
    dst_ts_id: int


@dataclasses.dataclass(frozen=True)
class TasoRule:
    name: str
    src_ops: Tuple[TasoOp, ...]
    dst_ops: Tuple[TasoOp, ...]
    mapped_outputs: Tuple[MapOutput, ...]


def _parse_op(j: dict) -> TasoOp:
    return TasoOp(
        type=j["type"],
        inputs=tuple(TensorRef(t["opId"], t["tsId"]) for t in j.get("input", [])),
        params=tuple((p["key"], p["value"]) for p in j.get("para", [])),
    )


def parse_rule_collection(path: str) -> List[TasoRule]:
    """Parse the reference's substitution catalog — either the JSON
    twin (RuleCollection schema) or the binary .pb the reference
    actually ships/loads (decoded by pcg/taso_pb.py).  Faithful:
    returns every rule in the file, including ones this engine later
    rejects as unusable."""
    from .taso_pb import looks_like_pb, pb_to_dict

    d = None
    if looks_like_pb(path):
        try:
            d = pb_to_dict(path)
        except ValueError:
            d = None  # mis-sniff (0x0A is '\n'): fall back to JSON
    if d is None:
        with open(path) as f:
            d = json.load(f)
    if d.get("_t") != "RuleCollection" or "rule" not in d:
        raise ValueError(f"{path}: not a TASO RuleCollection file")
    rules = []
    for rj in d["rule"]:
        rules.append(
            TasoRule(
                name=rj["name"],
                src_ops=tuple(_parse_op(o) for o in rj["srcOp"]),
                dst_ops=tuple(_parse_op(o) for o in rj["dstOp"]),
                mapped_outputs=tuple(
                    MapOutput(m["srcOpId"], m["srcTsId"], m["dstOpId"], m["dstTsId"])
                    for m in rj["mappedOutput"]
                ),
            )
        )
    return rules


def is_taso_rule_file(path: str) -> bool:
    from .taso_pb import looks_like_pb

    if looks_like_pb(path):
        try:
            with open(path, "rb") as f:
                head = f.read(4096)
        except OSError:
            return False
        if head.lstrip()[:1] == b"{":
            # newline-led JSON sniffed as pb (0x0A is '\n'): decide by
            # content so repo-format rewrite JSONs aren't misrouted
            return b'"RuleCollection"' in head
        # genuine binary: catalog or error either way — let
        # parse_rule_collection produce the clean diagnosis rather
        # than fully parsing twice here
        return True
    try:
        with open(path) as f:
            head = f.read(4096)
        return '"RuleCollection"' in head
    except (OSError, UnicodeDecodeError):
        return False


# --------------------------------------------------------------------------
# Catalog-op semantics tables
# --------------------------------------------------------------------------

# reference get_num_inputs (substitution.cc:1416-1454): binary ops take
# 2, concat takes PM_NUM_INPUTS, everything else (incl. linear, whose
# declared weight input is dropped) takes 1.
def _num_inputs(op: TasoOp) -> int:
    if op.type in ("OP_EW_ADD", "OP_EW_SUB", "OP_EW_MUL", "OP_EW_DIV",
                   "OP_EW_MAX", "OP_EW_MIN"):
        return 2
    if op.type == "OP_CONCAT":
        n = op.at("PM_NUM_INPUTS")
        if n is None:
            raise UnsupportedRule("concat without PM_NUM_INPUTS")
        return n
    return 1


def _num_outputs(op: TasoOp) -> int:
    if op.type == "OP_SPLIT":
        n = op.at("PM_NUM_OUTPUTS")
        if n is None:
            raise UnsupportedRule("split without PM_NUM_OUTPUTS")
        return n
    return 1


# TASO-native ActiMode (the generator's enum), see module docstring.
_TASO_ACTI = {0: ActiMode.NONE, 1: ActiMode.SIGMOID, 2: ActiMode.RELU,
              3: ActiMode.TANH}

_PARALLEL_TYPES = {"OP_PARTITION": OperatorType.REPARTITION,
                   "OP_COMBINE": OperatorType.COMBINE,
                   "OP_REPLICATE": OperatorType.REPLICATE_STACK,
                   "OP_REDUCE": OperatorType.REDUCTION_FOLD}

_EW_BINARY = {"OP_EW_ADD": OpBinary.ADD, "OP_EW_SUB": OpBinary.SUB,
              "OP_EW_MUL": OpBinary.MUL, "OP_EW_DIV": OpBinary.DIV,
              "OP_EW_MAX": OpBinary.MAX, "OP_EW_MIN": OpBinary.MIN}

_EW_UNARY = {"OP_RELU": OpUnary.RELU, "OP_SIGMOID": OpUnary.SIGMOID,
             "OP_TANH": OpUnary.TANH, "OP_EXP": OpUnary.EXP,
             "OP_IDENTITY": OpUnary.IDENTITY}

SUPPORTED_TYPES = (set(_PARALLEL_TYPES) | set(_EW_BINARY) | set(_EW_UNARY)
                   | {"OP_LINEAR", "OP_CONCAT", "OP_SPLIT"})


class UnsupportedRule(ValueError):
    """Rule cannot be compiled into this IR; carries the reason."""


def _logical_rank(t: ParallelTensor) -> int:
    return t.shape.logical_rank


def _col_to_row(dim: int, rank: int) -> int:
    """Catalog column-major dim -> row-major logical index."""
    if dim < 0 or dim >= rank:
        raise UnsupportedRule(f"dim {dim} out of range for rank {rank}")
    return rank - 1 - dim


# --------------------------------------------------------------------------
# The generic pattern rule
# --------------------------------------------------------------------------

class PatternRule(RewriteRule):
    """A catalog rule compiled at a concrete parallel degree.

    Matching mirrors GraphXfer::can_match (substitution.cc:235): per
    pattern op, op-type + parameter constraints + exact input wiring
    (pattern input slot j must be the matched producer's output, or a
    consistently-bound external).  Matches are found by backtracking in
    pattern dependency order over type-indexed candidates.
    """

    def __init__(self, rule: TasoRule, degree: int):
        self.rule = rule
        self.degree = degree
        self.name = f"{rule.name}@{degree}"
        self._src = self._compile_side(rule.src_ops)
        self._dst = self._compile_side(rule.dst_ops)
        self._validate()

    # -- compilation -----------------------------------------------------
    def _compile_side(self, ops: Sequence[TasoOp]):
        compiled = []
        for i, op in enumerate(ops):
            if op.type not in SUPPORTED_TYPES:
                raise UnsupportedRule(f"op type {op.type}")
            n_in = _num_inputs(op)
            inputs = op.inputs[:n_in]
            if len(inputs) < n_in:
                raise UnsupportedRule(f"{op.type} missing inputs")
            for ref in inputs:
                if ref.op_id >= i:
                    raise UnsupportedRule("pattern not in dependency order")
            compiled.append((op, inputs))
        return compiled

    def _validate(self):
        # uses_parallel decides degree-instantiation (see convert_rules)
        self.uses_parallel = any(
            op.type in _PARALLEL_TYPES
            for op, _ in (self._src + self._dst)
        )
        # reference create_xfers skips trivial 1->1 rules
        if len(self._src) == 1 and len(self._dst) == 1:
            raise UnsupportedRule("trivial 1->1 rule")
        # src pattern must stay connected after weight-input truncation
        # (treating shared externals as connections), else matching would
        # pair unrelated subgraphs
        n = len(self._src)
        adj = [set() for _ in range(n)]
        ext_users: Dict[int, List[int]] = {}
        for i, (op, inputs) in enumerate(self._src):
            for ref in inputs:
                if ref.op_id >= 0:
                    adj[i].add(ref.op_id)
                    adj[ref.op_id].add(i)
                else:
                    ext_users.setdefault(ref.op_id, []).append(i)
        for users in ext_users.values():
            for u in users[1:]:
                adj[users[0]].add(u)
                adj[u].add(users[0])
        seen = {0}
        stack = [0]
        while stack:
            x = stack.pop()
            for y in adj[x]:
                if y not in seen:
                    seen.add(y)
                    stack.append(y)
        if len(seen) != n:
            raise UnsupportedRule("src pattern disconnected after truncation")
        # attribute-carrying dst ops need exactly one same-type src op to
        # copy params from (reference find_opx_with_type asserts this,
        # substitution.cc:1521-1533)
        src_linears = [i for i, (op, _) in enumerate(self._src)
                       if op.type == "OP_LINEAR"]
        for op, _ in self._dst:
            if op.type == "OP_LINEAR" and len(src_linears) != 1:
                raise UnsupportedRule(
                    f"dst linear needs exactly 1 src linear, have {len(src_linears)}"
                )
        self._src_linear_idx = src_linears[0] if src_linears else None
        # every external the dst consumes must be bound by the src match
        # (truncation can strip the only src use of an external — the
        # reference would hit the mappedInputs assert at
        # substitution.cc:813; reject statically instead)
        src_exts = {r.op_id for _, inputs in self._src for r in inputs
                    if r.op_id < 0}
        for _, inputs in self._dst:
            for r in inputs:
                if r.op_id < 0 and r.op_id not in src_exts:
                    raise UnsupportedRule("dst uses external unbound by src")
        # every src output consumed by another src op is internal; the
        # remaining (externally visible) ones must be covered by
        # mappedOutput or apply() would drop consumers.  Checked lazily in
        # apply via KeyError -> None, but reject statically when NO output
        # of the sink src op is mapped (rule can never apply).
        mapped = {(m.src_op_id, m.src_ts_id) for m in self.rule.mapped_outputs}
        internally_used = {(r.op_id, r.ts_id)
                           for _, inputs in self._src for r in inputs
                           if r.op_id >= 0}
        sinks = [i for i in range(n)
                 if not any(r.op_id == i for _, inputs in self._src
                            for r in inputs)]
        for s in sinks:
            outs = range(_num_outputs(self._src[s][0]))
            if not any((s, t) in mapped or (s, t) in internally_used
                       for t in outs):
                raise UnsupportedRule(f"sink src op {s} output unmapped")

    # -- matching --------------------------------------------------------
    def _op_matches(self, pat: TasoOp, op: Op) -> bool:
        t = pat.type
        if t in _PARALLEL_TYPES:
            if op.op_type != _PARALLEL_TYPES[t]:
                return False
            deg = pat.at("PM_PARALLEL_DEGREE")
            if deg is not None and op.params.degree != self.degree:
                return False
            dim = pat.at("PM_PARALLEL_DIM")
            if dim is not None:
                rank = _logical_rank(op.inputs[0])
                if dim >= rank:
                    return False
                want = _col_to_row(dim, rank)
                actual = (op.params.dim if t in ("OP_PARTITION", "OP_COMBINE")
                          else op.params.axis)
                if actual % rank != want:
                    return False
            return True
        if t in _EW_UNARY:
            return (op.op_type == OperatorType.ELEMENT_UNARY
                    and op.params.op == _EW_UNARY[t])
        if t in _EW_BINARY:
            return (op.op_type == OperatorType.ELEMENT_BINARY
                    and op.params.op == _EW_BINARY[t])
        if t == "OP_LINEAR":
            if op.op_type != OperatorType.LINEAR:
                return False
            acti = pat.at("PM_ACTI")
            if acti is not None:
                want = _TASO_ACTI.get(acti)
                if want is None or op.params.activation != want:
                    return False
            return True
        if t == "OP_CONCAT":
            if op.op_type != OperatorType.CONCAT:
                return False
            n = pat.at("PM_NUM_INPUTS")
            if n is not None and len(op.inputs) != n:
                return False
            rank = _logical_rank(op.inputs[0])
            numdim = pat.at("PM_NUMDIM")
            if numdim is not None and rank != numdim:
                return False
            axis = pat.at("PM_AXIS")
            if axis is not None:
                if axis >= rank or op.params.axis % rank != _col_to_row(axis, rank):
                    return False
            return True
        if t == "OP_SPLIT":
            if op.op_type != OperatorType.SPLIT:
                return False
            n = pat.at("PM_NUM_OUTPUTS")
            if n is not None and len(op.outputs) != n:
                return False
            rank = _logical_rank(op.inputs[0])
            axis = pat.at("PM_AXIS")
            if axis is not None:
                if axis >= rank or op.params.axis % rank != _col_to_row(axis, rank):
                    return False
            return True
        return False

    def find_matches(self, graph: Graph) -> List[Match]:
        by_type: Dict[str, List[Op]] = {}
        topo = graph.topo_order()
        for op in topo:
            by_type.setdefault(op.op_type.value, []).append(op)
        # quick reject: every pattern type must occur in the graph
        for pat, _ in self._src:
            t = pat.type
            key = (_PARALLEL_TYPES[t].value if t in _PARALLEL_TYPES else
                   "element_unary" if t in _EW_UNARY else
                   "element_binary" if t in _EW_BINARY else
                   t[3:].lower())
            if key not in by_type:
                return []

        out: List[Match] = []
        n = len(self._src)
        assignment: List[Optional[Op]] = [None] * n
        used: set = set()
        ext: Dict[int, int] = {}  # external id -> tensor guid

        def candidates(pat: TasoOp) -> List[Op]:
            t = pat.type
            if t in _PARALLEL_TYPES:
                return by_type.get(_PARALLEL_TYPES[t].value, [])
            if t in _EW_UNARY:
                return by_type.get("element_unary", [])
            if t in _EW_BINARY:
                return by_type.get("element_binary", [])
            return by_type.get(t[3:].lower(), [])

        def wire_ok(i: int, op: Op, new_ext: Dict[int, int]) -> bool:
            pat, inputs = self._src[i]
            if len(op.inputs) != len(inputs):
                return False
            for j, ref in enumerate(inputs):
                actual = op.inputs[j]
                if ref.op_id >= 0:
                    prod = assignment[ref.op_id]
                    if (actual.owner_op is not prod
                            or actual.owner_idx != ref.ts_id):
                        return False
                else:
                    bound = ext.get(ref.op_id, new_ext.get(ref.op_id))
                    if bound is None:
                        new_ext[ref.op_id] = actual.guid
                    elif bound != actual.guid:
                        return False
            return True

        def backtrack(i: int):
            if i == n:
                out.append(Match(self, tuple(assignment)))
                return
            pat, _ = self._src[i]
            for op in candidates(pat):
                if op.guid in used or not self._op_matches(pat, op):
                    continue
                new_ext: Dict[int, int] = {}
                if not wire_ok(i, op, new_ext):
                    continue
                assignment[i] = op
                used.add(op.guid)
                ext.update(new_ext)
                backtrack(i + 1)
                assignment[i] = None
                used.discard(op.guid)
                for k in new_ext:
                    ext.pop(k, None)

        backtrack(0)
        return out

    # -- replacement -----------------------------------------------------
    def _make_dst_op(self, pat: TasoOp, new_inputs: List[ParallelTensor],
                     match: Match, name: str) -> Op:
        from ..ops.dense import Linear
        from ..ops.element import (ElementBinary, ElementBinaryParams,
                                   ElementUnary, ElementUnaryParams)
        from ..ops.shape import Concat, ConcatParams, Split, SplitParams
        from ..parallel.parallel_op import (Combine, CombineParams,
                                            FoldReduce, FoldReduceParams,
                                            Repartition, RepartitionParams,
                                            StackReplicate,
                                            StackReplicateParams)

        t = pat.type
        if t in _PARALLEL_TYPES:
            rank = _logical_rank(new_inputs[0])
            dim = pat.at("PM_PARALLEL_DIM")
            if dim is None:
                raise UnsupportedRule(f"{t} without PM_PARALLEL_DIM")
            row = _col_to_row(dim, rank)  # raises -> apply returns None
            cls, pcls, key = {
                "OP_PARTITION": (Repartition, RepartitionParams, "dim"),
                "OP_COMBINE": (Combine, CombineParams, "dim"),
                "OP_REPLICATE": (StackReplicate, StackReplicateParams, "axis"),
                "OP_REDUCE": (FoldReduce, FoldReduceParams, "axis"),
            }[t]
            return cls(pcls(**{key: row, "degree": self.degree}), new_inputs,
                       name=name)
        if t in _EW_UNARY:
            return ElementUnary(ElementUnaryParams(op=_EW_UNARY[t]),
                                new_inputs, name=name)
        if t in _EW_BINARY:
            return ElementBinary(ElementBinaryParams(op=_EW_BINARY[t]),
                                 new_inputs, name=name)
        if t == "OP_LINEAR":
            matched = match.ops[self._src_linear_idx]
            acti = pat.at("PM_ACTI")
            params = matched.params
            if acti is not None:
                want = _TASO_ACTI.get(acti)
                if want is None:
                    raise UnsupportedRule(f"unknown PM_ACTI {acti}")
                params = dataclasses.replace(params, activation=want)
            return clone_op(matched, new_inputs, name=name, params=params)
        if t == "OP_CONCAT":
            rank = _logical_rank(new_inputs[0])
            axis = pat.at("PM_AXIS")
            if axis is None:
                raise UnsupportedRule("concat without PM_AXIS")
            return Concat(ConcatParams(axis=_col_to_row(axis, rank)),
                          new_inputs, name=name)
        if t == "OP_SPLIT":
            rank = _logical_rank(new_inputs[0])
            axis = pat.at("PM_AXIS")
            nout = _num_outputs(pat)
            if axis is None:
                raise UnsupportedRule("split without PM_AXIS")
            row = _col_to_row(axis, rank)
            size = new_inputs[0].shape.logical_shape[row]
            if size % nout != 0:
                # reference: op = INVALID_NODE (substitution.cc:884-890)
                raise UnsupportedRule("split size not divisible")
            return Split(SplitParams(sizes=(size // nout,) * nout, axis=row),
                         new_inputs, name=name)
        raise UnsupportedRule(f"dst op type {t}")

    def build_replacement(self, match: Match, ext: Dict[int, ParallelTensor],
                          new_graph: Graph) -> Dict[int, ParallelTensor]:
        # re-derive external bindings exactly as matching did
        ext_bind: Dict[int, ParallelTensor] = {}
        for i, (pat, inputs) in enumerate(self._src):
            op = match.ops[i]
            for j, ref in enumerate(inputs):
                if ref.op_id < 0 and ref.op_id not in ext_bind:
                    ext_bind[ref.op_id] = ext[op.inputs[j].guid]
        base = match.ops[0].name
        new_ops: List[Op] = []
        for i, (pat, inputs) in enumerate(self._dst):
            new_inputs = []
            for ref in inputs:
                if ref.op_id < 0:
                    if ref.op_id not in ext_bind:
                        raise UnsupportedRule(
                            f"dst references unbound external {ref.op_id}")
                    new_inputs.append(ext_bind[ref.op_id])
                else:
                    new_inputs.append(new_ops[ref.op_id].outputs[ref.ts_id])
            # keep the matched linear's name when the rule has a unique
            # dst linear (weights then transfer by name across rewrite)
            if (pat.type == "OP_LINEAR"
                    and sum(1 for p, _ in self._dst if p.type == "OP_LINEAR") == 1):
                name = match.ops[self._src_linear_idx].name
            else:
                name = f"{base}.{self.rule.name}.{i}"
            op = self._make_dst_op(pat, new_inputs, match, name)
            new_graph.add_op(op)
            new_ops.append(op)
        out: Dict[int, ParallelTensor] = {}
        for m in self.rule.mapped_outputs:
            old = match.ops[m.src_op_id].outputs[m.src_ts_id]
            out[old.guid] = new_ops[m.dst_op_id].outputs[m.dst_ts_id]
        return out


# --------------------------------------------------------------------------
# Catalog conversion + per-rule numerical verification
# --------------------------------------------------------------------------

# bump when matching/realization semantics change: invalidates the
# verification cache
ENGINE_VERSION = 2


def verify_rule(prule: PatternRule) -> str:
    """Numerically verify one compiled rule under the realized
    semantics: instantiate its src pattern, self-match, apply, and
    compare probe outputs.  Returns a verdict string:

      "exact"        — rewrite is a numerical identity;
      "family"       — shapes preserved but a linear's input changed
                       (weight-repacking equivalence: same function
                       class, same FLOPs — TASO verified it with weight
                       tensors the schema then drops);
      "fail: ..."    — could not be validated; rule must not be used.

    TASO verifies every generated rule against concrete tensors; the
    reference ingests the JSON unverified.  Since our realization of
    Replicate/Reduction fixes a concrete intra-dim layout
    (StackReplicate/FoldReduce, block order), a handful of catalog
    rules whose equivalence only holds in the parallel-tensor algebra
    (degree as a device axis, layout-free) do not survive — this gate
    rejects exactly those.
    """
    import numpy as np

    inst = instantiate_src(prule, probes=True)
    if inst is None:
        return "fail: could not instantiate src pattern"
    g, _ = inst
    matches = prule.find_matches(g)
    if not matches:
        return "fail: src pattern does not self-match"
    g2 = None
    for m in matches:
        g2 = prule.apply(g, m)
        if g2 is not None:
            break
    if g2 is None:
        return "fail: apply rejected by shape rules"

    def run(graph, feeds):
        vals, out = {}, {}
        for op in graph.topo_order():
            if op.op_type == OperatorType.INPUT:
                vals[op.outputs[0].guid] = feeds[op.name]
                continue
            ws = []
            for spec in op.weight_specs:
                key = (op.name, spec.name)
                shape = tuple(dd.size for dd in spec.shape.dims
                              if not dd.is_replica_dim)
                ws.append(np.random.RandomState(
                    abs(hash(key)) % 2**31).randn(*shape).astype(np.float32) * 0.1)
            ins = [vals[t.guid] for t in op.inputs]
            res = op.forward(ins, ws)
            for t, v in zip(op.outputs, res):
                vals[t.guid] = np.asarray(v)
            if op.name.startswith("probe"):
                out[op.name] = np.asarray(res[0])
        return out

    feeds = {
        op.name: np.random.RandomState(7).randn(
            *op.outputs[0].shape.logical_shape).astype(np.float32)
        for op in g.ops if op.op_type == OperatorType.INPUT
    }
    try:
        o1, o2 = run(g, feeds), run(g2, feeds)
    except Exception as e:  # op forward on logical arrays must not fail
        return f"fail: execution error {type(e).__name__}"
    if set(o1) != set(o2) or any(o1[k].shape != o2[k].shape for k in o1):
        return "fail: probe shape mismatch"
    if all(np.allclose(o1[k], o2[k], rtol=1e-4, atol=1e-4) for k in o1):
        return "exact"
    src_lin = [(tuple(op.inputs[0].shape.logical_shape))
               for op in g.ops if op.op_type == OperatorType.LINEAR]
    dst_lin = [(tuple(op.inputs[0].shape.logical_shape))
               for op in g2.ops if op.op_type == OperatorType.LINEAR]
    if sorted(src_lin) != sorted(dst_lin):
        return "family"
    return "fail: numeric mismatch"


def _verify_cache_path() -> str:
    import os

    base = os.environ.get("FLEXFLOW_TPU_CACHE_DIR",
                          os.path.expanduser("~/.cache/flexflow_tpu"))
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, "taso_verify.json")


def _verified_verdicts(path: str, rules: Sequence[TasoRule]) -> Dict[str, str]:
    """Per-rule verdicts for a catalog file, cached on disk keyed by
    (file identity, engine version).  Verification is degree-independent
    (run at degree 2, the catalog's template degree)."""
    import os

    key = f"{os.path.abspath(path)}:{os.path.getmtime(path)}:v{ENGINE_VERSION}"
    cache_file = _verify_cache_path()
    cache = {}
    try:
        with open(cache_file) as f:
            cache = json.load(f)
    except (OSError, ValueError):
        pass
    if key in cache:
        return cache[key]
    verdicts: Dict[str, str] = {}
    for r in rules:
        try:
            pr = PatternRule(r, degree=2)
        except UnsupportedRule as e:
            verdicts[r.name] = f"skip: {e.args[0] if e.args else 'unsupported'}"
            continue
        verdicts[r.name] = verify_rule(pr)
    cache = {key: verdicts}  # keep only the latest file identity
    try:
        with open(cache_file, "w") as f:
            json.dump(cache, f)
    except OSError:
        pass
    return verdicts


def convert_rules(
    rules: Sequence[TasoRule],
    degrees: Sequence[int] = (2,),
    verdicts: Optional[Dict[str, str]] = None,
) -> Tuple[List[PatternRule], Dict[str, int]]:
    """Compile parsed rules into PatternRules.

    Parallel-op rules are instantiated once per degree (reference
    create_xfers is called per considered degree, substitution.cc:1779-
    1786); purely algebraic rules are degree-independent and
    instantiated once.  When `verdicts` is given (see
    `_verified_verdicts`), only rules verified "exact" or "family" are
    kept.  Returns (rules, report) where report counts skip reasons —
    the honest accounting of what the engine can and cannot ingest.
    """
    out: List[PatternRule] = []
    report: Dict[str, int] = {"converted": 0, "instantiated": 0}

    def skip(reason: str):
        key = f"skip: {reason}"
        report[key] = report.get(key, 0) + 1

    for r in rules:
        if verdicts is not None:
            v = verdicts.get(r.name, "fail: unverified")
            if v.startswith("skip: "):
                skip(v[6:])
                continue
            if v.startswith("fail"):
                skip(f"verification ({v})")
                continue
        try:
            first = PatternRule(r, degree=int(degrees[0]) if degrees else 2)
        except UnsupportedRule as e:
            skip(e.args[0] if e.args else "unsupported")
            continue
        report["converted"] += 1
        out.append(first)
        if first.uses_parallel:
            for d in list(degrees)[1:]:
                out.append(PatternRule(r, degree=int(d)))
    report["instantiated"] = len(out)
    return out, report


def load_taso_rules(
    path: str, degrees: Sequence[int] = (2,), verify: bool = True
) -> Tuple[List[PatternRule], Dict[str, int]]:
    rules = parse_rule_collection(path)
    verdicts = _verified_verdicts(path, rules) if verify else None
    return convert_rules(rules, degrees, verdicts=verdicts)


# --------------------------------------------------------------------------
# Pattern instantiation (test harness: realize a rule's src pattern as a
# concrete graph so match/apply/numerics can be validated per rule)
# --------------------------------------------------------------------------

def _make_src_op(prule: PatternRule, pat: TasoOp,
                 new_inputs: List[ParallelTensor], name: str) -> Op:
    """Concrete op for a SRC pattern node (no match to copy attrs from:
    linears get synthetic params)."""
    if pat.type == "OP_LINEAR":
        from ..ops.dense import Linear, LinearParams

        acti = pat.at("PM_ACTI")
        return Linear(
            LinearParams(out_channels=8, use_bias=True,
                         activation=_TASO_ACTI.get(acti, ActiMode.NONE)
                         if acti is not None else ActiMode.NONE),
            new_inputs, name=name)
    fake = Match(prule, ())
    return prule._make_dst_op(pat, new_inputs, fake, name)


def instantiate_src(
    prule: PatternRule, probes: bool = True
) -> Optional[Tuple[Graph, List[str]]]:
    """Build a concrete Graph realizing the rule's src pattern, trying a
    small family of external shapes/degrees until shape rules accept.
    Appends an identity probe op per mapped src output (so the rewritten
    graph keeps a same-named handle to compare against).  Returns
    (graph, ext_input_names) or None if no seed config builds."""
    from ..ops.element import ElementUnary, ElementUnaryParams
    from ..ops.sources import InputOp, SourceParams
    from ..tensor import ParallelTensorShape

    ext_ids = sorted({r.op_id for _, inputs in prule._src for r in inputs
                      if r.op_id < 0})
    d = prule.degree
    seed_cfgs = [
        ((1, 1, 1), 1), ((1, 1, 1), d), ((d, d, d), d), ((d, d, 1), d),
        ((1, d, 1), 1), ((d, 1, 1), d), ((1, 1, d), d), ((d, d, d), 1),
        ((1, d, d), d), ((d, d, 1), 1),
    ]
    size = 8 * d  # divisible through chained partitions up to d*d
    for degrees, rep in seed_cfgs:
        try:
            g = Graph()
            ext_map: Dict[int, ParallelTensor] = {}
            names = []
            for e in ext_ids:
                shape = ParallelTensorShape.make(
                    (size, size, size), degrees=degrees, replica_degree=rep)
                inp = InputOp(SourceParams(shape=shape), [],
                              name=f"ext{-e}")
                g.add_op(inp)
                ext_map[e] = inp.outputs[0]
                names.append(inp.name)
            ops: List[Op] = []
            for i, (pat, inputs) in enumerate(prule._src):
                new_inputs = [
                    ext_map[r.op_id] if r.op_id < 0
                    else ops[r.op_id].outputs[r.ts_id]
                    for r in inputs
                ]
                op = _make_src_op(prule, pat, new_inputs, f"pat{i}")
                g.add_op(op)
                ops.append(op)
            if probes:
                for k, m in enumerate(prule.rule.mapped_outputs):
                    t = ops[m.src_op_id].outputs[m.src_ts_id]
                    g.add_op(ElementUnary(
                        ElementUnaryParams(op=OpUnary.IDENTITY), [t],
                        name=f"probe{k}"))
            return g, names
        except (ValueError, KeyError, IndexError):
            continue
    return None
