"""Graph-rewrite substitution engine: match/apply rules over the PCG.

Reference: the Unity substitution engine — `GraphXfer::run` match/apply
(substitution.cc:1898-1945), the built-in rule catalog
`generate_all_pcg_xfers` (substitution.cc:1726-1868), TASO-style
algebraic rules loaded from JSON (substitution_loader.cc,
substitutions/graph_subst_3_v2.json), and `base_optimize`'s
budget-bounded enumeration over rewritten graphs
(substitution.cc:2229-2320).

Unlike pcg/substitution.py (whose xfers annotate per-op shard options),
the rules here REWRITE the operator graph itself: a matched pattern
subgraph is replaced by a different subgraph computing the same
function.  Built-in catalog:

  * fuse_{linear,conv2d}_activation — fold a trailing elementwise
    activation into the producing op's fused-activation slot (one XLA
    fusion instead of two ops in the PCG/search space);
  * merge_parallel_{linear,conv2d} — N sibling ops reading the same
    tensor with identical attributes merge into one op with summed
    out_channels followed by a Split (TASO's merge rule — turns N small
    MXU matmuls into one big one; fires on Inception-style branches);
  * cancel_inverse_parallel_ops — adjacent Combine(dim,d) /
    Repartition(dim,d) pairs (either order) collapse to identity — the
    cancellation that makes Megatron column->row parallelism emerge from
    rewrites in the reference.

`enumerate_variants` is the bounded best-first enumeration the Unity DP
ranks; applied rewrites are recorded on the Strategy (as
(rule name, match index) pairs) so strategy import/export replays them
deterministically.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

from ..fftype import ActiMode, OperatorType, OpUnary
from ..ops.op import Op, ShapeError
from ..tensor import ParallelTensor
from .graph import Graph


def clone_op(op: Op, new_inputs, name=None, shard=None, params=None) -> Op:
    """Re-instantiate an op on new input tensors, carrying user
    initializers and grad flags (same contract as apply_strategy)."""
    new_op = type(op)(
        params if params is not None else op.params,
        new_inputs,
        name=name or op.name,
        shard=shard if shard is not None else op.shard,
        **op.ctor_kwargs(),
    )
    old_by_name = {s.name: s for s in op.weight_specs}
    new_op.weight_specs = [
        dataclasses.replace(s, initializer=old_by_name[s.name].initializer)
        if s.name in old_by_name
        else s
        for s in new_op.weight_specs
    ]
    for old_out, new_out in zip(op.outputs, new_op.outputs):
        new_out.create_gradients = old_out.create_gradients
    return new_op


def _consumer_counts(graph: Graph) -> Dict[int, int]:
    counts: Dict[int, int] = {}
    for op in graph.ops:
        for t in op.inputs:
            counts[t.guid] = counts.get(t.guid, 0) + 1
    return counts


@dataclasses.dataclass
class Match:
    rule: "RewriteRule"
    ops: Tuple[Op, ...]


class RewriteRule:
    """A pattern -> replacement rewrite (reference GraphXfer,
    substitution.h:218-228)."""

    name: str = "abstract"

    def find_matches(self, graph: Graph) -> List[Match]:
        raise NotImplementedError

    def build_replacement(
        self, match: Match, ext: Dict[int, ParallelTensor], new_graph: Graph
    ) -> Dict[int, ParallelTensor]:
        """Emit replacement ops into `new_graph`.

        ext maps old external-input tensor guid -> new tensor.  Returns
        old matched-output tensor guid -> replacement tensor, for every
        matched output with consumers outside the match."""
        raise NotImplementedError

    def apply(self, graph: Graph, match: Match) -> Optional[Graph]:
        """Rebuild the graph with the match replaced.  Returns None when
        the match is non-convex (an unmatched op needs a matched output
        before all matched inputs exist) or shapes reject it."""
        matched = {op.guid for op in match.ops}
        matched_outs = {t.guid for op in match.ops for t in op.outputs}
        topo = graph.topo_order()
        last_pos = max(i for i, op in enumerate(topo) if op.guid in matched)
        new_graph = Graph()
        tensor_map: Dict[int, ParallelTensor] = {}
        try:
            for i, op in enumerate(topo):
                if op.guid in matched:
                    if i == last_pos:
                        ext = {}
                        for mop in match.ops:
                            for t in mop.inputs:
                                if t.guid not in matched_outs:
                                    ext[t.guid] = tensor_map[t.guid]
                        tensor_map.update(
                            self.build_replacement(match, ext, new_graph)
                        )
                    continue
                if any(
                    t.guid in matched_outs and t.guid not in tensor_map
                    for t in op.inputs
                ):
                    return None  # consumer of a matched output before emit
                new_inputs = [tensor_map[t.guid] for t in op.inputs]
                new_op = clone_op(op, new_inputs)
                new_graph.add_op(new_op)
                for o_t, n_t in zip(op.outputs, new_op.outputs):
                    tensor_map[o_t.guid] = n_t
        except (ShapeError, ValueError, KeyError):
            return None
        return new_graph


_ACT_OF_UNARY = {
    OpUnary.RELU: ActiMode.RELU,
    OpUnary.GELU: ActiMode.GELU,
    OpUnary.SIGMOID: ActiMode.SIGMOID,
    OpUnary.TANH: ActiMode.TANH,
}


class FuseActivation(RewriteRule):
    """linear/conv2d(activation=NONE) -> unary activation  ==>  fused op.

    Reference analogue: the fuse rules of the TASO catalog consumed by
    substitution_loader.cc; the fused-activation slot mirrors the
    reference kernels' built-in activation (linear_kernels.cu)."""

    def __init__(self, op_type: OperatorType = OperatorType.LINEAR):
        self.op_type = op_type
        self.name = f"fuse_{op_type.value}_activation"

    def find_matches(self, graph: Graph) -> List[Match]:
        counts = _consumer_counts(graph)
        out = []
        for op in graph.topo_order():
            if op.op_type != OperatorType.ELEMENT_UNARY:
                continue
            act = _ACT_OF_UNARY.get(op.params.op)
            if act is None or not op.inputs:
                continue
            prod = op.inputs[0].owner_op
            if prod is None or prod.op_type != self.op_type:
                continue
            if prod.params.activation != ActiMode.NONE:
                continue
            if counts.get(prod.outputs[0].guid, 0) != 1:
                continue
            out.append(Match(self, (prod, op)))
        return out

    def build_replacement(self, match, ext, new_graph):
        prod, act = match.ops
        params = dataclasses.replace(
            prod.params, activation=_ACT_OF_UNARY[act.params.op]
        )
        new_op = clone_op(
            prod, [ext[t.guid] for t in prod.inputs], params=params
        )
        new_graph.add_op(new_op)
        return {
            prod.outputs[0].guid: new_op.outputs[0],
            act.outputs[0].guid: new_op.outputs[0],
        }


class MergeParallelOps(RewriteRule):
    """N>=2 sibling linear/conv2d ops on one input, identical attributes
    except out_channels  ==>  one op with summed out_channels + Split.

    The TASO merge rule (graph_subst_3_v2.json's matmul/conv merge
    family): one big MXU matmul replaces N small ones — exactly the
    shape of Inception branch heads (parallel 1x1 convs on the same
    tensor)."""

    def __init__(self, op_type: OperatorType = OperatorType.LINEAR):
        self.op_type = op_type
        self.name = f"merge_parallel_{op_type.value}"

    def _group_key(self, op: Op):
        return (
            op.inputs[0].guid,
            dataclasses.replace(op.params, out_channels=0),
            op.shard,
        )

    @staticmethod
    def _mergeable(op: Op) -> bool:
        # merging re-initializes weights as one array: only legal when
        # every spec still carries the op-class default initializer and
        # all outputs are trainable (a user-pinned init or a frozen
        # branch must survive rewrites untouched)
        from ..initializer import DEFAULT_BIAS_INIT, DEFAULT_WEIGHT_INIT

        for s in op.weight_specs:
            if s.initializer not in (DEFAULT_WEIGHT_INIT, DEFAULT_BIAS_INIT):
                return False
        return all(t.create_gradients for t in op.outputs)

    def find_matches(self, graph: Graph) -> List[Match]:
        groups: Dict[Tuple, List[Op]] = {}
        for op in graph.topo_order():
            if op.op_type != self.op_type or len(op.inputs) != 1:
                continue
            if not op.shard.is_trivial() or not self._mergeable(op):
                continue
            groups.setdefault(self._group_key(op), []).append(op)
        return [
            Match(self, tuple(ops)) for ops in groups.values() if len(ops) >= 2
        ]

    def build_replacement(self, match, ext, new_graph):
        from ..ops.shape import Split, SplitParams

        ops = match.ops
        base = ops[0]
        sizes = tuple(o.params.out_channels for o in ops)
        params = dataclasses.replace(base.params, out_channels=sum(sizes))
        merged = type(base)(
            params,
            [ext[base.inputs[0].guid]],
            name=f"merged_{base.name}",
            shard=base.shard,
        )
        new_graph.add_op(merged)
        if self.op_type == OperatorType.CONV2D:
            axis = 1  # NCHW channel dim
        else:
            axis = merged.outputs[0].shape.logical_rank - 1
        sp = Split(
            SplitParams(sizes=sizes, axis=axis),
            [merged.outputs[0]],
            name=f"split_{base.name}",
        )
        new_graph.add_op(sp)
        return {
            op.outputs[0].guid: sp.outputs[k] for k, op in enumerate(ops)
        }


_INVERSE_PAIRS = {
    (OperatorType.COMBINE, OperatorType.REPARTITION),
    (OperatorType.REPARTITION, OperatorType.COMBINE),
}


class CancelInverseParallel(RewriteRule):
    """Combine(dim,d) ∘ Repartition(dim,d) (either order) is the
    identity on the parallel shape — drop both.  This is the parallel-op
    chain cancellation the reference performs during rewrite search
    (substitution.cc — what lets Megatron column->row emerge: linear1's
    trailing Combine cancels linear2's leading Repartition, leaving the
    tensor sharded across the boundary)."""

    name = "cancel_inverse_parallel_ops"

    def find_matches(self, graph: Graph) -> List[Match]:
        counts = _consumer_counts(graph)
        out = []
        for op in graph.topo_order():
            if not op.inputs:
                continue
            prod = op.inputs[0].owner_op
            if prod is None:
                continue
            if (prod.op_type, op.op_type) not in _INVERSE_PAIRS:
                continue
            if (
                prod.params.dim != op.params.dim
                or prod.params.degree != op.params.degree
            ):
                continue
            if counts.get(prod.outputs[0].guid, 0) != 1:
                continue
            out.append(Match(self, (prod, op)))
        return out

    def build_replacement(self, match, ext, new_graph):
        prod, op = match.ops
        src = ext[prod.inputs[0].guid]
        return {prod.outputs[0].guid: src, op.outputs[0].guid: src}


class CancelSplitConcat(RewriteRule):
    """Concat(Split(x)) with the same axis, outputs in order and
    unconsumed elsewhere, is the identity — drop both (the reference's
    Graph::simplify / remove-trivial-ops family, graph.cc; the TASO
    closure needs it so branch-merge chains can terminate: merge two
    linears -> split -> [relu,relu] -> concat becomes one linear+relu
    once taso_rule_543 hoists the relu past the concat)."""

    name = "cancel_split_concat"

    def find_matches(self, graph: Graph) -> List[Match]:
        counts = _consumer_counts(graph)
        out = []
        for op in graph.topo_order():
            if op.op_type != OperatorType.CONCAT or not op.inputs:
                continue
            prod = op.inputs[0].owner_op
            if prod is None or prod.op_type != OperatorType.SPLIT:
                continue
            if len(op.inputs) != len(prod.outputs):
                continue
            if any(t.owner_op is not prod or t.owner_idx != k
                   for k, t in enumerate(op.inputs)):
                continue
            rank = op.inputs[0].shape.logical_rank
            if op.params.axis % rank != prod.params.axis % rank:
                continue
            if any(counts.get(t.guid, 0) != 1 for t in prod.outputs):
                continue
            out.append(Match(self, (prod, op)))
        return out

    def build_replacement(self, match, ext, new_graph):
        prod, cat = match.ops
        src = ext[prod.inputs[0].guid]
        out = {cat.outputs[0].guid: src}
        for t in prod.outputs:
            out.setdefault(t.guid, src)  # unreferenced externally (checked)
        return out


def generate_rewrite_rules() -> List[RewriteRule]:
    """Built-in rewrite catalog (reference generate_all_pcg_xfers +
    TASO JSON rules)."""
    return [
        FuseActivation(OperatorType.LINEAR),
        FuseActivation(OperatorType.CONV2D),
        MergeParallelOps(OperatorType.LINEAR),
        MergeParallelOps(OperatorType.CONV2D),
        CancelInverseParallel(),
        CancelSplitConcat(),
    ]


_RULE_FACTORIES = {
    "fuse_activation": lambda r: FuseActivation(OperatorType(r["op_type"])),
    "merge_parallel": lambda r: MergeParallelOps(OperatorType(r["op_type"])),
    "cancel_inverse_parallel_ops": lambda r: CancelInverseParallel(),
}


def load_rewrite_rules(path: str, degrees=(2,)) -> List[RewriteRule]:
    """JSON-loadable rewrite rules (reference substitution_loader.cc).

    Two schemas are accepted:
      * the reference's TASO RuleCollection format
        (substitutions/graph_subst_3_v2.json — 640 pattern rules),
        detected by its "_t": "RuleCollection" tag and compiled by
        pcg/taso.py into generic pattern rules at the given parallel
        degrees;
      * this repo's own list format: {"rewrites": [{"type":
        "fuse_activation", "op_type": "linear"}, {"type":
        "merge_parallel", "op_type": "conv2d"},
        {"type": "cancel_inverse_parallel_ops"}]}.
    """
    from .taso import is_taso_rule_file, load_taso_rules

    if is_taso_rule_file(path):
        rules, _report = load_taso_rules(path, degrees=degrees)
        return list(rules)
    with open(path) as f:
        d = json.load(f)
    out = []
    for r in d.get("rewrites", []):
        fac = _RULE_FACTORIES.get(r.get("type"))
        if fac is None:
            raise ValueError(f"unknown rewrite rule type: {r.get('type')}")
        out.append(fac(r))
    return out


def rules_by_name(rules: Optional[Sequence[RewriteRule]] = None) -> Dict[str, RewriteRule]:
    return {r.name: r for r in (rules if rules is not None else generate_rewrite_rules())}


# Parallel degrees at which TASO catalog rules are instantiated.  The
# reference derives its considered_parallel_degrees from the machine at
# hand (substitution.cc:1773-1778), but a strategy records
# degree-qualified rule names ("taso_rule_N@16"), so the replay host
# must build the IDENTICAL list — a canonical environment-independent
# set keeps shipped artifacts loadable anywhere.  Degrees that don't
# divide the actual mesh simply never match (PatternRule checks the
# op's concrete degree).
CATALOG_DEGREES: Tuple[int, ...] = (2, 4, 8, 16)


def default_substitution_catalog() -> Optional[str]:
    """Default TASO catalog path for runs that don't pass
    --substitution-json, so the flagship joint-search feature is live
    (not opt-in) whenever a catalog is findable.  Per-rule verification
    verdicts are disk-cached (taso._verified_verdicts), so the
    default-on load costs one JSON/pb parse after the first run.

    Resolution order (first hit wins):
      1. $FLEXFLOW_TPU_SUBSTITUTIONS — a catalog file path; set EMPTY
         to disable default-on entirely;
      2. <repo-root>/substitutions/ then ./substitutions/ — first
         graph_subst*.pb / graph_subst*.json;
      3. a colocated reference checkout's shipped catalog (dev/CI
         layout: /root/reference/substitutions/graph_subst_3_v2.pb).
    """
    import glob
    import os

    env = os.environ.get("FLEXFLOW_TPU_SUBSTITUTIONS")
    if env is not None:
        return env or None
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for d in (os.path.join(repo_root, "substitutions"), "substitutions"):
        for pat in ("graph_subst*.pb", "graph_subst*.json"):
            hits = sorted(glob.glob(os.path.join(d, pat)))
            if hits:
                return hits[0]
    ref = "/root/reference/substitutions/graph_subst_3_v2.pb"
    if os.path.exists(ref):
        return ref
    return None


def catalog_for_config(cfg) -> Optional[str]:
    """The substitution catalog a config resolves to: an explicit
    --substitution-json wins ("none"/"" disables), else the default-on
    resolution above."""
    explicit = getattr(cfg, "substitution_json", None)
    if explicit is not None:
        return None if explicit in ("", "none") else explicit
    return default_substitution_catalog()


def catalog_fingerprint(path: str) -> Dict[str, object]:
    """Identity of a catalog file for strategy replay checks: replay
    resolves (rule name, match index) pairs, so the replaying host must
    see byte-identical rules compiled by the same engine semantics."""
    import hashlib
    import os

    from .taso import ENGINE_VERSION

    with open(path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    return {"path": os.path.abspath(path), "sha256": digest,
            "engine": ENGINE_VERSION}


def rules_for_config(cfg) -> List[RewriteRule]:
    """THE rule list for a given FFConfig — search and compile-time
    replay must build the identical ordered list or strategy.rewrites'
    (name, match index) pairs replay a different match.  (This is why
    the TASO catalog degrees are a fixed constant, not derived from the
    replaying host's device count.)"""
    rules = generate_rewrite_rules()
    catalog = catalog_for_config(cfg)
    if catalog:
        rules = rules + load_rewrite_rules(catalog, degrees=CATALOG_DEGREES)
    return rules


def rules_for_replay(cfg, strategy) -> List[RewriteRule]:
    """Rule list for replaying an imported strategy's rewrite trace.

    Default-on catalog resolution is environment-dependent (env var,
    cwd, colocated checkouts), so a strategy whose trace references
    taso_rule_* records the catalog's identity at search time
    (Strategy.catalog) and replay pins to it: the recorded path is used
    when the config doesn't name one explicitly, and whatever file
    resolves must hash to the recorded sha256 under the same engine
    version — otherwise match indices would silently select different
    subgraphs, so we fail loudly instead."""
    import os

    from .taso import ENGINE_VERSION

    rec = getattr(strategy, "catalog", None)
    needs = any(str(n).startswith("taso_rule_")
                for n, _ in getattr(strategy, "rewrites", []))
    if not needs:
        return rules_for_config(cfg)
    path = catalog_for_config(cfg)
    if rec:
        if getattr(cfg, "substitution_json", None) in (None, "", "none"):
            path = rec["path"] if os.path.exists(rec["path"]) else path
        if path is None:
            raise ValueError(
                "strategy references TASO catalog rules but no catalog "
                f"is findable (searched with {rec['path']})"
            )
        fp = catalog_fingerprint(path)
        if fp["sha256"] != rec.get("sha256"):
            raise ValueError(
                f"catalog {path} differs from the one this strategy was "
                "searched with — rewrite match indices would not replay"
            )
        if rec.get("engine") != ENGINE_VERSION:
            raise ValueError(
                "strategy was searched under TASO engine "
                f"v{rec.get('engine')}, this host runs v{ENGINE_VERSION} "
                "— re-run the search"
            )
    elif path is None:
        raise ValueError(
            "strategy references TASO catalog rules but no catalog is "
            "findable (set --substitution-json)"
        )
    return generate_rewrite_rules() + load_rewrite_rules(
        path, degrees=CATALOG_DEGREES
    )


def apply_rewrites(
    graph: Graph,
    rewrites: Sequence[Sequence],
    rules: Optional[Sequence[RewriteRule]] = None,
) -> Graph:
    """Replay a Strategy's recorded (rule name, match index) rewrite
    trace on a frontend graph (strategy import path)."""
    byname = rules_by_name(rules)
    for name, idx in rewrites:
        rule = byname.get(name)
        if rule is None:
            raise ValueError(f"unknown rewrite rule in strategy: {name}")
        matches = rule.find_matches(graph)
        if idx >= len(matches):
            raise ValueError(
                f"rewrite {name}[{idx}] does not match the graph "
                f"({len(matches)} matches)"
            )
        g2 = rule.apply(graph, matches[idx])
        if g2 is None:
            raise ValueError(f"rewrite {name}[{idx}] is not applicable")
        graph = g2
    return graph


def enumerate_variants(
    graph: Graph,
    rules: Optional[Sequence[RewriteRule]] = None,
    max_depth: int = 2,
    max_variants: int = 12,
) -> List[Tuple[Graph, List[List]]]:
    """Bounded enumeration of rewritten graphs (reference base_optimize's
    budget-bounded priority-queue backtracking, substitution.cc:2229).
    Returns [(graph, rewrite trace)], original first, deduped by
    structural hash."""
    rules = list(rules) if rules is not None else generate_rewrite_rules()
    seen = {graph.hash_key()}
    out: List[Tuple[Graph, List[List]]] = [(graph, [])]
    frontier = [(graph, [])]
    for _ in range(max_depth):
        nxt = []
        for g, hist in frontier:
            for rule in rules:
                for mi, m in enumerate(rule.find_matches(g)):
                    if len(out) >= max_variants:
                        return out
                    g2 = rule.apply(g, m)
                    if g2 is None:
                        continue
                    try:
                        k = g2.hash_key()
                    except TypeError:
                        continue
                    if k in seen:
                        continue
                    seen.add(k)
                    entry = (g2, hist + [[rule.name, mi]])
                    out.append(entry)
                    nxt.append(entry)
        frontier = nxt
    return out


def fuse_activations(graph: Graph, protected_names=()) -> Graph:
    """--fusion compile pass (reference apply_fusion, model.cc:2495,
    :2964-3061 — there it folds ops into FusedOp tasks; here the real
    win is shrinking the PCG/search space since XLA fuses kernels
    anyway): fold trailing activations into linear/conv2d until none
    remain.  Matches touching tensors or ops named in `protected_names`
    (strategy edge chains / shard configs) are left alone so the
    strategy still resolves."""
    rules = [
        FuseActivation(OperatorType.LINEAR),
        FuseActivation(OperatorType.CONV2D),
    ]
    protected = set(protected_names)

    def eligible(rule):
        for m in rule.find_matches(graph):
            prod, act = m.ops
            if (
                prod.name in protected
                or act.name in protected
                or any(t.name in protected for t in prod.outputs)
                or any(t.name in protected for t in act.outputs)
            ):
                continue
            yield m

    # each applied fuse removes one op, so #ops bounds the fixpoint
    for _ in range(len(graph.ops)):
        applied = False
        for rule in rules:
            for m in eligible(rule):
                g2 = rule.apply(graph, m)
                if g2 is not None:
                    graph = g2
                    applied = True
                    break
            if applied:
                break
        if not applied:
            break
    return graph


def cancel_all_inverse_parallel_ops(graph: Graph, max_iters: int = 32) -> Graph:
    """Fixed-point cancellation pass run on the applied (post-strategy)
    PCG before lowering, so redundant gather+rescatter boundaries never
    reach XLA."""
    rule = CancelInverseParallel()
    for _ in range(max_iters):
        matches = rule.find_matches(graph)
        if not matches:
            break
        g2 = rule.apply(graph, matches[0])
        if g2 is None:
            break
        graph = g2
    return graph
