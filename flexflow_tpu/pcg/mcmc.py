"""MCMC (simulated-annealing) strategy search.

Reference: FFModel::mcmc_optimize (src/runtime/model.cc:3285-3356) —
start from data-parallel, repeatedly `rewrite()` a random op's parallel
config (model.cc:3260-3283), simulate, and Metropolis-accept with
probability exp(-alpha * delta).

TPU-native search space (mesh-realizable by construction, SURVEY §7
hard part 4): a mesh factorization {data, model, expert} of the device
count plus per-op ShardConfigs — channel (linear out-dim / attention
heads / conv out-channels), attribute (embedding vocab), expert (MoE).
Candidates that fail shape/degree propagation are pruned by the
ShapeError the op shape rules raise.  Cost comes from the SPMD
simulator; the memory-aware mode adds the reference's lambda-weighted
memory objective (graph.cc:2056-2131 style) when the strategy exceeds
the per-device HBM budget.
"""
from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Tuple

from ..fftype import OperatorType
from ..logger import search_logger as slog
from ..obs.metrics import emit_counters
from ..ops.op import ShardConfig
from ..strategy import Strategy
from .evaluator import IncrementalEvaluator
from .graph import Graph


def search_stage_candidates(cfg) -> Tuple[int, ...]:
    """ZeRO ladder stages a search may choose (docs/PERF.md).  Pinned
    to cfg.zero_stage unless the memory-aware search is on — then every
    stage >= the configured floor competes, so memory-pressured models
    land on 2/3 (grad- and weight-resident HBM / dp at the price of
    per-layer all-gather traffic) while unconstrained ones keep 0/1.
    Shared by the MCMC and Unity searches."""
    if not cfg.memory_search:
        return (cfg.zero_stage,)
    return tuple(s for s in (0, 1, 2, 3) if s >= cfg.zero_stage)


def search_remat_enabled(cfg) -> bool:
    """Whether the searches may choose per-segment remat plans
    (docs/PERF.md "Searched rematerialization").  Like the ZeRO ladder,
    the dimension opens only under the memory-aware search; a global
    --remat floor does NOT close it — the search can still find a
    cheaper partial plan (a plan rides the strategy and overrides the
    bool in the executor)."""
    return bool(cfg.memory_search)


def remat_stats(strategy) -> Dict[str, object]:
    """The search_stats payload describing a winner's remat plan: the
    ON segment indices ("" when none) and their count — the
    placement_stats pattern for the remat dimension."""
    plan = getattr(strategy, "remat", None)
    return {
        "remat": ",".join(str(i) for i in plan) if plan else "",
        "remat_segments_on": len(plan or ()),
    }


def _factorizations(n: int, allow_expert: bool = True) -> List[Tuple[int, int, int]]:
    """(data, model, expert) triples with product n.  allow_expert=False
    drops ep>1 triples — the single source of the 'expert axis only with
    expert-shardable ops' invariant shared by the MCMC and Unity
    searches."""
    out = []
    for d in range(1, n + 1):
        if n % d:
            continue
        rest = n // d
        for m in range(1, rest + 1):
            if rest % m:
                continue
            e = rest // m
            if e > 1 and not allow_expert:
                continue
            out.append((d, m, e))
    return out


class _Candidate:
    """Ops whose ShardConfig the search may mutate, with legal degrees."""

    def __init__(self, op, kind: str, max_sizes: Dict[str, int]):
        self.name = op.name
        self.kind = kind  # "channel" | "attribute" | "expert"
        self.max_sizes = max_sizes  # e.g. {"channel": num_heads}


def find_candidates(graph: Graph) -> List[_Candidate]:
    cands = []
    for op in graph.ops:
        t = op.op_type
        if t == OperatorType.LINEAR:
            limit = getattr(op.params, "out_channels", None) or getattr(
                op.params, "out_dim", 0
            )
            cands.append(_Candidate(op, "channel", {"channel": limit}))
        elif t == OperatorType.CONV2D:
            cands.append(_Candidate(op, "channel", {"channel": op.params.out_channels}))
        elif t == OperatorType.MULTIHEAD_ATTENTION:
            cands.append(_Candidate(op, "channel", {"channel": op.params.num_heads}))
        elif t == OperatorType.EMBEDDING:
            cands.append(
                _Candidate(op, "attribute", {"attribute": op.params.num_entries})
            )
        elif t in (OperatorType.GROUP_BY,):
            cands.append(_Candidate(op, "expert", {"expert": op.params.n}))
    return cands


class MCMCSearch:
    def __init__(
        self,
        graph: Graph,
        num_devices: int,
        simulator_factory,
        budget: int = 100,
        alpha: float = 0.05,
        memory_budget: Optional[int] = None,
        memory_lambda: float = 1.0,
        seed: int = 0,
        propagate: bool = True,
        propagation_chance: float = 0.25,
        continue_chance: float = 0.7,
        use_eval_cache: bool = True,
        registry=None,
        zero_stages: Optional[Tuple[int, ...]] = None,
        remat_search: bool = False,
    ):
        # obs.metrics.MetricsRegistry (or None): final counters also
        # land in run telemetry, not just the log line
        self.registry = registry
        self.graph = graph
        self.n = num_devices
        self.simulator_factory = simulator_factory
        # ONE simulator per search, not one per candidate: the factory
        # still runs once so fitted-constant loading is unchanged, and
        # its (node_key)->cost / OpTerms caches persist across
        # evaluations (reference keeps one simulator for the whole
        # search, simulator.cc:550-560)
        self.simulator = simulator_factory()
        self.evaluator = IncrementalEvaluator(
            graph, self.simulator, training=True, use_cache=use_eval_cache
        )
        self.budget = budget
        self.alpha = alpha
        self.memory_budget = memory_budget
        self.memory_lambda = memory_lambda
        self.rng = random.Random(seed)
        # FF_USE_PROPAGATE (reference model.cc:3180-3258): a rewrite may
        # spread the changed op's config to adoptable neighbors, walking
        # while randf() < CONTINUE_PROPAGATION_CHANCE.  Our per-op state
        # is the shard flag, so the analogue copies the flipped value to
        # structurally identical candidates (same kind+limits — the 12
        # identical encoder layers of a deep net), which is the case the
        # reference optimization accelerates.
        self.propagate = propagate
        self.propagation_chance = propagation_chance
        self.continue_chance = continue_chance
        # ZeRO ladder stages the chain may move between.  A singleton
        # fixes the stage (no stage moves; candidates are stamped with
        # it); None also disables moves but leaves candidates at
        # zero_stage=None, costing under the simulator's own stage.
        self.zero_stages = tuple(zero_stages) if zero_stages else None
        # multi-slice hierarchy (topology/, docs/TOPOLOGY.md): when the
        # machine is a SliceHierarchy the chain gains a PLACEMENT move —
        # re-pick which mesh axis spans the DCN boundary.  Flat machines
        # keep the exact pre-topology move distribution.
        machine = self.simulator.machine
        self.slices = max(1, int(getattr(machine, "slices", 1) or 1))
        self._hier = (
            self.slices > 1 and hasattr(machine, "collective_cost")
        )
        self.candidates = find_candidates(graph)
        has_experts = any(c.kind == "expert" for c in self.candidates)
        self.factorizations = _factorizations(
            num_devices, allow_expert=has_experts
        )
        # searched remat (docs/PERF.md): the chain gains a FLIP-SEGMENT
        # move — toggle one pure single-tensor-boundary segment's remat
        # bit.  The flippable universe comes from the FRONTEND graph's
        # segmentation (applied graphs may split slightly differently
        # around inserted parallel ops; the evaluator always prices a
        # plan against the candidate's own applied segmentation, so the
        # move space is a proposal distribution, not a contract).
        self.remat_search = remat_search
        self.remat_flippable: List[int] = []
        if remat_search:
            from ..sim.simulator import MAX_REMAT_SEGMENTS
            from ..sim.simulator import remat_segments as _remat_segments

            self.remat_flippable = [
                i for i, (_, pure) in enumerate(
                    _remat_segments(graph.topo_order())
                ) if pure
            ][:MAX_REMAT_SEGMENTS]
        self.history: List[Tuple[int, float]] = []

    # -- strategy construction ------------------------------------------
    def _mesh_axes(self, dp: int, tp: int, ep: int) -> Dict[str, int]:
        axes = {}
        if dp > 1:
            axes["data"] = dp
        if tp > 1:
            axes["model"] = tp
        if ep > 1:
            axes["expert"] = ep
        if not axes:
            axes["data"] = 1
        return axes

    def _build(self, dp: int, tp: int, ep: int,
               flags: Dict[str, bool],
               zero_stage: Optional[int] = None,
               placement: Optional[str] = None,
               remat: Optional[Tuple[int, ...]] = None) -> Strategy:
        mesh_axes = self._mesh_axes(dp, tp, ep)
        if placement is not None:
            # a factorization move can strand the placement on an axis
            # the new mesh lacks (or that the slices no longer divide):
            # normalize to None = the shared resolve_placement default
            from ..topology.hierarchy import legal_placements

            if placement not in legal_placements(mesh_axes, self.slices):
                placement = None
        s = Strategy(mesh_axes=mesh_axes, zero_stage=zero_stage,
                     placement=placement,
                     remat=sorted(remat) if remat is not None else None)
        if dp > 1:
            s.edge_ops["__inputs__"] = [("repartition", {"dim": 0, "degree": dp})]
        # Megatron column->row pairing: a channel(tp)-sharded linear
        # leaves its output feature-sharded; a DIRECTLY consuming linear
        # must contract over that sharding (reduction=tp), not re-shard
        # channel — channel+channel on adjacent linears is an illegal
        # degree blow-up (the reference expresses the same pairing as
        # create_partition_linear_combine vs create_replicate_linear_
        # combine xfers, substitution.cc:1755-1820).  Walking topo order
        # alternates col,row,col,row through a sharded run.
        by_name = {op.name: op for op in self.graph.ops}
        is_col = {}  # name -> got channel=tp (output feature-sharded)
        for c in self.candidates:
            if not flags.get(c.name):
                continue
            if c.kind == "channel" and tp > 1 and c.max_sizes["channel"] % tp == 0:
                op = by_name.get(c.name)
                prod = (op.inputs[0].owner_op
                        if op is not None and op.inputs else None)
                while prod is not None and prod.op_type in (
                    OperatorType.ELEMENT_UNARY, OperatorType.DROPOUT,
                ):
                    prod = (prod.inputs[0].owner_op
                            if prod.inputs else None)
                if (op is not None and op.op_type == OperatorType.LINEAR
                        and prod is not None and is_col.get(prod.name)):
                    s.shard_configs[c.name] = ShardConfig(reduction=tp)
                else:
                    s.shard_configs[c.name] = ShardConfig(channel=tp)
                    if op is not None and op.op_type == OperatorType.LINEAR:
                        is_col[c.name] = True
            elif c.kind == "attribute" and tp > 1 and c.max_sizes["attribute"] % tp == 0:
                s.shard_configs[c.name] = ShardConfig(attribute=tp)
            elif c.kind == "expert" and ep > 1 and c.max_sizes["expert"] % ep == 0:
                s.shard_configs[c.name] = ShardConfig(expert=ep)
        return s

    # -- cost ------------------------------------------------------------
    def evaluate(self, strategy: Strategy) -> float:
        res = self.evaluator.evaluate(strategy)
        if res is None:  # ShapeError / unfactorable view -> illegal
            return math.inf
        cost = res.total_time
        # per_device_memory is lazy — the liveness scan only runs when a
        # budget makes the search actually consume it
        if self.memory_budget is not None and res.per_device_memory > self.memory_budget:
            over = res.per_device_memory / self.memory_budget - 1.0
            cost *= 1.0 + self.memory_lambda * over
        return cost

    @property
    def stats(self):
        """EvalStats for the whole search (memo/delta/full counters)."""
        return self.evaluator.stats

    # -- main loop (reference model.cc:3285-3356) ------------------------
    def optimize(self) -> Strategy:
        dp, tp, ep = self.n, 1, 1
        flags: Dict[str, bool] = {}
        # stage moves only when the ladder is actually searchable; the
        # chain starts at the ladder's floor (the configured stage)
        stage_moves = (
            self.zero_stages
            if self.zero_stages and len(self.zero_stages) > 1 else None
        )
        stage = self.zero_stages[0] if self.zero_stages else None
        placement = None  # the shared resolve_placement default
        remat: Optional[Tuple[int, ...]] = None  # not chosen
        current = self._build(dp, tp, ep, flags, stage, placement, remat)
        current_cost = self.evaluate(current)
        best, best_cost = current, current_cost
        self.best_iteration = -1  # evals needed to reach the winner
        state = (dp, tp, ep, dict(flags), stage, placement, remat)
        remat_moves = bool(self.remat_search and self.remat_flippable)
        for it in range(self.budget):
            ndp, ntp, nep, nflags = state[0], state[1], state[2], dict(state[3])
            nstage, nplacement, nremat = state[4], state[5], state[6]
            move = self.rng.random()
            # the placement move carves its window ABOVE the existing
            # thresholds (off shifts them) so the stage/factorization
            # move probabilities are unchanged on hierarchy machines —
            # and flat machines keep the exact historical distribution.
            # The remat flip-segment window stacks the same way (roff).
            off = 0.12 if self._hier else 0.0
            roff = 0.10 if remat_moves else 0.0
            if self._hier and move < off:
                # placement move: re-pick the mesh axis spanning the
                # DCN boundary (sharding unchanged — the evaluator
                # re-sums cached OpTerms under the new tiers, cheap
                # like the stage move).  None = the default placement.
                from ..topology.hierarchy import legal_placements

                mesh = self._mesh_axes(ndp, ntp, nep)
                nplacement = self.rng.choice(
                    [None] + legal_placements(mesh, self.slices)
                )
            elif remat_moves and move < off + roff:
                # flip-segment move (docs/PERF.md "Searched
                # rematerialization"): toggle one pure segment's remat
                # bit.  The applied graph is plan-invariant, so the
                # evaluator re-sums cached OpTerms — a cheap move like
                # the stage/placement ones.
                cur = set(nremat or ())
                seg = self.rng.choice(self.remat_flippable)
                cur.symmetric_difference_update({seg})
                nremat = tuple(sorted(cur))
            elif stage_moves is not None and move < off + roff + 0.15:
                # ZeRO-stage move: re-rung the ladder (the candidate's
                # sharding is unchanged, so the evaluator re-sums
                # cached OpTerms under the new stage — a cheap move)
                nstage = self.rng.choice(stage_moves)
            elif move < off + roff + 0.25 or not self.candidates:
                ndp, ntp, nep = self.rng.choice(self.factorizations)
            elif (self.propagate
                  and move < off + roff + 0.25
                  + 0.75 * self.propagation_chance):
                # propagate move (reference FFModel::propagate,
                # model.cc:3180-3258): spread a randomly selected op's
                # CURRENT config to a walk of adoptable neighbors —
                # here, structurally identical candidates — continuing
                # while randf() < CONTINUE_PROPAGATION_CHANCE.  This
                # harmonizes a half-sharded run of identical layers in
                # one accepted move instead of one flip per eval.
                c = self.rng.choice(self.candidates)
                val = nflags.get(c.name, False)
                sig = (c.kind, tuple(sorted(c.max_sizes.items())))
                peers = [
                    p for p in self.candidates
                    if p.name != c.name
                    and (p.kind, tuple(sorted(p.max_sizes.items()))) == sig
                ]
                for p in peers:  # graph order, like the BFS walk
                    nflags[p.name] = val
                    if self.rng.random() >= self.continue_chance:
                        break
            else:
                c = self.rng.choice(self.candidates)
                nflags[c.name] = not nflags.get(c.name, False)
            if ((ndp, ntp, nep) == state[:3] and nflags == state[3]
                    and nstage == state[4] and nplacement == state[5]
                    and nremat == state[6]):
                continue  # no-op move (e.g. propagate with no peers to
                # change): don't burn a simulator eval on it
            cand = self._build(ndp, ntp, nep, nflags, nstage, nplacement,
                               nremat)
            cost = self.evaluate(cand)
            self.history.append((it, cost))
            if cost < current_cost or (
                math.isfinite(cost)
                and self.rng.random()
                < math.exp(-self.alpha * (cost - current_cost) / max(1e-12, current_cost))
            ):
                current, current_cost = cand, cost
                state = (ndp, ntp, nep, nflags, nstage, nplacement, nremat)
                if cost < best_cost:
                    best, best_cost = cand, cost
                    self.best_iteration = it
        # search observability: counters ride on the returned strategy
        # so benchmarks and callers can track cache effectiveness
        best.search_stats = self.evaluator.stats.as_dict()
        # the winner's multi-slice placement ("" on flat machines) and
        # whether its grad reduction lowers hierarchically — gated on
        # _hier: a slices>1 TpuPodModel that is NOT a SliceHierarchy
        # never searched placements and must not claim one
        from ..topology.hierarchy import placement_stats

        best.search_stats.update(placement_stats(
            best, self.slices if self._hier else 1
        ))
        # the winner's per-segment remat plan ("" when no plan chosen)
        best.search_stats.update(remat_stats(best))
        # underlying cache layers (term decomposition + op-cost cache)
        best.search_stats["term_hits"] = self.simulator.term_hits
        best.search_stats["term_misses"] = self.simulator.term_misses
        best.search_stats["op_cost_hits"] = self.simulator.cost_model.cost_hits
        # identical log line to the pre-registry call (obs migration);
        # search_stats stays the same plain dict on the strategy
        emit_counters(slog, "mcmc eval stats", best.search_stats,
                      registry=self.registry, group="search/mcmc")
        return best


def make_search_simulator(cfg, machine, cost_model):
    """The ONE place an FFConfig becomes the Simulator configuration
    candidates are costed with: fitted overlap constants (when a
    calibration is persisted), parameter-sync mode, remat, and the
    ZeRO-1 update flags.  obs/fidelity.py reuses it so fidelity records
    measure the same simulator the search ranked candidates with."""
    from ..sim.calibrate import load_overlap_constants
    from ..sim.simulator import Simulator
    from .unity import _sync_mode

    fitted = load_overlap_constants()
    kw = {}
    if fitted is not None:
        kw["overlap_fraction"] = fitted["overlap_fraction"]
        kw["compute_scale"] = fitted.get("compute_scale", 1.0)
    return Simulator(
        machine,
        cost_model,
        sync_overlap_fraction=(
            fitted["sync_overlap_fraction"] if fitted is not None
            else (0.7 if cfg.search_overlap_backward_update else None)
        ),
        **kw,
        parameter_sync=_sync_mode(cfg.parameter_sync),
        remat=cfg.remat,
        zero_stage=cfg.zero_stage,
        wus_axis=cfg.wus_axis,
        dcn_bucket_bytes=float(
            getattr(cfg, "dcn_bucket_mb", 25.0)
        ) * 2**20,
    )


def mcmc_optimize(model, num_devices: int) -> Strategy:
    """Entry used by FFModel.compile (config-driven)."""
    from ..sim.machine_model import make_machine_model
    from ..sim.simulator import make_cost_model

    cfg = model.config
    machine = make_machine_model(cfg, num_devices)

    # one shared cost model: the (node_key)->cost cache must persist
    # across candidate evaluations (reference simulator.cc:550-560)
    cost_model = make_cost_model(cfg, machine)

    def sim_factory():
        return make_search_simulator(cfg, machine, cost_model)

    search = MCMCSearch(
        model.layers,
        num_devices,
        sim_factory,
        budget=max(1, cfg.search_budget),
        alpha=cfg.search_alpha,
        memory_budget=cfg.memory_per_device if cfg.memory_search else None,
        memory_lambda=cfg.memory_lambda,
        seed=cfg.seed,
        propagate=cfg.search_propagate,
        use_eval_cache=cfg.search_eval_cache,
        registry=getattr(
            getattr(model, "telemetry", None), "metrics", None
        ),
        zero_stages=search_stage_candidates(cfg),
        remat_search=search_remat_enabled(cfg),
    )
    best = search.optimize()
    # surface the ZeRO stage the winner was scored under (and the
    # legacy bool it subsumes)
    chosen = best.zero_stage if best.zero_stage is not None else cfg.zero_stage
    best.search_stats["zero_stage"] = int(chosen)
    best.search_stats["weight_update_sharding"] = chosen >= 1
    cost_model.save_persistent()
    return best
