"""Internal NHWC physical layout for 4-D CNN activations.

TPU convolutions want channels minormost: the MXU contracts over the
last dim and the (8, 128) vector tiling puts lanes on channels, so an
NCHW conv makes XLA wrap layout copies around every conv/pool/norm in
the tower.  The reference keeps cuDNN's NCHW end to end
(src/ops/conv_2d.cc); translating that literally costs ~2x on the conv
forward (measured on-chip).  Instead the PCG keeps its logical NCHW
shapes — reference API parity, shape rules untouched — and this pass
assigns each 4-D activation edge a PHYSICAL layout:

  * layout-preferring ops (Conv2D / Pool2D / BatchNorm) execute in NHWC
    and emit NHWC;
  * layout-agnostic pointwise ops (ElementUnary, Dropout, Cast, and
    same-shape ElementBinary — the residual add) pass whatever arrives
    straight through;
  * axis-remappable ops (Concat / Split — the Inception branch joins)
    stay in NHWC by remapping their axis at execution;
  * every other consumer materializes logical NCHW.

For a ResNet/Inception tower this inserts exactly one NCHW->NHWC
transpose at the input and one NHWC->NCHW before the classifier head;
the executor performs the conversions and permutes sharding specs for
NHWC-stored tensors.
"""
from __future__ import annotations

from typing import Dict, Set, Tuple

from ..fftype import OperatorType

LOGICAL = "nchw"
NHWC = "nhwc"

# logical NCHW axis -> physical NHWC position
NCHW_TO_NHWC_AXIS = {0: 0, 1: 3, 2: 1, 3: 2}
TO_NHWC_PERM = (0, 2, 3, 1)  # physical transpose logical->nhwc
TO_NCHW_PERM = (0, 3, 1, 2)  # physical transpose nhwc->logical

_PREFER = {OperatorType.CONV2D, OperatorType.POOL2D, OperatorType.BATCH_NORM}
_AGNOSTIC = {OperatorType.ELEMENT_UNARY, OperatorType.DROPOUT,
             OperatorType.CAST}
_REMAP = {OperatorType.CONCAT, OperatorType.SPLIT}


def _is_4d(pt) -> bool:
    return pt.shape.logical_rank == 4


def assign_layouts(
    graph, block_guids: Set[int] = frozenset()
) -> Tuple[Dict[int, str], Dict[int, str]]:
    """One topo walk -> (tensor guid -> layout, op guid -> exec layout).

    Tensor layouts: only NHWC entries are recorded; absent means
    logical.  Op exec layouts: "nhwc" (executor converts 4-D inputs to
    NHWC, forward runs with _data_layout="nhwc"), "pass" (pointwise —
    inputs used exactly as stored), absent (logical — executor
    materializes NCHW for any NHWC input).  Ops inside pipeline blocks
    run their template forwards directly (executor
    _run_pipeline_region), so they are pinned logical.
    """
    t_layout: Dict[int, str] = {}
    op_layout: Dict[int, str] = {}
    for op in graph.topo_order():
        if op.guid in block_guids:
            continue
        ot = op.op_type
        in_lay = [t_layout.get(t.guid, LOGICAL) for t in op.inputs]
        if ot in _PREFER and op.inputs and all(_is_4d(t) for t in op.inputs):
            op_layout[op.guid] = NHWC
            for out in op.outputs:
                if _is_4d(out):
                    t_layout[out.guid] = NHWC
        elif (
            ot in _AGNOSTIC
            and op.inputs
            and _is_4d(op.inputs[0])
            and in_lay[0] == NHWC
        ):
            # pointwise: value flows through in whatever layout it has
            op_layout[op.guid] = "pass"
            for out in op.outputs:
                if _is_4d(out):
                    t_layout[out.guid] = NHWC
        elif (
            ot == OperatorType.ELEMENT_BINARY
            and len(op.inputs) == 2
            and all(_is_4d(t) for t in op.inputs)
            and op.inputs[0].shape.logical_shape
            == op.inputs[1].shape.logical_shape
            and all(l == NHWC for l in in_lay)
        ):
            # same-shape add/mul (residual join): no broadcasting, so the
            # physical permutation is transparent
            op_layout[op.guid] = "pass"
            for out in op.outputs:
                if _is_4d(out):
                    t_layout[out.guid] = NHWC
        elif (
            ot in _REMAP
            and op.inputs
            and all(_is_4d(t) for t in op.inputs)
            and all(_is_4d(t) for t in op.outputs)
            and all(l == NHWC for l in in_lay)
        ):
            op_layout[op.guid] = NHWC
            for out in op.outputs:
                t_layout[out.guid] = NHWC
        # else: logical — executor materializes NCHW for any NHWC input
    return t_layout, op_layout
