"""Graph segmentation utilities shared by the Unity DP search and the
pipeline-stage planner.

Reference: `find_split_node` (substitution.cc:2094) cuts the PCG at
single-tensor bottlenecks for the sequence DP; the same cuts are where
pipeline stages can legally begin (exactly one activation crosses, so
one ppermute per tick moves the full inter-stage state).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..ops.op import Op
from .graph import Graph


def external_inputs(ops: List[Op]) -> List[int]:
    """Guids of tensors consumed by `ops` but produced outside, ordered
    by first consumption — THE boundary-detection helper shared by the
    Unity region DP, the pipeline planner, and pp candidate costing."""
    produced = {t.guid for op in ops for t in op.outputs}
    out: List[int] = []
    seen = set()
    for op in ops:
        for t in op.inputs:
            if t.guid not in produced and t.guid not in seen:
                seen.add(t.guid)
                out.append(t.guid)
    return out


def last_use_positions(topo: List[Op]) -> Dict[int, int]:
    """tensor guid -> topo position of its last consumer (shared by the
    segment splitter and the simulator's liveness scan)."""
    pos = {op.guid: i for i, op in enumerate(topo)}
    last_use: Dict[int, int] = {}
    for op in topo:
        for t in op.inputs:
            last_use[t.guid] = max(last_use.get(t.guid, -1), pos[op.guid])
    return last_use


def split_segments(graph: Graph) -> Tuple[List[List[Op]], List[Optional[int]]]:
    """Split topo order at single-tensor cuts.

    Returns (segments, crossing_guid_per_boundary): segment k feeds
    segment k+1 through exactly one tensor (the bottleneck); the final
    boundary is None."""
    return split_segments_ops(graph.topo_order())


def split_segments_ops(
    topo: List[Op],
) -> Tuple[List[List[Op]], List[Optional[int]]]:
    """`split_segments` over an already topo-ordered op list — the form
    the searched-remat costing uses on the evaluator's applied op
    sequences (where no Graph object exists on the delta path).  Runs a
    single O(n) liveness sweep: a tensor produced at position j with
    last use at position lu crosses every boundary i with j <= i < lu,
    so the live set is maintained incrementally instead of rescanning
    the prefix per position."""
    last_use = last_use_positions(topo)
    live = set()
    cuts: List[Tuple[int, int]] = []  # (topo position, crossing tensor guid)
    n = len(topo)
    expire: Dict[int, List[int]] = {}
    for op in topo:
        for t in op.outputs:
            lu = last_use.get(t.guid, -1)
            if lu >= 0:
                expire.setdefault(lu, []).append(t.guid)
    for i, op in enumerate(topo):
        for t in op.outputs:
            if last_use.get(t.guid, -1) > i:
                live.add(t.guid)
        for g in expire.get(i, ()):
            live.discard(g)
        if i < n - 1 and len(live) == 1:
            cuts.append((i, next(iter(live))))
    segments: List[List[Op]] = []
    boundaries: List[Optional[int]] = []
    start = 0
    for i, guid in cuts:
        segments.append(topo[start : i + 1])
        boundaries.append(guid)
        start = i + 1
    segments.append(topo[start:])
    boundaries.append(None)
    return segments, boundaries


def segment_signature(seg: List[Op], boundary_in: List[int]) -> Tuple:
    """Structural signature: identical stacked layers share it."""
    local = {guid: ("b", k) for k, guid in enumerate(boundary_in)}
    parts = []
    for j, op in enumerate(seg):
        srcs = tuple(local[t.guid] for t in op.inputs)
        parts.append((op.op_type, op.params, srcs))
        for oi, t in enumerate(op.outputs):
            local[t.guid] = ("i", j, oi)
    return tuple(parts)


def find_repeated_blocks(graph: Graph) -> List[List[Op]]:
    """Longest run of consecutive, structurally identical,
    shape-preserving single-tensor-boundary blocks — the pipelineable
    region (e.g. a transformer's stacked encoder layers).

    A block may span several segments (a period): the detector tries
    every (start, period) over the segment list and keeps the maximal
    repetition count x period coverage.  Requirements for pipelining:
      * >= 2 repetitions;
      * every block boundary crosses exactly one tensor whose logical
        shape/dtype matches the region's input (homogeneous stages —
        gpipe rotates a fixed-shape activation).
    Returns [] when no such region exists.
    """
    segments, boundaries = split_segments(graph)
    # signature of each segment, keyed by its incoming boundary guid
    sigs: List[Tuple] = []
    incoming: List[int] = []
    for seg, out_guid in zip(segments, boundaries):
        sigs.append(segment_signature(seg, incoming))
        incoming = [out_guid] if out_guid is not None else []

    tensor_by_guid = {}
    for op in graph.ops:
        for t in op.outputs:
            tensor_by_guid[t.guid] = t

    def boundary_shape(i: int):
        g = boundaries[i]
        if g is None:
            return None
        t = tensor_by_guid[g]
        return (tuple(t.shape.logical_shape), t.shape.dtype)

    n = len(segments)
    best: Tuple[int, int, int] = (0, 0, 0)  # (coverage, start, period)
    for period in range(1, n // 2 + 1):
        for start in range(0, n - 2 * period + 1):
            # block k = segments[start + k*period : start + (k+1)*period]
            reps = 1
            while True:
                nxt = start + reps * period
                if nxt + period > n:
                    break
                if any(
                    sigs[nxt + j] != sigs[start + j] for j in range(period)
                ):
                    break
                reps += 1
            if reps < 2:
                continue
            # homogeneous boundaries: each block ends at a single-tensor
            # cut with the same activation shape as the region input
            in_shape = boundary_shape(start - 1) if start > 0 else None
            shapes = [
                boundary_shape(start + (k + 1) * period - 1)
                for k in range(reps - 1)
            ]
            ref = shapes[0]
            if ref is None or any(s != ref for s in shapes):
                continue
            if in_shape is not None and in_shape != ref:
                # region input reshaped differently -> first block is not
                # homogeneous with the rest; drop it
                continue
            coverage = reps * period
            if coverage > best[0]:
                best = (coverage, start, period)
    if best[0] == 0:
        return []
    _, start, period = best
    reps = best[0] // period
    blocks = []
    for k in range(reps):
        ops: List[Op] = []
        for j in range(period):
            ops.extend(segments[start + k * period + j])
        blocks.append(ops)
    return blocks
