"""Executor: lowers a strategy-annotated PCG to jitted SPMD step functions.

This file replaces the reference's entire execution machinery — the
Legion task launches in every op's init/forward/backward
(e.g. linear.cc:328-436), the FFMapper placement (mapper.cc), Legion
iteration tracing (begin_trace/end_trace, flexflow_cffi.py:2078-2086),
and the NCCL optimizer sync (optimizer_kernel.cu:88) — with ONE design:

  * the whole training step (forward, loss, backward via jax.grad,
    metrics, optimizer update) is a single `jax.jit` computation over a
    `Mesh`, with every PCG tensor's MachineView lowered to a
    `with_sharding_constraint`;
  * XLA SPMD inserts all collectives (grad psum, tensor-parallel
    all-reduce/all-gather, MoE all-to-all) over ICI;
  * Legion's trace replay == XLA's compiled executable cache;
  * backward needs no per-op code at all.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .fftype import CompMode, OperatorType
from .loss import Loss
from .metrics import Metrics
from .ops.op import Op, trainable_weight_count as _num_trainable
from .optimizer import Optimizer
from .parallel.machine import view_to_spec
from .pcg.graph import Graph


class NonFiniteLossError(RuntimeError):
    """A train/eval step produced a non-finite (NaN/inf) loss.

    Raised by `check_step_health`; the resilience supervisor maps it to
    FFConfig.nan_policy (raise | skip_step | restore)."""

    def __init__(self, loss: float, step: Optional[int] = None):
        self.loss = loss
        self.step = step
        where = f" at step {step}" if step is not None else ""
        super().__init__(f"non-finite loss {loss!r}{where}")


def check_step_health(metrics: Dict[str, Any], step: Optional[int] = None,
                      nan_policy: str = "raise", watchdog=None) -> None:
    """Step health hook: raise NonFiniteLossError when the step's loss
    is NaN/inf.  Reads the metrics dict a step function returned, which
    blocks on the device value — so the sync is gated on the configured
    policy: with nan_policy "off" (or None) no caller consumes the
    health signal and the function returns without ever touching the
    device array.

    `watchdog` (a resilience.watchdog.StepWatchdog) bounds that device
    sync: a wedged collective raises HungStepTimeout here instead of
    blocking the host forever, so callers using this as their per-step
    sync point get hang detection for free."""
    if nan_policy in (None, "off"):
        return
    loss = metrics.get("loss") if isinstance(metrics, dict) else None
    if loss is None:
        return

    def read():
        return float(np.asarray(loss))

    val = watchdog.sync(read, step=step) if watchdog is not None else read()
    if not np.isfinite(val):
        raise NonFiniteLossError(val, step=step)


class GraphExecutor:
    """Compiles a PCG + strategy into init/step callables on a mesh."""

    def __init__(
        self,
        graph: Graph,
        mesh: Mesh,
        loss: Loss,
        metrics: Metrics,
        optimizer: Optimizer,
        comp_mode: CompMode = CompMode.TRAINING,
        label_replication: int = 1,
        remat: bool = False,
        compute_dtype=None,
        pipeline_plan=None,
        wus_axis: Optional[str] = None,
        zero_stage: int = 0,
        hier_axis: Optional[str] = None,
        remat_segments: Optional[Sequence[int]] = None,
    ):
        self.graph = graph
        self.mesh = mesh
        self.loss = loss
        self.metrics = metrics
        self.optimizer = optimizer
        self.comp_mode = comp_mode
        self.label_replication = label_replication
        self.remat = remat
        # Mixed precision (TPU: bfloat16 on the MXU, f32 master weights
        # and loss — replaces the reference's per-kernel DT_HALF support)
        self.compute_dtype = (
            jnp.dtype(compute_dtype) if compute_dtype is not None else None
        )
        self.order = graph.topo_order()
        self.sink = graph.sink_op()
        self._use_constraints = mesh.devices.size > 1
        # ZeRO ladder (parallel/zero.py, docs/PERF.md): the wus axis is
        # active only when it exists on the mesh with size > 1; without
        # it every stage collapses to 0 (the replicated update).
        #   stage 1: sharded update (grads reduce-scattered at the
        #            update, slots resident on the 1/N shard);
        #   stage 2: the gradient buffer itself is constrained to the
        #            scattered layout out of backward — grad HBM / N;
        #   stage 3: master weights live permanently scattered with
        #            just-in-time per-layer all-gather on use and
        #            double-buffered prefetch (no post-update gather).
        mesh_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.wus_axis = (
            wus_axis if wus_axis and mesh_sizes.get(wus_axis, 1) > 1 else None
        )
        # a live wus axis with stage 0 means a pre-ladder caller passed
        # only wus_axis: that contract WAS ZeRO-1
        self.zero_stage = (
            max(1, int(zero_stage)) if self.wus_axis is not None else 0
        )
        # multi-slice hierarchical grad reduction (topology/,
        # docs/TOPOLOGY.md): on a two-level mesh whose placement axis
        # has an intra-slice remainder, `hier_axis` names that
        # remainder.  With the ZeRO ladder off (no wus axis), the
        # update wrapper still re-specs the grads through the scattered
        # layout over it — XLA SPMD then lowers the cross-slice psum as
        # reduce-scatter over ICI, all-reduce of the shard over DCN,
        # all-gather over ICI — bit-identical to the flat all-reduce.
        # With the ladder ON, the wus machinery over the (now
        # intra-slice) wus axis already produces the hierarchical form,
        # so hier_axis is only consulted when wus is inactive.
        self.hier_axis = (
            hier_axis
            if hier_axis and mesh_sizes.get(hier_axis, 1) > 1
            and self.wus_axis is None
            else None
        )
        for op in self.order:
            op._mesh = mesh  # ops with shard_map lowerings (ring attention)
        self._step_fn = None
        self._input_names = [op.name for op in graph.source_ops()]
        # pipeline-parallel region (parallel/pipeline_plan.py): block ops
        # execute via the GPipe schedule with pp-stacked weights under
        # the "__pipeline__" pytree key instead of per-op entries
        self.pipeline_plan = pipeline_plan
        self._block_guids = (
            {op.guid for blk in pipeline_plan.blocks for op in blk}
            if pipeline_plan is not None
            else set()
        )
        # rematerialisation plan: single-tensor-boundary segments whose
        # internals are recomputed in backward (jax.checkpoint), saving
        # only boundary activations — the HBM/FLOPs trade the reference
        # cannot express (Legion keeps every region alive).
        # `remat_segments` (a strategy's searched per-segment plan,
        # docs/PERF.md "Searched rematerialization") selects WHICH
        # segments checkpoint; the global `remat` bool checkpoints every
        # pure segment (the plan, when present, takes precedence).
        plan = (
            self._build_remat_plan(remat_segments)
            if (remat or remat_segments is not None) else None
        )
        if plan is not None and not any(pure for *_, pure in plan):
            # nothing checkpoints (e.g. an explicit all-off searched
            # plan): keep the flat interpreter, which also keeps the
            # ZeRO-3 double-buffered prefetch path
            plan = None
        self._remat_plan = plan
        # physical NHWC layout for CNN activations (pcg/layout.py): the
        # logical shapes stay NCHW; conversions happen at exec time
        from .pcg.layout import assign_layouts

        self._t_layout, self._op_layout = assign_layouts(
            graph, self._block_guids
        )
        for op in self.order:
            op._data_layout = (
                "nhwc" if self._op_layout.get(op.guid) == "nhwc" else "nchw"
            )
        # ZeRO-3 just-in-time gather targets (op -> weight -> strategy
        # sharding); None below stage 3, so the weight-read hot path
        # pays one None check when the ladder is off or low
        self._z3_gather = (
            self._z3_gather_map() if self.zero_stage >= 3 else None
        )

    def _build_remat_plan(self, selected: Optional[Sequence[int]] = None):
        """[(ops, in_guids, out_guids, pure)] per segment.  Impure
        segments (inputs, cache, state, aux, pipeline blocks) run
        inline; pure ones are wrapped in jax.checkpoint.  `selected`
        (a searched strategy's per-segment plan) restricts the wrap to
        the named segment indices — everything else runs inline, so a
        plan naming every pure segment is exactly the legacy --remat
        lowering, and an empty plan is numerically the dense step."""
        OT = OperatorType
        from .pcg.segments import external_inputs, split_segments

        sel = None if selected is None else {int(i) for i in selected}
        segments, _ = split_segments(self.graph)
        pos_of = {}
        for i, seg in enumerate(segments):
            for op in seg:
                pos_of[op.guid] = i
        sink_out = self.sink.outputs[0].guid
        consumers: Dict[int, List[int]] = {}
        for op in self.graph.ops:
            for t in op.inputs:
                consumers.setdefault(t.guid, []).append(pos_of[op.guid])
        impure_types = {OT.INPUT, OT.CACHE, OT.GROUP_BY, OT.AGGREGATE,
                        OT.AGGREGATE_SPEC}
        plan = []
        for i, seg in enumerate(segments):
            out_guids = [
                t.guid
                for op in seg
                for t in op.outputs
                if t.guid == sink_out
                or any(c > i for c in consumers.get(t.guid, ()))
            ]
            pure = (sel is None or i in sel) and all(
                op.op_type not in impure_types
                and op.guid not in self._block_guids
                and _num_trainable(op) == len(op.weight_specs)
                for op in seg
            )
            plan.append((seg, external_inputs(seg), out_guids, pure))
        return plan

    # -- shardings -------------------------------------------------------
    def tensor_sharding(self, pt) -> NamedSharding:
        return NamedSharding(self.mesh, view_to_spec(pt))

    def _physical_sharding(self, pt) -> NamedSharding:
        """Sharding for the value as stored in env: NHWC-stored tensors
        get their logical NCHW spec permuted to match."""
        from .pcg.layout import NHWC, TO_NHWC_PERM

        spec = view_to_spec(pt)
        if self._t_layout.get(pt.guid) == NHWC:
            entries = list(spec) + [None] * (4 - len(spec))
            spec = PartitionSpec(*(entries[i] for i in TO_NHWC_PERM))
        return NamedSharding(self.mesh, spec)

    def _weight_sharding_tree(
        self, make
    ) -> Dict[str, Dict[str, NamedSharding]]:
        """The ONE walk over trainable-weight leaves (per-op entries
        plus the pp-stacked __pipeline__ entries).  `make(spec, shape)`
        maps each leaf's strategy PartitionSpec + global shape to its
        NamedSharding, so weight_shardings and wus_shardings stay
        structurally identical by construction."""
        out: Dict[str, Dict[str, NamedSharding]] = {}
        for op in self.order:
            if op.guid in self._block_guids:
                continue
            nt = _num_trainable(op)
            entry = {}
            for w in op.weights[:nt]:
                entry[w.name.split(".")[-1]] = make(
                    view_to_spec(w), w.shape.logical_shape
                )
            if entry:
                out[op.name] = entry
        if self.pipeline_plan is not None:
            entry = {}
            plan = self.pipeline_plan
            for j, op in enumerate(plan.blocks[0]):
                for spec, pt in zip(op.weight_specs, op.weights):
                    shape = (len(plan.blocks),) + tuple(
                        pt.shape.logical_shape
                    )  # stacked dim leads
                    entry[f"{j}.{spec.name}"] = make(
                        PartitionSpec(
                            plan.pp_axis, *([None] * (len(shape) - 1))
                        ),
                        shape,
                    )
            if entry:
                out["__pipeline__"] = entry
        return out

    def weight_shardings(self) -> Dict[str, Dict[str, NamedSharding]]:
        return self._weight_sharding_tree(
            lambda spec, shape: NamedSharding(self.mesh, spec)
        )

    def wus_shardings(self) -> Dict[str, Dict[str, NamedSharding]]:
        """ZeRO-1 update layout (parallel/zero.py): each trainable
        weight's strategy sharding with the wus axis folded into its
        first free, evenly-divisible logical dim.  Leaves with no such
        dim keep their strategy sharding — they fall back to the
        replicated update individually.  Mirrors weight_shardings()'s
        pytree structure exactly (same underlying walk)."""
        return self._scatter_shardings(self.wus_axis)

    def _scatter_shardings(self, axis: str
                           ) -> Dict[str, Dict[str, NamedSharding]]:
        """Every trainable leaf's strategy sharding with `axis` folded
        into its first free, evenly-divisible logical dim (the shared
        parallel/zero.py axis-picking) — the wus layout when `axis` is
        the wus axis, the hierarchical-reduction scatter layout when it
        is the intra-slice remainder of a cross-slice placement."""
        from .parallel.zero import shard_update_spec

        size = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))[axis]

        def make(spec, shape):
            z = shard_update_spec(spec, shape, axis, size)
            return NamedSharding(self.mesh, z if z is not None else spec)

        return self._weight_sharding_tree(make)

    def master_weight_shardings(self) -> Dict[str, Dict[str, NamedSharding]]:
        """Resident layout of the master weight tree: the strategy
        shardings below stage 3; the ZeRO-3 scattered (wus) layout at
        stage 3 — per-op entries only, since the pipeline-stacked
        `__pipeline__` weights are already 1/S per device on the pipe
        axis and the GPipe region consumes them whole."""
        if self.zero_stage < 3:
            return self.weight_shardings()
        out = self.wus_shardings()
        if "__pipeline__" in out:
            out["__pipeline__"] = self.weight_shardings()["__pipeline__"]
        return out

    def grad_shardings(self) -> Dict[str, Dict[str, NamedSharding]]:
        """Layout the backward gradients are constrained to: the
        scattered (wus) layout at ZeRO stage >= 2 — per-device grad HBM
        drops by 1/N and the grads feed the 1/N-shard update directly —
        else each weight's strategy sharding."""
        if self.zero_stage >= 2:
            return self.wus_shardings()
        return self.weight_shardings()

    def _wus_layout_diff(
        self,
    ) -> Tuple[Dict[str, Dict[str, NamedSharding]], List[str]]:
        """One strat-vs-wus tree walk classifying every trainable leaf.
        Returns (gather_map, fallback_names): leaves whose wus layout
        differs from the strategy layout live scattered — the stage-3
        gather map, op name -> {weight name: strategy NamedSharding},
        per-op entries only since the pp-stacked `__pipeline__` weights
        are consumed whole by the GPipe region — while leaves where
        shard_update_spec kept the strategy spec exactly fell back to
        the replicated update ('op.weight' names, `__pipeline__`
        included: those participate in the sharded update like any
        other leaf)."""
        strat = self.weight_shardings()
        wus = self.wus_shardings()
        gather: Dict[str, Dict[str, NamedSharding]] = {}
        fallback: List[str] = []
        for op_name, entry in strat.items():
            need = {}
            for wname, sh in entry.items():
                if wus[op_name][wname] == sh:
                    fallback.append(f"{op_name}.{wname}")
                elif op_name != "__pipeline__":
                    need[wname] = sh
            if need:
                gather[op_name] = need
        return gather, fallback

    def zero_fallback_leaves(self) -> List[str]:
        """'op.weight' names whose update falls back to the replicated
        path while update sharding is active (no free logical dim
        evenly divisible by the wus axis, or the axis already shards
        the leaf) — the observability face of parallel/zero.py's
        silent per-leaf fallback.  Empty when the ladder is off."""
        if self.wus_axis is None:
            return []
        return self._wus_layout_diff()[1]

    def _z3_gather_map(self) -> Dict[str, Dict[str, NamedSharding]]:
        """Stage-3 leaves that actually live scattered (fallback leaves
        are absent — they're already resident at their strategy
        sharding and need no gather)."""
        return self._wus_layout_diff()[0]

    def shard_opt_state(self, opt_state):
        """device_put the optimizer's weight-mirroring slot trees (SGD
        v, Adam m/v) onto the ZeRO-1 update layout — 1/N per-device HBM
        along the wus axis — and scalar entries (Adam's t) onto a
        mesh-replicated sharding (an eagerly created scalar carries a
        single-device sharding that checkpoint restore would otherwise
        commit to, wedging multi-device steps).  When weight-update
        sharding is off (or its axis collapsed on the searched mesh)
        the slot trees inherit each weight's strategy sharding from
        init_state, but scalar entries still get the replicated put —
        the wedge doesn't care whether ZeRO-1 is on."""
        if self.mesh.devices.size <= 1:
            return opt_state
        rep = NamedSharding(self.mesh, PartitionSpec())
        if self.wus_axis is None:
            return {
                k: sub if isinstance(sub, dict) else jax.device_put(sub, rep)
                for k, sub in opt_state.items()
            }
        sh = self.wus_shardings()
        return {
            k: (
                jax.tree.map(lambda v, s: jax.device_put(v, s), sub, sh)
                if isinstance(sub, dict)
                else jax.device_put(sub, rep)
            )
            for k, sub in opt_state.items()
        }

    def state_shardings(self) -> Dict[str, Dict[str, NamedSharding]]:
        out: Dict[str, Dict[str, NamedSharding]] = {}
        for op in self.order:
            nt = _num_trainable(op)
            entry = {}
            for w in op.weights[nt:]:
                entry[w.name.split(".")[-1]] = self.tensor_sharding(w)
            if entry:
                out[op.name] = entry
        return out

    def input_shardings(self) -> Dict[str, NamedSharding]:
        return {
            op.name: self.tensor_sharding(op.outputs[0])
            for op in self.graph.source_ops()
        }

    def label_sharding(self) -> NamedSharding:
        # labels follow the final op's sample-dim sharding (reference
        # creates the label tensor to match the final op's machine view,
        # model.cc:3086-3124)
        spec = view_to_spec(self.sink.outputs[0])
        first = spec[0] if len(spec) else None
        return NamedSharding(self.mesh, PartitionSpec(first))

    # -- weight init -----------------------------------------------------
    def init_weights(self, seed: int = 0):
        """Initialize weight + state pytrees, sharded via out_shardings
        (stage 3 initializes master weights directly onto their
        scattered resident layout)."""
        w_shardings = self.master_weight_shardings()
        s_shardings = self.state_shardings()

        def build():
            weights: Dict[str, Dict[str, jax.Array]] = {}
            state: Dict[str, Dict[str, jax.Array]] = {}
            key = jax.random.key(seed)
            for op in self.order:
                if op.guid in self._block_guids:
                    continue
                nt = _num_trainable(op)
                for i, (spec, pt) in enumerate(zip(op.weight_specs, op.weights)):
                    key, sub = jax.random.split(key)
                    dtype = pt.dtype.np_dtype
                    if (
                        i >= nt
                        and spec.name in ("k_cache", "v_cache")
                        and self.compute_dtype is not None
                    ):
                        # decode caches live in the compute dtype: their
                        # values are produced in it anyway, and an f32
                        # cache would double HBM footprint and add a
                        # full-cache cast per token (ADVICE r4)
                        dtype = self.compute_dtype
                    arr = spec.initializer(
                        sub, pt.shape.logical_shape, dtype
                    )
                    short = spec.name
                    if i < nt:
                        weights.setdefault(op.name, {})[short] = arr
                    else:
                        state.setdefault(op.name, {})[short] = arr
            if self.pipeline_plan is not None:
                # per-block independent inits stacked on a leading dim
                # sharded over the pp axis
                for j, t_op in enumerate(self.pipeline_plan.blocks[0]):
                    for wi, spec in enumerate(t_op.weight_specs):
                        layers = []
                        for blk in self.pipeline_plan.blocks:
                            w_spec = blk[j].weight_specs[wi]
                            w_pt = blk[j].weights[wi]
                            key, sub = jax.random.split(key)
                            layers.append(
                                w_spec.initializer(
                                    sub,
                                    w_pt.shape.logical_shape,
                                    w_pt.dtype.np_dtype,
                                )
                            )
                        weights.setdefault("__pipeline__", {})[
                            f"{j}.{spec.name}"
                        ] = jnp.stack(layers)
            return weights, state

        out_shardings = (w_shardings, s_shardings)
        with self.mesh:
            return jax.jit(build, out_shardings=out_shardings)()

    # -- forward ---------------------------------------------------------
    def run_forward(
        self,
        weights,
        state,
        inputs: Dict[str, jax.Array],
        training: bool,
        rng: Optional[jax.Array],
    ):
        """Interpret the PCG. Returns (sink_output, new_state, aux_losses, env)."""
        env: Dict[int, jax.Array] = {}
        new_state = {k: dict(v) for k, v in state.items()}
        aux_losses: List[jax.Array] = []

        def to_compute(x):
            if (
                self.compute_dtype is not None
                and jnp.issubdtype(x.dtype, jnp.floating)
                and x.dtype != self.compute_dtype
            ):
                return x.astype(self.compute_dtype)
            return x

        state_ctx = {
            "pipeline_done": False,
            "weights": weights,
            "state": state,
            "new_state": new_state,
            "aux": aux_losses,
            "inputs": inputs,
            "training": training,
            "rng": rng,
            "to_compute": to_compute,
            # ZeRO-3 gathered-weight memo: flat path only.  Under remat
            # it stays None so gathers are emitted INSIDE checkpointed
            # segments — jax.checkpoint then re-gathers in backward
            # instead of saving full gathered copies as residuals (the
            # FSDP memory contract; see docs/PERF.md).
            "z3_cache": None,
        }
        if self._remat_plan is not None and training:
            for seg, in_guids, out_guids, pure in self._remat_plan:
                if not pure:
                    for op in seg:
                        self._exec_op(op, env, state_ctx)
                    continue

                def seg_fn(*in_vals, _seg=seg, _in=in_guids, _out=out_guids):
                    local = dict(zip(_in, in_vals))
                    for op in _seg:
                        self._exec_op(op, local, state_ctx)
                    return tuple(local[g] for g in _out)

                outs = jax.checkpoint(seg_fn)(
                    *(env[g] for g in in_guids)
                )
                env.update(zip(out_guids, outs))
        else:
            z3_next = None
            if self._z3_gather is not None:
                # explicit double-buffered prefetch: gather op k+1's
                # scattered weights BEFORE op k's compute is traced, so
                # XLA's scheduler can overlap the all-gather of the
                # next layer with the current layer's work (this
                # replaces the post-update whole-tree all-gather that
                # stages 1/2 pay)
                state_ctx["z3_cache"] = {}
                gatherable = [
                    o for o in self.order if o.name in self._z3_gather
                ]
                z3_next = {
                    a.guid: b for a, b in zip(gatherable, gatherable[1:])
                }
                if gatherable:
                    self._z3_prefetch(gatherable[0], state_ctx)
            for op in self.order:
                if z3_next is not None and op.guid in z3_next:
                    self._z3_prefetch(z3_next[op.guid], state_ctx)
                self._exec_op(op, env, state_ctx)
        out = env[self.sink.outputs[0].guid]
        from .pcg.layout import NHWC, TO_NCHW_PERM

        if self._t_layout.get(self.sink.outputs[0].guid) == NHWC:
            out = jnp.transpose(out, TO_NCHW_PERM)  # callers see logical
        if self.compute_dtype is not None and jnp.issubdtype(out.dtype, jnp.floating):
            out = out.astype(jnp.float32)  # loss/metrics in full precision
        return out, new_state, aux_losses, env

    def _z3_fetch(self, op_name: str, wname: str, w, ctx: Dict):
        """One trainable weight as the compute copy: below stage 3 the
        resident value IS the compute copy; at stage 3 a scattered leaf
        is constrained to its strategy sharding (XLA SPMD emits the
        just-in-time per-layer all-gather), memoized per trace through
        ctx['z3_cache'] so the prefetch and the use share one gather."""
        if self._z3_gather is None:
            return w
        sh = self._z3_gather.get(op_name, {}).get(wname)
        if sh is None:
            return w  # fallback leaf: already resident at strategy layout
        cache = ctx.get("z3_cache")
        if cache is not None:
            hit = cache.get((op_name, wname))
            if hit is not None:
                return hit
        g = jax.lax.with_sharding_constraint(w, sh)
        if cache is not None:
            cache[(op_name, wname)] = g
        return g

    def _z3_prefetch(self, op: Op, ctx: Dict):
        """Populate the gather memo for all of `op`'s scattered weights
        (emits their all-gathers at the CURRENT trace point)."""
        entry = ctx["weights"].get(op.name, {})
        for wname in self._z3_gather.get(op.name, {}):
            self._z3_fetch(op.name, wname, entry[wname], ctx)

    def _exec_op(self, op: Op, env: Dict[int, jax.Array], ctx: Dict):
        """Execute one PCG op into env — the shared body of the flat
        interpreter and the remat segment functions.  The op's jax ops
        are emitted under `jax.named_scope(op.name)` so device-side
        profiles (jax.profiler / XLA op_name metadata) attribute to PCG
        operator names; named_scope runs at trace time only, so the
        compiled step pays nothing per iteration."""
        with jax.named_scope(op.name):
            self._exec_op_traced(op, env, ctx)

    def _exec_op_traced(self, op: Op, env: Dict[int, jax.Array], ctx: Dict):
        training = ctx["training"]
        to_compute = ctx["to_compute"]
        if (
            op.op_type == OperatorType.CACHE
            and getattr(op, "_load_cached", False)
        ):
            # replay the host-cached batch (reference load_cached
            # forward, cache.cc:214-231), fed as an extra input
            env[op.outputs[0].guid] = to_compute(
                ctx["inputs"][f"__cache__{op.name}"]
            )
            return
        if op.guid in self._block_guids:
            if not ctx["pipeline_done"]:
                out = self._run_pipeline_region(
                    ctx["weights"], env, to_compute, training, ctx["rng"]
                )
                env[self.pipeline_plan.region_out_guid] = out
                ctx["pipeline_done"] = True
            return
        if op.op_type == OperatorType.INPUT:
            env[op.outputs[0].guid] = to_compute(ctx["inputs"][op.name])
            return
        from .pcg.layout import NHWC, TO_NCHW_PERM, TO_NHWC_PERM

        want = self._op_layout.get(op.guid)
        ins = []
        for t in op.inputs:
            v = env[t.guid]
            have_nhwc = self._t_layout.get(t.guid) == NHWC
            if want == "nhwc" and not have_nhwc and v.ndim == 4:
                v = jnp.transpose(v, TO_NHWC_PERM)
            elif want is None and have_nhwc:
                v = jnp.transpose(v, TO_NCHW_PERM)
            ins.append(v)
        nt = _num_trainable(op)
        ws: List[jax.Array] = []
        for i, spec in enumerate(op.weight_specs):
            src = ctx["weights"] if i < nt else ctx["state"]
            w = src[op.name][spec.name]
            if i < nt and self._z3_gather is not None:
                w = self._z3_fetch(op.name, spec.name, w, ctx)
            ws.append(to_compute(w))
        op_rng = None
        if ctx["rng"] is not None:
            op_rng = jax.random.fold_in(ctx["rng"], op.guid)
        results = op.forward(ins, ws, training=training, rng=op_rng)
        outs = results[: len(op.outputs)]
        extra = results[len(op.outputs):]
        if extra:
            for spec, val in zip(op.weight_specs[nt:], extra):
                ctx["new_state"][op.name][spec.name] = val.astype(
                    ctx["state"][op.name][spec.name].dtype
                )
        aux = getattr(op, "_last_aux", None)
        if aux is not None:
            ctx["aux"].append(aux)
            op._last_aux = None
        for pt, val in zip(op.outputs, outs):
            if self._use_constraints:
                val = jax.lax.with_sharding_constraint(
                    val, self._physical_sharding(pt)
                )
            env[pt.guid] = val

    # -- pipeline region -------------------------------------------------
    def _run_pipeline_region(self, weights, env, to_compute, training, rng):
        """Execute the homogeneous block stack via the GPipe schedule
        (parallel/pipeline.py): blocks stacked over the pp axis, one
        ppermute per tick over ICI, backward by autodiff through the
        scan."""
        from .parallel.pipeline import pipelined_apply

        plan = self.pipeline_plan
        template = plan.blocks[0]
        act = env[plan.region_in_guid]
        from .pcg.layout import NHWC, TO_NCHW_PERM

        if self._t_layout.get(plan.region_in_guid) == NHWC:
            # block template ops are pinned logical (assign_layouts skips
            # block guids); materialize the region input to match
            act = jnp.transpose(act, TO_NCHW_PERM)
        stacked = {
            k: to_compute(v) for k, v in weights["__pipeline__"].items()
        }
        # per-layer index rides the stacked pytree so dropout rng can
        # fold in the physical block id inside the scanned body
        stacked["__layer__"] = jnp.arange(plan.num_blocks, dtype=jnp.int32)

        def block_fn(params, a):
            local = {plan.region_in_guid: a}
            for j, t_op in enumerate(template):
                ins = [local[t.guid] for t in t_op.inputs]
                ws = [
                    params[f"{j}.{s.name}"] for s in t_op.weight_specs
                ]
                op_rng = None
                if rng is not None:
                    op_rng = jax.random.fold_in(
                        jax.random.fold_in(rng, t_op.guid),
                        params["__layer__"],
                    )
                outs = t_op.forward(ins, ws, training=training, rng=op_rng)
                for pt, val in zip(t_op.outputs, outs):
                    local[pt.guid] = val
            return local[plan.template_out_guid]

        return pipelined_apply(
            block_fn,
            stacked,
            act,
            mesh=self.mesh,
            num_microbatches=plan.num_microbatches,
            pp_axis=plan.pp_axis,
            dp_axis=plan.dp_axis,
            # --remat extends to the pipeline region: block internals
            # are recomputed in backward, so in-flight microbatches
            # cost one boundary activation each instead of the block's
            # full residuals
            remat=self.remat and training,
        )

    # -- train step ------------------------------------------------------
    def _make_update_fn(self, opt: Optimizer):
        """opt.update, wrapped for the ZeRO ladder when a wus axis is
        active (stage 1: arXiv:2004.13336; stages 2/3: arXiv:1910.02054):
        constraining the grads to the update layout turns the backward
        psum into a reduce-scatter, the update then runs on the 1/N
        shard (where the slots permanently live), and constraining the
        result back to the OUTPUT layout emits the weight all-gather —
        the strategy sharding at stages 1/2, or the scattered master
        layout at stage 3, where no post-update gather happens at all
        (forward re-gathers per layer instead).  Numerically the
        replicated update — all-reduce == reduce-scatter + all-gather —
        with 1/N of the update compute and slot HBM per device."""
        if self.wus_axis is None:
            if self.hier_axis is None:
                return opt.update
            # multi-slice, ladder off: synthesize the HIERARCHICAL grad
            # reduction alone.  Constraining the grads through the
            # scattered layout over the intra-slice axis and straight
            # back re-associates the cross-slice psum as
            # RS(ICI) -> AR(DCN on the 1/N shard) -> AG(ICI); the
            # update itself stays the plain replicated optimizer pass.
            # Bit-identical to the flat all-reduce (the same
            # re-association the ZeRO ladder's tests pin down).
            scat = self._scatter_shardings(self.hier_axis)
            out_sh = self.weight_shardings()

            def hier_update(weights, grads, state):
                grads = jax.tree.map(
                    jax.lax.with_sharding_constraint, grads, scat
                )
                grads = jax.tree.map(
                    jax.lax.with_sharding_constraint, grads, out_sh
                )
                return opt.update(weights, grads, state)

            return hier_update
        wus = self.wus_shardings()
        out_sh = self.master_weight_shardings()

        def constrain(tree, sh):
            return jax.tree.map(
                jax.lax.with_sharding_constraint, tree, sh
            )

        def update(weights, grads, state):
            grads = constrain(grads, wus)
            shard_w = constrain(weights, wus)
            new_w, new_state = opt.update(shard_w, grads, state)
            new_w = constrain(new_w, out_sh)
            new_state = {
                k: constrain(sub, wus) if isinstance(sub, dict) else sub
                for k, sub in new_state.items()
            }
            return new_w, new_state

        return update

    def build_step(self):
        metrics = self.metrics
        loss_obj = self.loss
        opt = self.optimizer
        update_fn = self._make_update_fn(opt)
        grad_sh = self.grad_shardings() if self.zero_stage >= 2 else None
        lrep = self.label_replication

        # replay-mode (_load_cached) ops are excluded: the reference's
        # load_cached forward performs no cache refresh (cache.cc:214);
        # block-region exclusion is defensive (plan_pipeline rejects
        # CACHE inside blocks)
        cache_ops = [
            op for op in self.order
            if op.op_type == OperatorType.CACHE
            and not getattr(op, "_load_cached", False)
            and op.guid not in self._block_guids
        ]

        def step(weights, opt_state, state, inputs, labels, rng):
            if lrep > 1:
                # AggregateSpec emits sample-major [s0k0, s0k1, s1k0, ...]
                labels = jnp.repeat(labels, lrep, axis=0)

            def loss_fn(w):
                logits, new_state, aux, env = self.run_forward(
                    w, state, inputs, training=True, rng=rng
                )
                loss_val = loss_obj(logits, labels)
                for a in aux:
                    loss_val = loss_val + a
                # cache taps: each Cache op's live input batch, handed
                # to the host for ring/score accounting (reference
                # cache_update task, cache.cc:180-231); materialized
                # logical so the host ring never sees a physical layout
                from .pcg.layout import NHWC, TO_NCHW_PERM

                taps = {
                    op.name: (
                        jnp.transpose(env[op.inputs[0].guid], TO_NCHW_PERM)
                        if self._t_layout.get(op.inputs[0].guid) == NHWC
                        else env[op.inputs[0].guid]
                    )
                    for op in cache_ops
                }
                return loss_val, (logits, new_state, taps)

            (loss_val, (logits, new_state, taps)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(weights)
            if grad_sh is not None:
                # ZeRO-2+: the gradient buffer is reduce-scattered AT
                # PRODUCTION and stays scattered through the update —
                # per-device grad HBM drops by 1/N, and no pre-update
                # gather ever materializes the full tree
                grads = jax.tree.map(
                    jax.lax.with_sharding_constraint, grads, grad_sh
                )
            new_w, new_opt_state = update_fn(weights, grads, opt_state)
            m = metrics.compute(logits, labels)
            m["loss"] = loss_val
            if taps:
                m["__cache_taps__"] = taps
            return new_w, new_opt_state, new_state, m

        with self.mesh:
            self._step_fn = jax.jit(step, donate_argnums=(0, 1, 2))
        return self._step_fn

    def build_eval_step(self):
        metrics = self.metrics
        loss_obj = self.loss
        lrep = self.label_replication

        def eval_step(weights, state, inputs, labels):
            if lrep > 1:
                labels = jnp.repeat(labels, lrep, axis=0)
            logits, _, _, _ = self.run_forward(
                weights, state, inputs, training=False, rng=None
            )
            m = metrics.compute(logits, labels)
            m["loss"] = loss_obj(logits, labels)
            return m

        with self.mesh:
            return jax.jit(eval_step)

    def build_forward(self):
        def fwd(weights, state, inputs):
            logits, _, _, _ = self.run_forward(
                weights, state, inputs, training=False, rng=None
            )
            return logits

        with self.mesh:
            return jax.jit(fwd)

    def build_decode_step(self):
        """Inference forward that RETURNS the updated op-state pytree —
        the KV-cache decode contract (attention ops in decode mode carry
        k/v caches + position in state; the caller threads state between
        steps).  State is donated: each step reuses the cache buffers
        in place on device."""

        def step(weights, state, inputs):
            logits, new_state, _, _ = self.run_forward(
                weights, state, inputs, training=False, rng=None
            )
            return logits, new_state

        with self.mesh:
            return jax.jit(step, donate_argnums=(1,))
