"""Loss functions.

Reference: src/runtime/loss_functions.cc (backward-only Legion task — the
reference never materializes the scalar loss, it writes logit gradients
directly, loss_functions.cc:41-150, with a per-replica scale factor).
TPU-first: the loss IS a scalar jnp expression and `jax.grad` produces
exactly those gradients; the replica scale factor falls out of the mean.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .fftype import LossType


def compute_loss(
    loss_type: LossType,
    logits: jax.Array,
    labels: jax.Array,
    from_logits: bool = True,
) -> jax.Array:
    """from_logits=False matches the reference convention: the model ends
    in a Softmax op and the loss consumes probabilities
    (loss_functions.cu's grad = prob - onehot)."""
    if loss_type == LossType.SPARSE_CATEGORICAL_CROSSENTROPY:
        if from_logits:
            logp = jax.nn.log_softmax(logits, axis=-1)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-12, 1.0))
        # labels: class ids with either the same rank as logits (trailing
        # dim 1, reference label-tensor layout model.cc:3086-3124) or one
        # rank less (per-sample or per-token ids)
        if labels.ndim == logits.ndim:
            labels = labels[..., 0]
        nll = -jnp.take_along_axis(
            logp, labels.astype(jnp.int32)[..., None], axis=-1
        )
        return jnp.mean(nll)
    if loss_type == LossType.CATEGORICAL_CROSSENTROPY:
        if from_logits:
            logp = jax.nn.log_softmax(logits, axis=-1)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-12, 1.0))
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))
    if loss_type == LossType.MEAN_SQUARED_ERROR_AVG_REDUCE:
        return jnp.mean(jnp.square(logits - labels))
    if loss_type == LossType.MEAN_SQUARED_ERROR_SUM_REDUCE:
        return jnp.mean(jnp.sum(jnp.square(logits - labels), axis=tuple(range(1, logits.ndim))))
    if loss_type == LossType.IDENTITY:
        return jnp.mean(logits)
    raise ValueError(loss_type)


class Loss:
    def __init__(self, loss_type, from_logits: bool = True):
        if isinstance(loss_type, str):
            loss_type = LossType(loss_type)
        self.loss_type = loss_type
        self.from_logits = from_logits

    def __call__(self, logits, labels):
        return compute_loss(self.loss_type, logits, labels, self.from_logits)
