"""Weight initializers.

Reference: /root/reference/src/runtime/initializer.cc (349 LoC) +
initializer_kernel.cu — Glorot/Zero/Constant/Uniform/Normal run as Legion
index tasks over sharded weights with curand.  TPU-native: initializers
are pure functions of a jax PRNG key; under SPMD each device materializes
only its shard of the (already-sharded) weight via jit + out_shardings.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Initializer:
    def __call__(self, key: jax.Array, shape: Tuple[int, ...], dtype) -> jax.Array:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class ZeroInitializer(Initializer):
    def __call__(self, key, shape, dtype):
        return jnp.zeros(shape, dtype)


@dataclasses.dataclass(frozen=True)
class ConstantInitializer(Initializer):
    value: float = 0.0

    def __call__(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@dataclasses.dataclass(frozen=True)
class UniformInitializer(Initializer):
    minv: float = -0.05
    maxv: float = 0.05

    def __call__(self, key, shape, dtype):
        return jax.random.uniform(key, shape, jnp.float32, self.minv, self.maxv).astype(dtype)


@dataclasses.dataclass(frozen=True)
class NormInitializer(Initializer):
    mean: float = 0.0
    stddev: float = 0.05

    def __call__(self, key, shape, dtype):
        return (self.mean + self.stddev * jax.random.normal(key, shape, jnp.float32)).astype(dtype)


@dataclasses.dataclass(frozen=True)
class GlorotUniform(Initializer):
    """Glorot/Xavier uniform.

    fan_in/fan_out default to the reference's convention (initializer.cc):
    for a rank-N weight, fan_out = dim 0, fan_in = product of the rest —
    override via the explicit fields for conv filters.
    """

    fan_in: Optional[int] = None
    fan_out: Optional[int] = None

    def __call__(self, key, shape, dtype):
        if self.fan_in is not None and self.fan_out is not None:
            fan_in, fan_out = self.fan_in, self.fan_out
        elif len(shape) >= 2:
            receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
            fan_out = shape[0] * receptive
            fan_in = shape[1] * receptive
        else:
            fan_in = fan_out = int(np.prod(shape)) if shape else 1
        scale = float(np.sqrt(6.0 / max(1, fan_in + fan_out)))
        return jax.random.uniform(key, shape, jnp.float32, -scale, scale).astype(dtype)


class ArrayInitializer(Initializer):
    """Initialize from a fixed host array — used by frontends importing
    explicit weights (e.g. torch functional F.linear/F.conv2d)."""

    def __init__(self, array):
        self.array = np.asarray(array)

    def __call__(self, key, shape, dtype):
        if tuple(self.array.shape) != tuple(shape):
            raise ValueError(
                f"ArrayInitializer shape {self.array.shape} != weight "
                f"shape {tuple(shape)}"
            )
        return jnp.asarray(self.array, dtype)


DEFAULT_WEIGHT_INIT = GlorotUniform()
DEFAULT_BIAS_INIT = ZeroInitializer()
