"""Dynamic recompilation (reference RecompileState, recompile.h:26-42,
recompile_state.cc, FFModel::recompile_on_condition model.cc:2422-2427).

The reference's only dynamic-adaptation mechanism: a user trigger
function inspects runtime signals (the MoE Cache op's staleness score,
examples/cpp/mixture_of_experts/moe.cc:65-98) and an alter function
mutates the model, after which training continues.  TPU-native: "alter"
usually swaps the parallelization Strategy or model hyperparams and
calls `FFModel.recompile()`, which re-runs compile while carrying the
trained weights and optimizer state over (matched by op/weight name and
shape) — XLA's compilation cache makes repeat strategies cheap.
"""
from __future__ import annotations

from typing import Callable, Optional


class RecompileState:
    """Holds trigger/alter hooks and a recompilation counter."""

    def __init__(
        self,
        trigger_func: Callable[["object"], bool],
        alter_func: Callable[["object"], None],
        ff,
    ):
        self.trigger_func = trigger_func
        self.alter_func = alter_func
        self.ff = ff
        self.recompilations = 0

    def trigger(self) -> bool:
        return bool(self.trigger_func(self.ff))

    def alter(self) -> None:
        self.alter_func(self.ff)
        self.recompilations += 1


def recompile_on_condition(ff, r: RecompileState) -> bool:
    """Fire alter() when trigger() holds (model.cc:2422-2427).
    Returns True when a recompilation happened."""
    if r.trigger():
        r.alter()
        return True
    return False
