"""StrategyStore: content-addressed persistence for searched strategies.

Layout (one directory per key digest, under <root>/strategies/):

    <root>/strategies/<digest>/manifest.json   # key fields + provenance
    <root>/strategies/<digest>/strategy.json   # Strategy.to_json body
    <root>/xla_cache/                          # JAX persistent compile cache

Write discipline is checkpoint.py's verify-then-publish: serialize into
a process-unique tmp dir, fsync, re-read and re-parse against the
manifest digest, then one atomic os.replace into the final name — a
mid-write kill leaves only an ignorable tmp dir, never a torn entry.
Reads tolerate corruption the same way restores do: any unreadable /
digest-mismatched entry counts as a miss (and is quarantined so the
follow-up search's publish repairs it) instead of crashing the caller.

The store is safe to share between processes on one filesystem:
publishes are atomic renames, lookups never see partial writes, and a
concurrent double-publish of the same key resolves to
first-write-wins — EXCEPT that a publish carrying a strictly better
`searched_cost` replaces the incumbent (the best-cost upgrade policy:
a longer-budget search or a replica's degraded-mesh re-search improves
the shared entry).  Metrics (store/hits, store/misses,
store/publishes, store/best_cost_upgrades, store/lookup_ms, ...) flow
through an optional obs.metrics registry into run_telemetry.jsonl.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..checkpoint import _fsync_dir, _write_json_fsync
from ..logger import store_logger
from ..strategy import Strategy
from .key import StoreKey, strategy_sha256

MANIFEST_VERSION = 1

#: gc() only sweeps .tmp-* staging dirs older than this — a young tmp
#: may be a LIVE concurrent publisher mid-write on the shared root
STALE_TMP_AGE_S = 3600.0


class StoreVerifyError(RuntimeError):
    """A publish failed its write-time re-read verification."""


def _json_safe(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): _json_safe(v) for k, v in obj.items()}
        return str(obj)


class StrategyStore:
    """Durable strategy artifacts keyed by StoreKey digests."""

    def __init__(self, root: str, registry=None):
        self.root = os.path.abspath(root)
        self.registry = registry
        os.makedirs(self.strategies_dir, exist_ok=True)

    @property
    def strategies_dir(self) -> str:
        return os.path.join(self.root, "strategies")

    @property
    def compilation_cache_dir(self) -> str:
        """Where --compilation-cache auto points XLA's persistent cache
        (the compiled step function's half of instant cold start)."""
        return os.path.join(self.root, "xla_cache")

    def _entry_dir(self, digest: str) -> str:
        return os.path.join(self.strategies_dir, digest)

    # -- metrics --------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        if self.registry is not None:
            self.registry.counter(f"store/{name}").inc(n)

    def _observe_ms(self, name: str, dt_s: float) -> None:
        if self.registry is not None:
            self.registry.histogram(f"store/{name}").observe(dt_s * 1e3)

    # -- lookup ---------------------------------------------------------
    def lookup(self, key: StoreKey) -> Optional[Strategy]:
        """Strategy for `key`, or None.  A hit carries the manifest's
        provenance as strategy.search_stats with store_hit=True — the
        compile path surfaces it exactly like a fresh search's stats.
        Corrupt entries are quarantined (removed) so the caller's
        post-search publish can repair them."""
        t0 = time.perf_counter()
        digest = key.digest
        d = self._entry_dir(digest)
        try:
            with open(os.path.join(d, "manifest.json")) as f:
                manifest = json.load(f)
            if manifest.get("manifest_version") != MANIFEST_VERSION:
                # a newer (or foreign) schema: valid for ITS readers —
                # miss without quarantining, never delete on a maybe
                store_logger.info(
                    "store entry %s has manifest_version %r (this "
                    "reader speaks %d): treating as a miss",
                    digest[:16], manifest.get("manifest_version"),
                    MANIFEST_VERSION,
                )
                self._count("misses")
                self._observe_ms("lookup_ms", time.perf_counter() - t0)
                return None
            if manifest.get("key_digest") != digest:
                raise StoreVerifyError(
                    f"manifest key_digest {manifest.get('key_digest')!r} "
                    f"!= directory digest {digest!r}"
                )
            with open(os.path.join(d, "strategy.json")) as f:
                text = f.read()
            if strategy_sha256(text) != manifest.get("strategy_sha256"):
                raise StoreVerifyError("strategy.json digest mismatch")
            strategy = Strategy.from_json(text)
        except FileNotFoundError:
            if not os.path.isdir(d):  # clean miss: no entry at all
                self._count("misses")
                self._observe_ms("lookup_ms", time.perf_counter() - t0)
                return None
            # entry dir exists but a file is gone: a half-entry would
            # block the publish (first-write-wins) forever — quarantine
            # it like any other corruption so the re-search repairs it
            store_logger.info(
                "store entry %s is missing files: quarantined, "
                "treating as a miss", digest[:16],
            )
            shutil.rmtree(d, ignore_errors=True)
            self._count("misses")
            self._count("corrupt_entries")
            self._observe_ms("lookup_ms", time.perf_counter() - t0)
            return None
        except OSError as e:
            # transient I/O (NFS ESTALE, EIO, a permissions blip): the
            # entry may be perfectly valid for every other reader —
            # miss WITHOUT quarantining, never delete on a maybe
            store_logger.info(
                "store entry %s unreadable (%s: %s): treating as a "
                "miss", digest[:16], type(e).__name__, e,
            )
            self._count("misses")
            self._observe_ms("lookup_ms", time.perf_counter() - t0)
            return None
        except Exception as e:
            # genuine corruption (torn write survivor, bit rot, digest
            # mismatch, a foreign/older schema): quarantine so the
            # follow-up search's publish repairs the key — never the
            # caller's problem either way
            store_logger.info(
                "corrupt store entry %s (%s: %s): quarantined, "
                "treating as a miss", digest[:16], type(e).__name__, e,
            )
            shutil.rmtree(d, ignore_errors=True)
            self._count("misses")
            self._count("corrupt_entries")
            self._observe_ms("lookup_ms", time.perf_counter() - t0)
            return None
        stats = dict(manifest.get("search_stats") or {})
        stats["store_hit"] = True
        stats["store_key"] = digest
        strategy.search_stats = stats
        if manifest.get("searched_cost") is not None:
            strategy.search_cost = manifest["searched_cost"]
        self._count("hits")
        self._observe_ms("lookup_ms", time.perf_counter() - t0)
        return strategy

    # -- publish --------------------------------------------------------
    def publish(
        self,
        key: StoreKey,
        strategy: Strategy,
        *,
        searched_cost: Optional[float] = None,
        search_stats: Optional[Dict] = None,
        created_at: Optional[float] = None,
        overwrite: bool = False,
    ) -> bool:
        """Write-verify-rename one entry; returns True when the entry
        was (re)written, False when an existing entry was kept
        (first-write-wins) or the write failed survivably.  created_at
        is caller-supplied provenance (seconds since epoch).

        Best-cost upgrade policy: a publish carrying a STRICTLY better
        (lower) `searched_cost` than the existing entry's replaces it —
        so a longer-budget search, or a serving replica's degraded-mesh
        re-search that beat the fleet entry, improves the shared store
        instead of being dropped on first-write-wins.  Equal or worse
        costs (and cost-less publishes) still lose to the incumbent."""
        digest = key.digest
        final = self._entry_dir(digest)
        upgrading = False
        if os.path.isdir(final) and not overwrite:
            if not self._upgrades_cost(final, searched_cost):
                return False
            overwrite = upgrading = True
            store_logger.info(
                "store entry %s: replacing with strictly better "
                "searched_cost %.6g", digest[:16], searched_cost,
            )
        text = strategy.to_json()
        manifest = {
            "manifest_version": MANIFEST_VERSION,
            "key_digest": digest,
            "key": key.manifest_fields(),
            "strategy_sha256": strategy_sha256(text),
            "searched_cost": (
                None if searched_cost is None else float(searched_cost)
            ),
            "search_stats": _json_safe(search_stats or {}),
            "created_at": (
                time.time() if created_at is None else float(created_at)
            ),
        }
        tmp = os.path.join(
            self.strategies_dir,
            f".tmp-{digest[:16]}-{os.getpid()}-{threading.get_ident()}",
        )
        try:
            os.makedirs(tmp)
            with open(os.path.join(tmp, "strategy.json"), "w") as f:
                f.write(text)
                f.flush()
                os.fsync(f.fileno())
            _write_json_fsync(os.path.join(tmp, "manifest.json"), manifest)
            self._verify_dir(tmp, digest)
            if (upgrading and os.path.isdir(final)
                    and not self._upgrades_cost(final, searched_cost)):
                # the incumbent changed while we serialized (a
                # concurrent publisher landed something at least as
                # good): dropping our copy keeps the best entry.  The
                # remaining replace-after-check window is microseconds
                # — an accepted cost of the lock-free shared store.
                shutil.rmtree(tmp, ignore_errors=True)
                return False
            if os.path.isdir(final):  # overwrite=True repair path
                shutil.rmtree(final)
            os.replace(tmp, final)
            _fsync_dir(self.strategies_dir)
        except FileExistsError:
            # a concurrent publisher beat us into the tmp or final name:
            # their verified entry serves the key; ours is redundant
            shutil.rmtree(tmp, ignore_errors=True)
            return False
        except (OSError, StoreVerifyError) as e:
            shutil.rmtree(tmp, ignore_errors=True)
            if isinstance(e, OSError) and os.path.isdir(final):
                # on Linux the concurrent-publish race surfaces as
                # ENOTEMPTY from os.replace, not FileExistsError: the
                # other writer's verified entry now serves the key —
                # benign first-write-wins, not a store failure
                return False
            self._count("publish_failures")
            store_logger.info(
                "store publish failed for %s (%s: %s); search result "
                "still used, entry not persisted",
                digest[:16], type(e).__name__, e,
            )
            return False
        self._count("publishes")
        if upgrading:  # counted only once the replacement actually landed
            self._count("best_cost_upgrades")
        return True

    def _upgrades_cost(self, entry_dir: str,
                       searched_cost: Optional[float]) -> bool:
        """True when `searched_cost` strictly beats the published
        entry's.  Unreadable/partial incumbents do NOT upgrade-replace
        here — lookup() owns quarantine policy (a transient I/O blip
        must not let a publish clobber a healthy entry)."""
        if searched_cost is None:
            return False
        try:
            with open(os.path.join(entry_dir, "manifest.json")) as f:
                existing = json.load(f).get("searched_cost")
        except (OSError, ValueError):
            return False
        return existing is not None and float(searched_cost) < float(existing)

    @staticmethod
    def _verify_dir(path: str, digest: str) -> None:
        """Re-read a staged entry and check manifest/strategy coherence
        (the checkpoint.py write-time verification discipline)."""
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        if manifest.get("key_digest") != digest:
            raise StoreVerifyError("staged manifest key_digest mismatch")
        with open(os.path.join(path, "strategy.json")) as f:
            text = f.read()
        if strategy_sha256(text) != manifest.get("strategy_sha256"):
            raise StoreVerifyError("staged strategy.json digest mismatch")
        Strategy.from_json(text)  # must parse back

    # -- maintenance ----------------------------------------------------
    def entries(self) -> List[Tuple[str, Dict]]:
        """(digest, manifest) pairs, oldest created_at first; unreadable
        manifests are skipped (lookup() quarantines them on access)."""
        out = []
        try:
            names = os.listdir(self.strategies_dir)
        except OSError:
            return []
        for name in names:
            if name.startswith(".tmp-"):
                continue
            try:
                with open(os.path.join(self.strategies_dir, name,
                                       "manifest.json")) as f:
                    out.append((name, json.load(f)))
            except (OSError, ValueError):
                continue
        out.sort(key=lambda e: e[1].get("created_at", 0.0))
        return out

    def gc(self, keep_last: int) -> int:
        """Keep the `keep_last` newest entries by created_at, drop the
        rest (plus any stale tmp dirs); returns the number removed.
        Keep/gc policy rationale: docs/STORE.md."""
        if keep_last < 0:
            raise ValueError(f"keep_last must be >= 0, got {keep_last}")
        removed = 0
        entries = self.entries()
        drop = entries[: max(0, len(entries) - keep_last)]
        for digest, _m in drop:
            shutil.rmtree(os.path.join(self.strategies_dir, digest),
                          ignore_errors=True)
            removed += 1
        try:
            now = time.time()
            for name in os.listdir(self.strategies_dir):
                if not name.startswith(".tmp-"):
                    continue
                p = os.path.join(self.strategies_dir, name)
                try:
                    age = now - os.path.getmtime(p)
                except OSError:
                    continue  # the publisher just renamed it away
                if age > STALE_TMP_AGE_S:
                    # old enough that its writer is dead, not mid-write
                    shutil.rmtree(p, ignore_errors=True)
        except OSError:
            pass
        if removed:
            self._count("gc_removed", removed)
        return removed

    def import_strategy(self, key: StoreKey, path: str, *,
                        created_at: Optional[float] = None,
                        overwrite: bool = False, **meta) -> bool:
        """Promote an on-disk Strategy JSON (examples/strategies/*.json)
        into a store entry — Strategy.load stays the compatibility
        surface; the store gains a verified, key-addressed copy."""
        strategy = Strategy.load(path)
        stats = dict(meta.pop("search_stats", {}) or {})
        stats.setdefault("imported_from", os.path.basename(path))
        return self.publish(
            key, strategy, search_stats=stats, created_at=created_at,
            overwrite=overwrite, **meta,
        )
