"""StrategyStore: content-addressed persistence for searched strategies.

Layout (one directory per key digest, under <root>/strategies/):

    <root>/strategies/<digest>/manifest.json   # key fields + provenance
    <root>/strategies/<digest>/strategy.json   # Strategy.to_json body
    <root>/xla_cache/                          # JAX persistent compile cache

Write discipline is checkpoint.py's verify-then-publish: serialize into
a process-unique tmp dir, fsync, re-read and re-parse against the
manifest digest, then one atomic os.replace into the final name — a
mid-write kill leaves only an ignorable tmp dir, never a torn entry.
Reads tolerate corruption the same way restores do: any unreadable /
digest-mismatched entry counts as a miss (and is quarantined so the
follow-up search's publish repairs it) instead of crashing the caller.

The store is safe to share between processes on one filesystem:
publishes are atomic renames, lookups never see partial writes, and a
concurrent double-publish of the same key resolves to
first-write-wins — EXCEPT that a publish carrying a strictly better
`searched_cost` replaces the incumbent (the best-cost upgrade policy:
a longer-budget search or a replica's degraded-mesh re-search improves
the shared entry).  Metrics (store/hits, store/misses,
store/publishes, store/best_cost_upgrades, store/lookup_ms, ...) flow
through an optional obs.metrics registry into run_telemetry.jsonl.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..checkpoint import _fsync_dir, _write_json_fsync
from ..logger import store_logger
from ..strategy import Strategy
from .blobstore import (
    BlobNotFound,
    BlobStore,
    BlobStoreError,
    rmtree_blob_prefix,
)
from .key import StoreKey, strategy_sha256

MANIFEST_VERSION = 1

#: gc() only sweeps .tmp-* staging dirs older than this — a young tmp
#: may be a LIVE concurrent publisher mid-write on the shared root
STALE_TMP_AGE_S = 3600.0


class StoreVerifyError(RuntimeError):
    """A publish failed its write-time re-read verification."""


def _json_safe(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): _json_safe(v) for k, v in obj.items()}
        return str(obj)


class RemoteStrategyMirror:
    """Fleet mirror of the strategy store on a BlobStore (docs/STORE.md
    "Fleet mirror").

    Remote layout mirrors the local one: `strategies/<digest>/
    {manifest.json,strategy.json}`.  Reads verify the same invariants
    the local store does (manifest version, key digest, strategy
    sha256) and treat anything torn as a miss — a sha-mismatched pair
    is quarantined so the next publish repairs it.  Writes put
    strategy.json first, manifest.json last, and honor the best-cost
    upgrade policy against the REMOTE incumbent (strictly lower
    searched_cost replaces; everything else is first-write-wins).  The
    pair-write is lock-free like the local store: a concurrent push of
    the same key can tear the pair, which the next fetch detects and
    the next push repairs."""

    def __init__(self, blob: BlobStore, prefix: str = "strategies/"):
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        self.blob = blob
        self.prefix = prefix

    def _entry_prefix(self, digest: str) -> str:
        return f"{self.prefix}{digest}/"

    def fetch(self, digest: str):
        """(manifest dict, strategy.json text) for a verified remote
        entry, or None — unreadable/foreign-schema entries miss without
        deletion, genuinely torn pairs are quarantined."""
        prefix = self._entry_prefix(digest)
        try:
            manifest = json.loads(self.blob.get(prefix + "manifest.json"))
        except BlobNotFound:
            return None
        except (BlobStoreError, ValueError) as e:
            store_logger.info(
                "remote store entry %s unreadable (%s: %s): treating as "
                "a miss", digest[:16], type(e).__name__, e,
            )
            return None
        if manifest.get("manifest_version") != MANIFEST_VERSION:
            return None  # a newer reader's entry: never delete on a maybe
        try:
            text = self.blob.get(prefix + "strategy.json").decode("utf-8")
        except BlobNotFound:
            # writes land strategy.json BEFORE manifest.json, so a
            # manifest without its strategy is never mid-publish — it's
            # a quarantine that raced a concurrent push.  Left in place,
            # push()'s first-write-wins would honor the orphan manifest
            # forever; delete it so the next publish repairs the entry.
            store_logger.info(
                "remote store entry %s has a manifest but no strategy: "
                "quarantined, treating as a miss", digest[:16],
            )
            try:
                rmtree_blob_prefix(self.blob, prefix)
            except BlobStoreError:
                pass
            return None
        except (BlobStoreError, UnicodeDecodeError) as e:
            store_logger.info(
                "remote store entry %s unreadable (%s: %s): treating as "
                "a miss", digest[:16], type(e).__name__, e,
            )
            return None
        if (manifest.get("key_digest") != digest
                or strategy_sha256(text) != manifest.get("strategy_sha256")):
            store_logger.info(
                "remote store entry %s torn/mismatched: quarantined, "
                "treating as a miss", digest[:16],
            )
            try:
                rmtree_blob_prefix(self.blob, prefix)
            except BlobStoreError:
                pass
            return None
        return manifest, text

    def push(self, digest: str, manifest: Dict, text: str) -> bool:
        """Publish-through one locally-verified entry; returns True when
        the remote entry was (re)written.  First-write-wins against the
        remote incumbent, except a strictly better searched_cost."""
        prefix = self._entry_prefix(digest)
        existing = None
        try:
            existing = json.loads(self.blob.get(prefix + "manifest.json"))
        except BlobNotFound:
            pass
        except (BlobStoreError, ValueError):
            existing = None  # unreadable incumbent: repair it
        if existing is not None:
            new_cost = manifest.get("searched_cost")
            old_cost = existing.get("searched_cost")
            if not (new_cost is not None and old_cost is not None
                    and float(new_cost) < float(old_cost)):
                return False
        self.blob.put(prefix + "strategy.json", text.encode("utf-8"))
        self.blob.put(prefix + "manifest.json",
                      json.dumps(manifest).encode("utf-8"))
        return True


class StrategyStore:
    """Durable strategy artifacts keyed by StoreKey digests.

    `remote` (a RemoteStrategyMirror) adds the fleet tier: lookups
    consult local -> remote (a remote hit is verified, then
    materialized as a normal local entry so the NEXT lookup is local),
    and successful publishes mirror through, so a brand-new host warms
    from the fleet store before its first compile."""

    def __init__(self, root: str, registry=None, remote=None):
        self.root = os.path.abspath(root)
        self.registry = registry
        self.remote = remote
        os.makedirs(self.strategies_dir, exist_ok=True)

    @property
    def strategies_dir(self) -> str:
        return os.path.join(self.root, "strategies")

    @property
    def compilation_cache_dir(self) -> str:
        """Where --compilation-cache auto points XLA's persistent cache
        (the compiled step function's half of instant cold start)."""
        return os.path.join(self.root, "xla_cache")

    def _entry_dir(self, digest: str) -> str:
        return os.path.join(self.strategies_dir, digest)

    # -- metrics --------------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        if self.registry is not None:
            self.registry.counter(f"store/{name}").inc(n)

    def _observe_ms(self, name: str, dt_s: float) -> None:
        if self.registry is not None:
            self.registry.histogram(f"store/{name}").observe(dt_s * 1e3)

    # -- lookup ---------------------------------------------------------
    def lookup(self, key: StoreKey) -> Optional[Strategy]:
        """Strategy for `key`, or None — consulting local THEN the
        fleet mirror.  A hit carries the manifest's provenance as
        strategy.search_stats with store_hit=True (remote hits add
        store_remote_hit=True); a verified remote hit is materialized
        as a local entry so later lookups never leave the host.
        Corrupt local entries are quarantined (removed) so the
        caller's post-search publish can repair them."""
        strategy = self._lookup_local(key)
        if strategy is not None or self.remote is None:
            return strategy
        return self._lookup_remote(key)

    def _lookup_remote(self, key: StoreKey) -> Optional[Strategy]:
        digest = key.digest
        try:
            fetched = self.remote.fetch(digest)
        except Exception as e:  # noqa: BLE001 — mirror failures never crash
            self._count("remote_errors")
            store_logger.info(
                "fleet mirror lookup failed for %s (%s: %s)",
                digest[:16], type(e).__name__, e,
            )
            return None
        if fetched is None:
            return None
        manifest, text = fetched
        try:
            strategy = Strategy.from_json(text)
        except Exception as e:  # noqa: BLE001 — verified sha, odd schema
            self._count("remote_errors")
            store_logger.info(
                "fleet mirror entry %s unparseable (%s)", digest[:16], e,
            )
            return None
        self._count("remote_hits")
        store_logger.info(
            "fleet mirror hit %s: strategy materialized locally",
            digest[:16],
        )
        # materialize through the normal verify-then-publish write so
        # the next lookup is local; mirror=False — it came FROM remote
        self.publish(
            key, strategy,
            searched_cost=manifest.get("searched_cost"),
            search_stats=manifest.get("search_stats"),
            created_at=manifest.get("created_at"),
            overwrite=True, mirror=False,
        )
        stats = dict(manifest.get("search_stats") or {})
        stats["store_hit"] = True
        stats["store_remote_hit"] = True
        stats["store_key"] = digest
        strategy.search_stats = stats
        if manifest.get("searched_cost") is not None:
            strategy.search_cost = manifest["searched_cost"]
        return strategy

    def _lookup_local(self, key: StoreKey) -> Optional[Strategy]:
        t0 = time.perf_counter()
        digest = key.digest
        d = self._entry_dir(digest)
        try:
            with open(os.path.join(d, "manifest.json")) as f:
                manifest = json.load(f)
            if manifest.get("manifest_version") != MANIFEST_VERSION:
                # a newer (or foreign) schema: valid for ITS readers —
                # miss without quarantining, never delete on a maybe
                store_logger.info(
                    "store entry %s has manifest_version %r (this "
                    "reader speaks %d): treating as a miss",
                    digest[:16], manifest.get("manifest_version"),
                    MANIFEST_VERSION,
                )
                self._count("misses")
                self._observe_ms("lookup_ms", time.perf_counter() - t0)
                return None
            if manifest.get("key_digest") != digest:
                raise StoreVerifyError(
                    f"manifest key_digest {manifest.get('key_digest')!r} "
                    f"!= directory digest {digest!r}"
                )
            with open(os.path.join(d, "strategy.json")) as f:
                text = f.read()
            if strategy_sha256(text) != manifest.get("strategy_sha256"):
                raise StoreVerifyError("strategy.json digest mismatch")
            strategy = Strategy.from_json(text)
        except FileNotFoundError:
            if not os.path.isdir(d):  # clean miss: no entry at all
                self._count("misses")
                self._observe_ms("lookup_ms", time.perf_counter() - t0)
                return None
            # entry dir exists but a file is gone: a half-entry would
            # block the publish (first-write-wins) forever — quarantine
            # it like any other corruption so the re-search repairs it
            store_logger.info(
                "store entry %s is missing files: quarantined, "
                "treating as a miss", digest[:16],
            )
            shutil.rmtree(d, ignore_errors=True)
            self._count("misses")
            self._count("corrupt_entries")
            self._observe_ms("lookup_ms", time.perf_counter() - t0)
            return None
        except OSError as e:
            # transient I/O (NFS ESTALE, EIO, a permissions blip): the
            # entry may be perfectly valid for every other reader —
            # miss WITHOUT quarantining, never delete on a maybe
            store_logger.info(
                "store entry %s unreadable (%s: %s): treating as a "
                "miss", digest[:16], type(e).__name__, e,
            )
            self._count("misses")
            self._observe_ms("lookup_ms", time.perf_counter() - t0)
            return None
        except Exception as e:
            # genuine corruption (torn write survivor, bit rot, digest
            # mismatch, a foreign/older schema): quarantine so the
            # follow-up search's publish repairs the key — never the
            # caller's problem either way
            store_logger.info(
                "corrupt store entry %s (%s: %s): quarantined, "
                "treating as a miss", digest[:16], type(e).__name__, e,
            )
            shutil.rmtree(d, ignore_errors=True)
            self._count("misses")
            self._count("corrupt_entries")
            self._observe_ms("lookup_ms", time.perf_counter() - t0)
            return None
        stats = dict(manifest.get("search_stats") or {})
        stats["store_hit"] = True
        stats["store_key"] = digest
        strategy.search_stats = stats
        if manifest.get("searched_cost") is not None:
            strategy.search_cost = manifest["searched_cost"]
        self._count("hits")
        self._observe_ms("lookup_ms", time.perf_counter() - t0)
        return strategy

    # -- publish --------------------------------------------------------
    def publish(
        self,
        key: StoreKey,
        strategy: Strategy,
        *,
        searched_cost: Optional[float] = None,
        search_stats: Optional[Dict] = None,
        created_at: Optional[float] = None,
        overwrite: bool = False,
        mirror: bool = True,
    ) -> bool:
        """Write-verify-rename one entry; returns True when the entry
        was (re)written, False when an existing entry was kept
        (first-write-wins) or the write failed survivably.  created_at
        is caller-supplied provenance (seconds since epoch).  A
        successful write publishes THROUGH to the fleet mirror when one
        is configured (mirror=False marks entries that came from it).

        Best-cost upgrade policy: a publish carrying a STRICTLY better
        (lower) `searched_cost` than the existing entry's replaces it —
        so a longer-budget search, or a serving replica's degraded-mesh
        re-search that beat the fleet entry, improves the shared store
        instead of being dropped on first-write-wins.  Equal or worse
        costs (and cost-less publishes) still lose to the incumbent."""
        digest = key.digest
        final = self._entry_dir(digest)
        upgrading = False
        if os.path.isdir(final) and not overwrite:
            if not self._upgrades_cost(final, searched_cost):
                return False
            overwrite = upgrading = True
            store_logger.info(
                "store entry %s: replacing with strictly better "
                "searched_cost %.6g", digest[:16], searched_cost,
            )
        text = strategy.to_json()
        manifest = {
            "manifest_version": MANIFEST_VERSION,
            "key_digest": digest,
            "key": key.manifest_fields(),
            "strategy_sha256": strategy_sha256(text),
            "searched_cost": (
                None if searched_cost is None else float(searched_cost)
            ),
            "search_stats": _json_safe(search_stats or {}),
            "created_at": (
                time.time() if created_at is None else float(created_at)
            ),
        }
        tmp = os.path.join(
            self.strategies_dir,
            f".tmp-{digest[:16]}-{os.getpid()}-{threading.get_ident()}",
        )
        try:
            os.makedirs(tmp)
            with open(os.path.join(tmp, "strategy.json"), "w") as f:
                f.write(text)
                f.flush()
                os.fsync(f.fileno())
            _write_json_fsync(os.path.join(tmp, "manifest.json"), manifest)
            self._verify_dir(tmp, digest)
            if (upgrading and os.path.isdir(final)
                    and not self._upgrades_cost(final, searched_cost)):
                # the incumbent changed while we serialized (a
                # concurrent publisher landed something at least as
                # good): dropping our copy keeps the best entry.  The
                # remaining replace-after-check window is microseconds
                # — an accepted cost of the lock-free shared store.
                shutil.rmtree(tmp, ignore_errors=True)
                return False
            if os.path.isdir(final):  # overwrite=True repair path
                shutil.rmtree(final)
            os.replace(tmp, final)
            _fsync_dir(self.strategies_dir)
        except FileExistsError:
            # a concurrent publisher beat us into the tmp or final name:
            # their verified entry serves the key; ours is redundant
            shutil.rmtree(tmp, ignore_errors=True)
            return False
        except (OSError, StoreVerifyError) as e:
            shutil.rmtree(tmp, ignore_errors=True)
            if isinstance(e, OSError) and os.path.isdir(final):
                # on Linux the concurrent-publish race surfaces as
                # ENOTEMPTY from os.replace, not FileExistsError: the
                # other writer's verified entry now serves the key —
                # benign first-write-wins, not a store failure
                return False
            self._count("publish_failures")
            store_logger.info(
                "store publish failed for %s (%s: %s); search result "
                "still used, entry not persisted",
                digest[:16], type(e).__name__, e,
            )
            return False
        self._count("publishes")
        if upgrading:  # counted only once the replacement actually landed
            self._count("best_cost_upgrades")
        if mirror and self.remote is not None:
            try:
                if self.remote.push(digest, manifest, text):
                    self._count("remote_publishes")
            except Exception as e:  # noqa: BLE001 — the mirror is an
                # accelerator for OTHER hosts; its failure never
                # un-publishes the verified local entry
                self._count("remote_errors")
                store_logger.info(
                    "fleet mirror publish failed for %s (%s: %s); local "
                    "entry intact", digest[:16], type(e).__name__, e,
                )
        return True

    def _upgrades_cost(self, entry_dir: str,
                       searched_cost: Optional[float]) -> bool:
        """True when `searched_cost` strictly beats the published
        entry's.  Unreadable/partial incumbents do NOT upgrade-replace
        here — lookup() owns quarantine policy (a transient I/O blip
        must not let a publish clobber a healthy entry)."""
        if searched_cost is None:
            return False
        try:
            with open(os.path.join(entry_dir, "manifest.json")) as f:
                existing = json.load(f).get("searched_cost")
        except (OSError, ValueError):
            return False
        return existing is not None and float(searched_cost) < float(existing)

    @staticmethod
    def _verify_dir(path: str, digest: str) -> None:
        """Re-read a staged entry and check manifest/strategy coherence
        (the checkpoint.py write-time verification discipline)."""
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        if manifest.get("key_digest") != digest:
            raise StoreVerifyError("staged manifest key_digest mismatch")
        with open(os.path.join(path, "strategy.json")) as f:
            text = f.read()
        if strategy_sha256(text) != manifest.get("strategy_sha256"):
            raise StoreVerifyError("staged strategy.json digest mismatch")
        Strategy.from_json(text)  # must parse back

    # -- maintenance ----------------------------------------------------
    def entries(self) -> List[Tuple[str, Dict]]:
        """(digest, manifest) pairs, oldest created_at first; unreadable
        manifests are skipped (lookup() quarantines them on access)."""
        out = []
        try:
            names = os.listdir(self.strategies_dir)
        except OSError:
            return []
        for name in names:
            if name.startswith(".tmp-"):
                continue
            try:
                with open(os.path.join(self.strategies_dir, name,
                                       "manifest.json")) as f:
                    out.append((name, json.load(f)))
            except (OSError, ValueError):
                continue
        out.sort(key=lambda e: e[1].get("created_at", 0.0))
        return out

    def gc(self, keep_last: int) -> int:
        """Keep the `keep_last` newest entries by created_at, drop the
        rest (plus any stale tmp dirs); returns the number removed.
        Keep/gc policy rationale: docs/STORE.md."""
        if keep_last < 0:
            raise ValueError(f"keep_last must be >= 0, got {keep_last}")
        removed = 0
        entries = self.entries()
        drop = entries[: max(0, len(entries) - keep_last)]
        for digest, _m in drop:
            shutil.rmtree(os.path.join(self.strategies_dir, digest),
                          ignore_errors=True)
            removed += 1
        try:
            now = time.time()
            for name in os.listdir(self.strategies_dir):
                if not name.startswith(".tmp-"):
                    continue
                p = os.path.join(self.strategies_dir, name)
                try:
                    age = now - os.path.getmtime(p)
                except OSError:
                    continue  # the publisher just renamed it away
                if age > STALE_TMP_AGE_S:
                    # old enough that its writer is dead, not mid-write
                    shutil.rmtree(p, ignore_errors=True)
        except OSError:
            pass
        if removed:
            self._count("gc_removed", removed)
        return removed

    def import_strategy(self, key: StoreKey, path: str, *,
                        created_at: Optional[float] = None,
                        overwrite: bool = False, **meta) -> bool:
        """Promote an on-disk Strategy JSON (examples/strategies/*.json)
        into a store entry — Strategy.load stays the compatibility
        surface; the store gains a verified, key-addressed copy."""
        strategy = Strategy.load(path)
        stats = dict(meta.pop("search_stats", {}) or {})
        stats.setdefault("imported_from", os.path.basename(path))
        return self.publish(
            key, strategy, search_stats=stats, created_at=created_at,
            overwrite=overwrite, **meta,
        )
