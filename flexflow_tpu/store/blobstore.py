"""Minimal object-store abstraction for the durable offload tier.

Everything durable in this repo used to live on one host's disk — the
verified local checkpoints (checkpoint.py) and the strategy store
(store/store.py).  A full host loss destroyed both.  This module is the
second durability tier's substrate: a tiny blob-store interface with
exactly the operations the offload protocols need (put/get/list/delete
plus a *generation-conditional* put for crash-safe pointer updates, the
GCS `ifGenerationMatch` primitive), a filesystem backend so tests and
bench run anywhere, and a seeded fault-injecting wrapper so every
upload failure mode is exercisable on a laptop.

Backends:

  * `LocalBlobStore` — objects are files under a root directory,
    written tmp+fsync+rename so a reader never sees a torn object;
    per-object generation counters back the conditional put.  This is
    the hermetic stand-in for GCS/S3 (an NFS/Filestore mount used this
    way IS a production deployment for single-cluster fleets).
  * `FaultyBlobStore` — wraps any backend and injects the upload fault
    matrix from a seeded `resilience.faults.FaultPlan`: transient
    errors, partial/truncated uploads, latency spikes, and
    unavailability windows (docs/RESILIENCE.md "Durable offload").
  * `blobstore_from_uri` — `file:///path` or a bare path map to
    `LocalBlobStore`; `gs://`/`s3://` name the production backends this
    interface is shaped for and raise a clear error until their SDKs
    are provisioned (no import-time dependency is taken).

Key discipline: keys are `/`-separated UTF-8 paths (`ckpt/step_00000004
/state.npz`); no leading slash, no `..` segments.  All operations are
whole-object and atomic per key; cross-key transactions are built from
the conditional put (see resilience/offload.py's REMOTE_LATEST
protocol).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

_log = logging.getLogger("flexflow_tpu.blobstore")


class BlobStoreError(RuntimeError):
    """Base of blob-store failures (network, backend, precondition)."""


class BlobNotFound(BlobStoreError, KeyError):
    """get/delete of a key that does not exist."""


class BlobUnavailableError(BlobStoreError):
    """Transient backend failure: the operation may succeed on retry
    (the 429/503/connection-reset class).  Callers retry under a
    jittered-backoff budget and degrade gracefully past it."""


class BlobPreconditionFailed(BlobStoreError):
    """A conditional put's generation precondition did not hold —
    another writer updated (or created) the object first."""


@dataclasses.dataclass
class BlobInfo:
    key: str
    size: int
    generation: int


def _check_key(key: str) -> str:
    if not key or key.startswith("/") or key.endswith("/"):
        raise ValueError(f"blob key must be a relative path, got {key!r}")
    parts = key.split("/")
    if any(p in ("", ".", "..") for p in parts):
        raise ValueError(f"blob key must not contain empty/dot segments: "
                         f"{key!r}")
    return key


class BlobStore:
    """Abstract whole-object store.  Generation semantics follow GCS:
    generation 0 means "the object does not exist", so
    `put(key, data, if_generation_match=0)` is create-if-absent and
    `put(key, data, if_generation_match=g)` replaces only the exact
    version a reader previously observed."""

    def put(self, key: str, data: bytes, *,
            if_generation_match: Optional[int] = None) -> int:
        """Write one object atomically; returns its new generation.
        Raises BlobPreconditionFailed when `if_generation_match` names
        a generation other than the current one."""
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        """Full object bytes; raises BlobNotFound."""
        raise NotImplementedError

    def stat(self, key: str) -> Optional[BlobInfo]:
        """BlobInfo for `key`, or None when absent."""
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        """Sorted keys under `prefix` (flat namespace, like GCS)."""
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        """Remove one object; returns False when it was already gone."""
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        return self.stat(key) is not None


class LocalBlobStore(BlobStore):
    """Filesystem-backed BlobStore.

    Objects live at `<root>/<key>`; per-object generation counters live
    in a parallel `<root>/.meta/<key>` tree (kept out of list()).
    Writes stage to a `.tmp-*` sibling, fsync, then `os.replace` — a
    reader never observes a torn object, mirroring real object stores'
    whole-object atomicity.  Generations are protected by an in-process
    lock; cross-process writers on one root still get atomic objects,
    but conditional-put races between *processes* are best-effort (the
    production backends this stands in for arbitrate server-side).
    """

    _META = ".meta"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()

    def _data_path(self, key: str) -> str:
        return os.path.join(self.root, *_check_key(key).split("/"))

    def _meta_path(self, key: str) -> str:
        return os.path.join(self.root, self._META, *key.split("/"))

    def _generation(self, key: str) -> int:
        try:
            with open(self._meta_path(key)) as f:
                return int(json.load(f)["generation"])
        except (OSError, ValueError, KeyError):
            # object present but meta torn/absent (foreign writer, crash
            # between data and meta): treat as generation 1 so readers
            # still see it and unconditional puts still supersede it
            return 1 if os.path.exists(self._data_path(key)) else 0

    def put(self, key: str, data: bytes, *,
            if_generation_match: Optional[int] = None) -> int:
        path = self._data_path(key)
        with self._lock:
            cur = self._generation(key)
            if if_generation_match is not None \
                    and cur != int(if_generation_match):
                raise BlobPreconditionFailed(
                    f"{key}: generation {cur} != required "
                    f"{if_generation_match}"
                )
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
                try:
                    with open(tmp, "wb") as f:
                        f.write(data)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, path)
                except OSError:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise
                gen = cur + 1
                mpath = self._meta_path(key)
                os.makedirs(os.path.dirname(mpath), exist_ok=True)
                mtmp = f"{mpath}.tmp-{os.getpid()}-{threading.get_ident()}"
                with open(mtmp, "w") as f:
                    json.dump({"generation": gen}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(mtmp, mpath)
            except OSError as e:
                # every `except BlobStoreError` handler in the durability
                # tiers must see filesystem trouble too (read-only NFS,
                # EPERM on a foreign uid's object) — same contract as get()
                raise BlobUnavailableError(f"{key}: {e}") from e
            return gen

    def get(self, key: str) -> bytes:
        try:
            with open(self._data_path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise BlobNotFound(key) from None
        except OSError as e:
            raise BlobUnavailableError(f"{key}: {e}") from e

    def stat(self, key: str) -> Optional[BlobInfo]:
        path = self._data_path(key)
        try:
            size = os.path.getsize(path)
        except OSError:
            return None
        return BlobInfo(key=key, size=size, generation=self._generation(key))

    def list(self, prefix: str = "") -> List[str]:
        out = []
        # root the walk at the prefix's directory portion: the
        # preemption barrier polls list("barrier/<run_id>/") at 20Hz
        # and must not stat every mirrored step in the tree
        base = prefix.rsplit("/", 1)[0] if "/" in prefix else ""
        start = (os.path.join(self.root, *base.split("/"))
                 if base else self.root)
        if not os.path.isdir(start):
            return out
        for dirpath, dirnames, filenames in os.walk(start):
            # the generation tree and staged writes are implementation
            # detail, never listed
            dirnames[:] = [d for d in dirnames if d != self._META]
            for name in filenames:
                if ".tmp-" in name:
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete(self, key: str) -> bool:
        with self._lock:
            existed = False
            try:
                os.unlink(self._data_path(key))
                existed = True
            except FileNotFoundError:
                pass
            except OSError as e:
                raise BlobUnavailableError(f"{key}: {e}") from e
            try:
                os.unlink(self._meta_path(key))
            except OSError:
                pass
            return existed


class FaultyBlobStore(BlobStore):
    """Fault-injecting wrapper around any BlobStore.

    Faults come from a seeded `resilience.faults.FaultPlan` whose
    object-store `FaultKind`s (BLOB_TRANSIENT / BLOB_PARTIAL_UPLOAD /
    BLOB_LATENCY / BLOB_UNAVAILABLE) target the wrapper's own operation
    counter — `Fault.step` is "fire at or after the Nth blob op", so a
    plan is deterministic regardless of training cadence.  Each fault
    fires once; BLOB_UNAVAILABLE opens a window of `payload["ops"]`
    consecutive operations (default 5) that all raise
    `BlobUnavailableError`.

    A partial upload truncates the put's bytes to `payload["fraction"]`
    (default 0.5) and lets the truncated object LAND — exactly the torn
    upload a real store can surface — so only the reader-side manifest
    verification can catch it (which is the property under test).
    """

    def __init__(self, inner: BlobStore, plan=None, *,
                 sleep: Callable[[float], None] = time.sleep):
        from ..resilience.faults import FaultPlan

        self.inner = inner
        self.plan = plan or FaultPlan()
        self.sleep = sleep
        self.ops = 0  # operations attempted so far (the fault clock)
        self._unavailable_until = -1  # op index the outage window ends at
        self.counters: Dict[str, int] = {
            "transient_errors": 0,
            "partial_uploads": 0,
            "latency_injections": 0,
            "unavailable_rejections": 0,
        }

    # -- fault clock -----------------------------------------------------
    def _tick(self, op: str, key: str) -> Optional[float]:
        """Advance the op counter and fire due faults.  Returns the
        put-truncation fraction when a partial-upload fault hit (the
        caller applies it), else None."""
        from ..resilience.faults import FaultKind

        self.ops += 1
        if self.ops <= self._unavailable_until:
            self.counters["unavailable_rejections"] += 1
            raise BlobUnavailableError(
                f"injected outage window: {op} {key} (op {self.ops})"
            )
        fraction = None
        for f in self.plan.faults:
            if f.fired or self.ops < f.step:
                continue
            if f.kind == FaultKind.BLOB_TRANSIENT:
                f.fired = True
                self.counters["transient_errors"] += 1
                raise BlobUnavailableError(
                    f"injected transient error: {op} {key} (op {self.ops})"
                )
            if f.kind == FaultKind.BLOB_UNAVAILABLE:
                f.fired = True
                window = int(f.payload.get("ops", 5))
                self._unavailable_until = self.ops + window
                self.counters["unavailable_rejections"] += 1
                raise BlobUnavailableError(
                    f"injected outage window ({window} ops): {op} {key}"
                )
            if f.kind == FaultKind.BLOB_LATENCY:
                f.fired = True
                self.counters["latency_injections"] += 1
                self.sleep(float(f.payload.get("delay_s", 0.05)))
            elif f.kind == FaultKind.BLOB_PARTIAL_UPLOAD and op == "put":
                f.fired = True
                self.counters["partial_uploads"] += 1
                fraction = float(f.payload.get("fraction", 0.5))
        return fraction

    # -- delegated ops ---------------------------------------------------
    def put(self, key: str, data: bytes, *,
            if_generation_match: Optional[int] = None) -> int:
        fraction = self._tick("put", key)
        if fraction is not None:
            cut = max(0, min(len(data), int(len(data) * fraction)))
            _log.warning(
                "injected partial upload of %s: %d of %d bytes land",
                key, cut, len(data),
            )
            data = data[:cut]
        return self.inner.put(key, data,
                              if_generation_match=if_generation_match)

    def get(self, key: str) -> bytes:
        self._tick("get", key)
        return self.inner.get(key)

    def stat(self, key: str) -> Optional[BlobInfo]:
        self._tick("stat", key)
        return self.inner.stat(key)

    def list(self, prefix: str = "") -> List[str]:
        self._tick("list", prefix)
        return self.inner.list(prefix)

    def delete(self, key: str) -> bool:
        self._tick("delete", key)
        return self.inner.delete(key)


def blobstore_from_uri(uri: str) -> BlobStore:
    """Resolve a `--remote-store` URI to a backend.

    `file:///abs/path` and bare paths build a LocalBlobStore (hermetic
    tests, NFS fleet mounts); `gs://`/`s3://` are the production
    backends this interface is shaped for — their SDKs are not baked
    into this container, so they raise a clear provisioning error
    instead of a deep ImportError at first use."""
    uri = str(uri).strip()
    if not uri:
        raise ValueError("remote store URI must be non-empty")
    if uri.startswith("file://"):
        return LocalBlobStore(uri[len("file://"):] or "/")
    if "://" in uri:
        scheme = uri.split("://", 1)[0]
        raise NotImplementedError(
            f"remote store scheme {scheme!r} needs its cloud SDK "
            "provisioned; use file:// (or a bare path) for the "
            "filesystem backend"
        )
    return LocalBlobStore(uri)


def rmtree_blob_prefix(store: BlobStore, prefix: str) -> int:
    """Delete every key under `prefix`; returns the count removed (the
    blob analogue of shutil.rmtree, used by quarantine and pruning)."""
    removed = 0
    for key in store.list(prefix):
        if store.delete(key):
            removed += 1
    return removed


__all__ = [
    "BlobInfo",
    "BlobNotFound",
    "BlobPreconditionFailed",
    "BlobStore",
    "BlobStoreError",
    "BlobUnavailableError",
    "FaultyBlobStore",
    "LocalBlobStore",
    "blobstore_from_uri",
    "rmtree_blob_prefix",
]
