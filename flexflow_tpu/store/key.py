"""Store keys: (graph signature, mesh fingerprint, simulator version).

A searched strategy is reusable exactly when three things match the
search that produced it:

  * the FRONTEND graph it was searched for — ops, params, shapes,
    dtypes, edges, and the op/tensor NAMES a Strategy's shard_configs /
    edge_ops reference (the reference keys its exported strategies the
    same way: graph.cc:2164-2400 serializes per-op guids+params);
  * the machine it was placed onto — device count, machine-model
    identity, backend kind (an 8-chip plan is wrong on 4 survivors;
    a v5p-torus plan is wrong on a flat CPU mesh);
  * the simulator that ranked the candidates — cost-model version,
    fitted calibration table, and every search-shaping config knob
    (a ZeRO-1-costed winner is stale once the calibration improves —
    the invalidation discipline arXiv:2008.01040's learned cost model
    will also need).

Each component is a canonical JSON blob; the composed sha256 is the
content address under StrategyStore.  Digests are of EFFECTIVE inputs:
the calibration component hashes the constants a search would actually
load (sim/calibrate.load_overlap_constants), not raw file bytes, so an
ignored/invalid table can't split keys.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, Optional


def _sha256_text(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _sha256_json(obj) -> str:
    return _sha256_text(json.dumps(obj, sort_keys=True, default=str))


def _sha256_file(path: Optional[str]) -> Optional[str]:
    if not path:
        return None
    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None


# -- component fingerprints -------------------------------------------------

def graph_signature(graph) -> str:
    """Canonical hash of a frontend (degree-1) PCG.

    One record per op: name, type, params, input tensor names, output
    (name, shape) pairs.  Records sort by op name — layer names are the
    stable identity strategies bind to (shard_configs / edge_ops are
    name-keyed), so two construction orders of the same named graph
    hash identically, while any op/shape/dtype/edge change does not.
    """
    records = []
    for op in graph.topo_order():
        records.append({
            "name": op.name,
            "type": op.op_type.value,
            "params": repr(op.params),
            "shard": repr(op.shard) if getattr(op, "shard", None) else None,
            "inputs": [t.name for t in op.inputs],
            "outputs": [(t.name, str(t.shape)) for t in op.outputs],
        })
    records.sort(key=lambda r: r["name"])
    return _sha256_json(records)


def mesh_fingerprint(cfg, num_devices: int) -> Dict:
    """Identity of the hardware a strategy was placed onto: device
    count, node split, machine-model id (version + file digest), and
    the live backend kind (calibrated searches rank differently per
    chip generation).

    Hierarchy-aware (docs/TOPOLOGY.md): on a multi-slice run the slice
    count, per-slice topology and per-tier DCN bandwidth/latency join
    the fingerprint — a placement searched for 2 slices at one DCN
    speed is wrong for 4 slices or a faster fabric, so those entries
    must not alias.  Single-slice runs (the default) emit EXACTLY the
    pre-topology fields: the slice/DCN knobs never split a flat key.
    (The composed key still changes once per COST_MODEL_VERSION bump —
    v3 shipped with this subsystem — which is the digest guard working
    as designed: new cost semantics re-search once, fleet-wide.)"""
    platform, kind = "unknown", "unknown"
    try:
        import jax

        d = jax.devices()[0]
        platform, kind = d.platform, d.device_kind
    except Exception:
        pass
    out = {
        "num_devices": int(num_devices),
        "num_nodes": int(cfg.num_nodes),
        "machine_model_version": int(cfg.machine_model_version),
        "machine_model_file": _sha256_file(cfg.machine_model_file),
        "platform": platform,
        "device_kind": kind,
    }
    if int(getattr(cfg, "slices", 1)) > 1:
        out["slices"] = int(cfg.slices)
        out["slice_topology"] = (
            str(cfg.slice_topology) if cfg.slice_topology else None
        )
        out["dcn_bandwidth"] = float(cfg.dcn_bandwidth)
        out["dcn_latency"] = float(cfg.dcn_latency)
    return out


def _calibration_digest() -> str:
    """Digest of the overlap-constants table a search would actually
    load (None when absent/invalid — load_overlap_constants ignores
    those, so they must not split keys)."""
    try:
        from ..sim.calibrate import load_overlap_constants

        fitted = load_overlap_constants()
    except Exception:
        fitted = None
    if fitted is None:
        return "none"
    return _sha256_json(fitted)


def simulator_version(cfg) -> Dict:
    """Identity of the simulator + search configuration that ranked the
    candidates: cost-model/measure-cache versions, the fitted
    calibration digest, the TASO catalog identity, and every FFConfig
    knob that shapes what the search returns."""
    from ..sim.simulator import COST_MODEL_VERSION, OpCostModel

    catalog_sha = None
    try:
        from ..pcg.rewrite import catalog_fingerprint, catalog_for_config

        path = catalog_for_config(cfg)
        if path:
            catalog_sha = catalog_fingerprint(path).get("sha256")
    except Exception:
        catalog_sha = "unresolved"
    out = {
        "cost_model_version": COST_MODEL_VERSION,
        "measure_cache_version": OpCostModel.MEASURE_CACHE_VERSION,
        "calibration_digest": _calibration_digest(),
        "calibrated": bool(cfg.should_calibrate()),
        "catalog_sha256": catalog_sha,
        "search": {
            "algo": cfg.search_algo,
            "budget": int(cfg.search_budget),
            "alpha": float(cfg.search_alpha),
            "propagate": bool(cfg.search_propagate),
            "only_data_parallel": bool(cfg.only_data_parallel),
            "enable_parameter_parallel": bool(cfg.enable_parameter_parallel),
            "enable_attribute_parallel": bool(cfg.enable_attribute_parallel),
            "enable_sample_parallel": bool(cfg.enable_sample_parallel),
            "overlap_backward_update": bool(cfg.search_overlap_backward_update),
            "parameter_sync": str(cfg.parameter_sync.value),
            "memory_search": bool(cfg.memory_search),
            "memory_lambda": float(cfg.memory_lambda),
            "memory_per_device": int(cfg.memory_per_device),
            "segment_size": int(cfg.simulator_segment_size),
            "rewrite_depth": int(cfg.rewrite_depth),
            "rewrite_max_variants": int(cfg.rewrite_max_variants),
            "remat": bool(cfg.remat),
            # the ZeRO ladder stage shapes what the search returns
            # (stage rides the winning strategy); the legacy bool stays
            # in the key for operator-facing manifest readability
            "zero_stage": int(getattr(cfg, "zero_stage", 0)),
            "weight_update_sharding": bool(cfg.weight_update_sharding),
            "wus_axis": cfg.wus_axis,
            "seed": int(cfg.seed),
        },
    }
    # DCN grad-sync bucketing (--dcn-bucket-mb) reshapes grad-sync
    # costs only where a DCN tier exists, so — like the mesh
    # fingerprint's slice fields — the knob joins the key ONLY on
    # multi-slice configs: single-slice keys are bit-identical with or
    # without it.  The searched remat dimension needs no key field of
    # its own: it opens under memory_search (already keyed), the chosen
    # plan rides the stored strategy body (which serializes the plan
    # only when one was chosen — remat-free strategy digests are
    # unchanged), and the v4 cost-model bump already re-keys everything
    # once.
    if int(getattr(cfg, "slices", 1)) > 1:
        out["search"]["dcn_bucket_mb"] = float(
            getattr(cfg, "dcn_bucket_mb", 25.0)
        )
    return out


# -- the composed key -------------------------------------------------------

@dataclasses.dataclass
class StoreKey:
    """Composed store key.  `digest` is the content address; the
    component dicts land in the entry manifest so operators can read
    WHY two entries differ (docs/STORE.md)."""

    graph: str         # graph_signature hex
    mesh: Dict         # mesh_fingerprint
    sim: Dict          # simulator_version

    @property
    def digest(self) -> str:
        return _sha256_json(
            {"graph": self.graph, "mesh": self.mesh, "sim": self.sim}
        )

    def manifest_fields(self) -> Dict:
        return {
            "graph_signature": self.graph,
            "mesh": dict(self.mesh),
            "sim": json.loads(json.dumps(self.sim, default=str)),
        }


def store_key_for(cfg, graph, num_devices: int) -> StoreKey:
    """The key FFModel.compile / the elastic re-search consult the
    store under: frontend graph x target mesh x simulator identity."""
    return StoreKey(
        graph=graph_signature(graph),
        mesh=mesh_fingerprint(cfg, num_devices),
        sim=simulator_version(cfg),
    )


def strategy_sha256(text: str) -> str:
    """Digest of a serialized strategy body (manifest integrity field)."""
    return _sha256_text(text)


__all__ = [
    "StoreKey",
    "graph_signature",
    "mesh_fingerprint",
    "simulator_version",
    "store_key_for",
    "strategy_sha256",
]
