"""Persistent strategy + compile artifact store (docs/STORE.md).

The reference FlexFlow ships searched strategies as on-disk artifacts
(--export-strategy/--import-strategy, graph.cc:2164-2400) because the
search is the expensive, reusable part of the system.  This package
makes that a first-class, content-addressed tier:

  * StrategyStore — durable searched strategies keyed by
    (graph signature, mesh fingerprint, simulator version), with
    verify-then-publish writes and corrupt-entry tolerance (store.py);
  * cached_search — the one consult-then-publish wrapper every search
    site uses: FFModel.compile, the resilience supervisor's elastic
    re-search, and (through compile) serving replica spin-up;
  * enable_compilation_cache — JAX persistent compilation cache wired
    under the store root, so the compiled step function itself
    survives process death alongside the strategy that produced it.

Config surface: FFConfig.strategy_store / --strategy-store DIR /
--no-strategy-store (or the FLEXFLOW_TPU_STORE_DIR env var for fleet
deployments), FFConfig.compilation_cache / --compilation-cache [DIR].
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional

from ..logger import store_logger
from .key import (
    StoreKey,
    graph_signature,
    mesh_fingerprint,
    simulator_version,
    store_key_for,
)
from .blobstore import (
    BlobNotFound,
    BlobPreconditionFailed,
    BlobStore,
    BlobStoreError,
    BlobUnavailableError,
    FaultyBlobStore,
    LocalBlobStore,
    blobstore_from_uri,
)
from .store import (
    MANIFEST_VERSION,
    RemoteStrategyMirror,
    StoreVerifyError,
    StrategyStore,
)

#: env var naming a shared store root for every process in a fleet
#: (per-run --strategy-store overrides it; --no-strategy-store opts out)
STORE_DIR_ENV = "FLEXFLOW_TPU_STORE_DIR"


def resolve_store_dir(cfg) -> Optional[str]:
    """FFConfig.strategy_store -> effective store root, or None when
    the store is off.  None falls through to $FLEXFLOW_TPU_STORE_DIR;
    ''/'none' is an explicit opt-out (the substitution_json pattern)."""
    v = cfg.strategy_store
    if v is None:
        v = os.environ.get(STORE_DIR_ENV) or None
    if not v or str(v).strip().lower() == "none":
        return None
    return str(v)


def store_from_config(cfg, registry=None) -> Optional[StrategyStore]:
    """The run's StrategyStore, or None when disabled/unusable.  An
    unwritable root degrades to store-off with a log line — persistence
    is an accelerator, never a crash source.  FFConfig.remote_store
    attaches the fleet mirror (docs/STORE.md "Fleet mirror"): lookups
    consult local -> remote and publishes mirror through, sharing the
    checkpoint offload tier's blob root under its `strategies/`
    prefix."""
    root = resolve_store_dir(cfg)
    if root is None:
        return None
    remote = None
    uri = getattr(cfg, "remote_store", None)
    if uri and str(uri).strip().lower() != "none":
        try:
            from .blobstore import blobstore_from_uri
            from .store import RemoteStrategyMirror

            remote = RemoteStrategyMirror(blobstore_from_uri(uri))
        except (OSError, ValueError, NotImplementedError) as e:
            store_logger.info(
                "fleet mirror %r unusable (%s); continuing with the "
                "local store only", uri, e,
            )
    try:
        return StrategyStore(root, registry=registry, remote=remote)
    except OSError as e:
        store_logger.info(
            "strategy store root %s unusable (%s); continuing without "
            "the store", root, e,
        )
        return None


def enable_compilation_cache(cfg) -> Optional[str]:
    """Point JAX's persistent compilation cache at
    FFConfig.compilation_cache ('auto' = <store root>/xla_cache), so a
    restarted process re-loads its XLA executables from disk instead of
    recompiling.  Returns the cache dir, or None when off.  GLOBAL jax
    config: the most recent compile's setting wins for the whole
    process, so point every model in one process at the same cache
    (content-addressed internally — sharing is safe; split dirs only
    cost duplicate executables)."""
    spec = cfg.compilation_cache
    if not spec:
        return None
    if str(spec).strip().lower() == "auto":
        root = resolve_store_dir(cfg)
        if root is None:
            raise ValueError(
                "compilation_cache='auto' ties the XLA cache to the "
                "strategy store root, but no store is configured — set "
                f"--strategy-store/${STORE_DIR_ENV} or pass an explicit "
                "--compilation-cache DIR"
            )
        path = os.path.join(root, "xla_cache")  # StrategyStore layout
    else:
        path = str(spec)
    os.makedirs(path, exist_ok=True)
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", path)
        if jax.default_backend() not in ("cpu",):
            # cache EVERY executable on accelerators: cold start is the
            # point, and the store root is operator-provisioned space
            # (gc via docs/STORE.md).  On the CPU backend keep jax's
            # conservative defaults — force-caching sub-second CPU
            # executables makes their deserialization path segfault
            # (observed on jax 0.4.37 CPU meshes), and a CPU recompile
            # is cheaper than the risk
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except (AttributeError, ValueError) as e:  # older/newer jax knob drift
        store_logger.info(
            "jax persistent compilation cache tuning unavailable (%s); "
            "cache dir still set where supported", e,
        )
    return path


def cached_search(model, num_devices: int,
                  run_search: Callable[[], "object"]):
    """Consult-then-publish around one strategy search.

    Store off -> run_search() unchanged.  Store on: a hit returns the
    published strategy with search_stats carrying store_hit=True (the
    search is skipped entirely); a miss runs the search and publishes
    the winner under the same key so every later process — a preempted
    worker's replacement, an elastic re-search on the degraded mesh, a
    new serving replica — restores it instead of re-paying the search.
    """
    cfg = model.config
    registry = getattr(getattr(model, "telemetry", None), "metrics", None)
    store = store_from_config(cfg, registry=registry)
    if store is None:
        return run_search()
    key = store_key_for(cfg, model.layers, num_devices)
    hit = store.lookup(key)
    if hit is not None:
        store_logger.info(
            "store hit %s: strategy restored for %d devices, search "
            "skipped", key.digest[:16], num_devices,
        )
        return hit
    strategy = run_search()
    stats = getattr(strategy, "search_stats", None)
    if stats is None:
        stats = {}
        strategy.search_stats = stats
    stats["store_hit"] = False
    stats["store_key"] = key.digest
    store.publish(
        key,
        strategy,
        searched_cost=getattr(strategy, "search_cost", None),
        search_stats=stats,
        created_at=time.time(),
    )
    return strategy


__all__ = [
    "MANIFEST_VERSION",
    "STORE_DIR_ENV",
    "BlobNotFound",
    "BlobPreconditionFailed",
    "BlobStore",
    "BlobStoreError",
    "BlobUnavailableError",
    "FaultyBlobStore",
    "LocalBlobStore",
    "RemoteStrategyMirror",
    "StoreKey",
    "StoreVerifyError",
    "StrategyStore",
    "blobstore_from_uri",
    "cached_search",
    "enable_compilation_cache",
    "graph_signature",
    "mesh_fingerprint",
    "resolve_store_dir",
    "simulator_version",
    "store_from_config",
    "store_key_for",
]
