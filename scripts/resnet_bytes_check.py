"""Compare XLA bytes-accessed of resnet step variants (no timing needed,
cost_analysis is exact for static shapes): did the dot form let XLA fuse
the BN stats pass into the GEMM (bytes drop ~4GB) or not?"""
import sys
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

dev = jax.devices()[0]
import bench
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel
from flexflow_tpu.ops import dense as dense_mod
from flexflow_tpu.ops.dense import Conv2DParams, apply_activation

leg = bench.MANIFEST["legs"]["resnet50"]
sys.path.insert(0, "/root/repo/examples/python/pytorch")
from resnet50_search import ResNet50
B, px = leg["batch"], leg["px"]


def build_lowered():
    cfg = FFConfig(batch_size=B, num_devices=1, compute_dtype="bfloat16")
    ff = FFModel(cfg)
    x = ff.create_tensor([B, 3, px, px], name="input")
    (out,) = PyTorchModel(ResNet50(classes=leg["classes"])).torch_to_ff(ff, [x])
    ff.softmax(out)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    r = np.random.RandomState(0)
    xs = jax.device_put(r.randn(B, 3, px, px).astype(np.float32),
                        ff.executor.input_shardings()["input"])
    ys = jax.device_put(r.randint(0, leg["classes"], B).astype(np.int32),
                        ff.executor.label_sharding())
    import jax.random as jr
    lowered = ff.executor._step_fn.lower(
        ff._weights, ff._opt_state, ff._state, {"input": xs}, ys, jr.key(0))
    an = lowered.compile().cost_analysis()
    return an.get("bytes accessed"), an.get("flops")


orig_forward = dense_mod.Conv2D.forward


def dot1x1_forward(self, inputs, weights, *, training=False, rng=None):
    (x,) = inputs
    p: Conv2DParams = self.params
    nhwc = getattr(self, "_data_layout", "nchw") == "nhwc"
    if (nhwc and tuple(p.kernel) == (1, 1) and tuple(p.padding) == (0, 0)
            and p.groups == 1):
        w = weights[0]
        wt = jnp.transpose(w.reshape(w.shape[0], w.shape[1]), (1, 0)).astype(x.dtype)
        xs = x if tuple(p.stride) == (1, 1) else x[:, ::p.stride[0], ::p.stride[1], :]
        y = lax.dot_general(xs, wt, (((3,), (0,)), ((), ())))
        if p.use_bias:
            y = y + weights[1][None, None, None, :]
        return [apply_activation(y, p.activation)]
    return orig_forward(self, inputs, weights, training=training, rng=rng)


for name, fwd in [("base", orig_forward), ("dot1x1", dot1x1_forward)]:
    dense_mod.Conv2D.forward = fwd
    b, f = build_lowered()
    print(f"{name:8s}: bytes={b/1e9:.2f} GB  flops={f/1e12:.2f} TF", flush=True)
dense_mod.Conv2D.forward = orig_forward
