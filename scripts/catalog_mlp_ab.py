#!/usr/bin/env python
"""Catalog-rule A/B on chip: branchy linear model (the residual of
VERDICT r4 #1 — how `taso_rule_*` behaves ON HARDWARE, not just in
searched cost).

The model is the catalog's home turf: two dense+relu branches off one
input, concatenated.  Three variants:

  no_rewrites       rewrite enumeration off;
  joint             catalog + builtins, ANALYTIC costs only
                    (--no-calibrate path): the roofline prefers the
                    merge composite, which hardware mispriced at
                    width 4096 (0.90x — the documented negative);
  joint_calibrated  measured-cost calibration on (the real-TPU
                    default): the search measures the merged region,
                    drops the regressive merge, and keeps
                    taso_rule_543 (concat(relu,relu)->relu(concat)) —
                    a catalog rule in an on-chip calibrated winning
                    trace, measured neutral.

Interleaved best-of-N windows via scripts/_ab_common.py.

Usage: python scripts/catalog_mlp_ab.py [--batch 256] [--width 4096]
       [--iters 20] [--windows 6] [--skip-calibrated] [--cpu-smoke]
"""
import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, ".."))
sys.path.insert(0, _HERE)

from _ab_common import interleaved_best, make_train_window, summarize  # noqa: E402


def build(extra, batch, seq, width, dev, dtype):
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer

    cfg = FFConfig(batch_size=batch, num_devices=1, search_budget=20,
                   compute_dtype=dtype, **extra)
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, seq, width], name="input")
    a = ff.relu(ff.dense(x, width, name="fa"))
    b = ff.relu(ff.dense(x, width, name="fb"))
    t = ff.concat([a, b], axis=2)
    t = ff.dense(t, 16, name="head")
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    return ff


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--seq", type=int, default=4)
    ap.add_argument("--width", type=int, default=4096)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--windows", type=int, default=6)
    ap.add_argument("--skip-calibrated", action="store_true",
                    help="skip the calibrated leg (calibration adds "
                         "on-chip region timing to the search)")
    ap.add_argument("--cpu-smoke", action="store_true")
    args = ap.parse_args()
    if args.cpu_smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
        args.batch, args.width, args.iters, args.windows = 8, 64, 2, 1
        args.skip_calibrated = True  # calibration is a TPU-path feature
    import jax
    import numpy as np

    dev = jax.devices()[0]
    dtype = "bfloat16" if dev.platform != "cpu" else "float32"

    variants = [
        ("no_rewrites", dict(substitution_json="none",
                             rewrite_max_variants=1,
                             search_calibrate=False)),
        ("joint", dict(rewrite_depth=3, rewrite_max_variants=24,
                       search_calibrate=False)),
    ]
    if not args.skip_calibrated:
        variants.append(
            ("joint_calibrated", dict(rewrite_depth=3,
                                      rewrite_max_variants=24,
                                      search_calibrate=True)))

    rng = np.random.RandomState(0)
    xs = rng.randn(args.batch, args.seq, args.width).astype(np.float32)
    ys = rng.randint(0, 16, (args.batch, args.seq)).astype(np.int32)

    legs, windows = {}, {}
    for tag, extra in variants:
        print(f"[{tag}] searching + compiling ...", file=sys.stderr)
        ff = build(extra, args.batch, args.seq, args.width, dev, dtype)
        legs[tag] = {"rewrites": [list(r) for r in ff.strategy.rewrites]}
        windows[tag] = make_train_window(ff, {"input": xs}, ys, args.iters)
    for tag, timing in summarize(
            interleaved_best(windows, args.windows)).items():
        legs[tag].update(timing)

    base = legs["no_rewrites"]["step_ms"]
    out = {
        "workload": f"branchy-linear b{args.batch} seq{args.seq} "
                    f"w{args.width} {dtype} single-chip",
        **legs,
    }
    for tag in legs:
        if tag != "no_rewrites":
            out[f"speedup_{tag}"] = round(base / legs[tag]["step_ms"], 4)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
