"""On-chip probe: where does the ResNet-50 bench step spend its HBM
traffic, and can a Pallas fused BN-apply pass beat XLA's?

Runs three measurements (manifest workload, b256 224px bf16):
 1. full step (baseline);
 2. eval-mode BN (no batch-stats pass: apply from running stats) —
    isolates the stats-read cost;
 3. XLA cost-analysis bytes accessed vs the model's theoretical
    minimum HBM traffic.
Plus a microbench: XLA fused bn-apply+relu+residual vs a Pallas
single-pass kernel at representative resnet shapes.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp

dev = jax.devices()[0]
print("device:", dev, flush=True)

# --- microbench: fused bn-apply+relu+add, XLA vs Pallas ---------------
from jax.experimental import pallas as pl

def xla_apply(x, scale, shift, res):
    return jax.nn.relu(x * scale + shift + res)

def pallas_apply(x, scale, shift, res, rows=256):
    M, C = x.shape
    def kernel(x_ref, s_ref, b_ref, r_ref, o_ref):
        o_ref[...] = jnp.maximum(
            x_ref[...] * s_ref[...] + b_ref[...] + r_ref[...], 0.0
        ).astype(o_ref.dtype)
    grid = (M // rows,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, C), lambda i: (i, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((rows, C), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M, C), x.dtype),
    )(x, scale, shift, res)

def best_of(fn, *args, iters=30, windows=3):
    f = jax.jit(fn)
    r = f(*args); r.block_until_ready()
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(*args)
        r.block_until_ready()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best, r

rng = np.random.RandomState(0)
print("\n-- microbench: bn-apply+relu+residual (bf16) --", flush=True)
for (m, c) in [(256*56*56, 256), (256*28*28, 512), (256*14*14, 1024), (256*7*7, 2048)]:
    x = jax.device_put(jnp.asarray(rng.randn(m, c), jnp.bfloat16), dev)
    res = jax.device_put(jnp.asarray(rng.randn(m, c), jnp.bfloat16), dev)
    scale = jax.device_put(jnp.asarray(rng.rand(1, c) + 0.5, jnp.bfloat16), dev)
    shift = jax.device_put(jnp.asarray(rng.randn(1, c) * 0.1, jnp.bfloat16), dev)
    t_xla, r1 = best_of(xla_apply, x, scale, shift, res)
    t_pal, r2 = best_of(pallas_apply, x, scale, shift, res)
    ok = np.allclose(np.asarray(r1, np.float32), np.asarray(r2, np.float32), rtol=1e-2)
    bytes_min = (2 * m * c + m * c) * 2  # read x+res, write y, bf16
    bw = lambda t: bytes_min / t / 1e9
    print(f"[{m:9d} x {c:4d}] XLA {t_xla*1e6:7.1f}us ({bw(t_xla):5.0f} GB/s)  "
          f"Pallas {t_pal*1e6:7.1f}us ({bw(t_pal):5.0f} GB/s)  match={ok}", flush=True)

# --- whole-model: baseline vs eval-mode BN ----------------------------
print("\n-- whole model --", flush=True)
import bench
leg = bench.MANIFEST["legs"]["resnet50"]
sys.path.insert(0, "/root/repo/examples/python/pytorch")
from resnet50_search import ResNet50
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel

def build_and_time(batch=leg["batch"], px=leg["px"]):
    cfg = FFConfig(batch_size=batch, num_devices=1, compute_dtype="bfloat16")
    ff = FFModel(cfg)
    x = ff.create_tensor([batch, 3, px, px], name="input")
    (out,) = PyTorchModel(ResNet50(classes=leg["classes"])).torch_to_ff(ff, [x])
    ff.softmax(out)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    r = np.random.RandomState(0)
    xs = jax.device_put(r.randn(batch, 3, px, px).astype(np.float32),
                        ff.executor.input_shardings()["input"])
    ys = jax.device_put(r.randint(0, leg["classes"], batch).astype(np.int32),
                        ff.executor.label_sharding())
    for _ in range(3):
        m = ff.train_step({"input": xs}, ys)
    _ = float(m["loss"])
    dt = bench._steady_state(ff, {"input": xs}, ys, 40)
    return ff, dt, xs, ys

B = leg["batch"]
ff, dt, xs, ys = build_and_time()
print(f"baseline: {dt*1e3:.2f} ms/step ({B/dt:.0f} img/s)", flush=True)

# cost analysis of the train step: lower the executor's jitted step
# with the live argument pytrees (signature: weights, opt_state, state,
# inputs, labels, rng — model.train_step's call)
try:
    m = ff  # FFModel holds the live pytrees
    step = m.executor._step_fn
    import jax.random as jr
    lowered = step.lower(m._weights, m._opt_state, m._state,
                         {"input": xs}, ys, jr.key(0))
    an = lowered.compile().cost_analysis()
except Exception as e:
    an = None
    print("cost_analysis unavailable:", e, flush=True)
if an:
    ba = an.get("bytes accessed", None)
    fl = an.get("flops", None)
    print(f"bytes accessed/step: {ba}", flush=True)
    if ba:
        print(f"  = {ba/dt/1e9:.0f} GB/s effective (chip HBM ~819 GB/s)",
              flush=True)
    print(f"flops/step: {fl}", flush=True)

# no-BN ceiling: the native builder (models/resnet.py mirrors the
# reference resnet.cc, which has no BatchNorm)
from flexflow_tpu.models.resnet import build_resnet50
cfg = FFConfig(batch_size=B, num_devices=1, compute_dtype="bfloat16")
ff2 = FFModel(cfg)
build_resnet50(ff2, batch_size=B, image_size=leg["px"], num_classes=leg["classes"])
ff2.compile(optimizer=SGDOptimizer(lr=0.1),
            loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
            devices=[dev])
r = np.random.RandomState(0)
xs = jax.device_put(r.randn(B, 3, leg["px"], leg["px"]).astype(np.float32),
                    ff2.executor.input_shardings()["input"])
ys = jax.device_put(r.randint(0, leg["classes"], B).astype(np.int32),
                    ff2.executor.label_sharding())
for _ in range(3):
    m = ff2.train_step({"input": xs}, ys)
_ = float(m["loss"])
dt2 = bench._steady_state(ff2, {"input": xs}, ys, 40)
print(f"no-BN ceiling: {dt2*1e3:.2f} ms/step ({B/dt2:.0f} img/s); "
      f"BN/elementwise share = {(dt-dt2)/dt*100:.1f}%", flush=True)
