#!/usr/bin/env python
"""InceptionV3 joint-search A/B: is a rewrite (TASO catalog or built-in
merge) load-bearing on the real chip?  (VERDICT r4 #1; reference AE
/root/reference/scripts/osdi22ae/inception.sh — Unity vs DP on
Inception b=64 budget=10.)

Two searches over the identical model, measured back-to-back on chip:
  A "no-rewrites": rewrite enumeration disabled (max_variants=1),
    catalog off — parallelization-only search;
  B "joint": TASO catalog default-on + built-ins, rewrite_depth=3,
    rewrite_max_variants=16 — the full joint rewrite+parallelization
    search.

Prints one JSON line with both step times, the winning trace, and the
delta.  Honest either way: a ~0 delta with the trace shown is evidence
of the single-chip ceiling, not a failure to run.

Usage: python scripts/inception_taso_ab.py [--batch 32] [--px 299]
       [--iters 12] [--windows 3] [--cpu-smoke]
"""
import argparse
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, ".."))
sys.path.insert(0, _HERE)

from _ab_common import interleaved_best, make_train_window, summarize  # noqa: E402


def build(cfg_kwargs, batch, px, classes, dev):
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_inception_v3

    cfg = FFConfig(**cfg_kwargs)
    ff = FFModel(cfg)
    build_inception_v3(ff, batch_size=batch, num_classes=classes,
                       image_size=px)
    t0 = time.perf_counter()
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    search_s = time.perf_counter() - t0
    return ff, search_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--px", type=int, default=299)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--windows", type=int, default=3)
    ap.add_argument("--budget", type=int, default=10)
    ap.add_argument("--cpu-smoke", action="store_true",
                    help="tiny config on the host CPU (logic check)")
    args = ap.parse_args()

    if args.cpu_smoke:
        import jax

        jax.config.update("jax_platforms", "cpu")
        args.batch, args.px, args.iters, args.windows = 4, 75, 2, 1
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    dtype = "bfloat16" if on_tpu else "float32"
    common = dict(batch_size=args.batch, num_devices=1,
                  search_budget=args.budget, search_calibrate=False,
                  compute_dtype=dtype)

    import numpy as np

    # build both, then INTERLEAVE timing windows A/B/A/B...: the tunnel's
    # 2-6x throughput wobble is time-correlated, so alternating windows
    # puts both variants under the same conditions (best-of-N per side)
    variants = (
        ("no_rewrites", dict(substitution_json="none",
                             rewrite_max_variants=1)),
        ("joint", dict(rewrite_depth=3, rewrite_max_variants=16)),
    )
    rng = np.random.RandomState(0)
    xs = rng.randn(args.batch, 3, args.px, args.px).astype(np.float32)
    ys = rng.randint(0, args.classes, args.batch).astype(np.int32)
    legs, windows = {}, {}
    for tag, extra in variants:
        print(f"[{tag}] searching + compiling ...", file=sys.stderr)
        ff, search_s = build({**common, **extra}, args.batch, args.px,
                             args.classes, dev)
        legs[tag] = {
            "search_compile_s": round(search_s, 1),
            "rewrites": [list(r) for r in ff.strategy.rewrites],
        }
        windows[tag] = make_train_window(ff, {"input": xs}, ys, args.iters)
    for tag, timing in summarize(
            interleaved_best(windows, args.windows)).items():
        legs[tag].update(timing)
        legs[tag]["samples_per_sec"] = round(
            args.batch / (legs[tag]["step_ms"] / 1e3), 2)

    a, b = legs["no_rewrites"], legs["joint"]
    out = {
        "workload": f"InceptionV3 {args.px}px b{args.batch} {dtype} "
                    f"single-chip, search budget {args.budget}",
        "no_rewrites": a,
        "joint": b,
        "speedup": round(a["step_ms"] / b["step_ms"], 4),
        "winning_rules": sorted({r[0] for r in b["rewrites"]}),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
