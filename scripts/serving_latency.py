"""Serving latency benchmark: concurrent clients against the pipelined
DynamicBatcher; prints p50/p95/p99 request latency and throughput.

Run on the chip: python scripts/serving_latency.py
CPU smoke:       JAX_PLATFORMS=cpu python scripts/serving_latency.py --clients 4 --requests 50
"""
import argparse
import sys
import threading
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer  # noqa: E402
from flexflow_tpu.fftype import ActiMode, CompMode  # noqa: E402
from flexflow_tpu.serving import DynamicBatcher, InferenceEngine  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--requests", type=int, default=200, help="per client")
    p.add_argument("--max-batch", type=int, default=64)
    args = p.parse_args()

    ff = FFModel(FFConfig(batch_size=args.max_batch))
    x = ff.create_tensor([args.max_batch, 256], name="x")
    t = ff.dense(x, 1024, activation=ActiMode.RELU)
    t = ff.dense(t, 1024, activation=ActiMode.RELU)
    t = ff.dense(t, 16)
    ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               comp_mode=CompMode.INFERENCE)
    engine = InferenceEngine(ff, max_batch=args.max_batch)
    batcher = DynamicBatcher(engine, max_batch=args.max_batch,
                             flush_timeout_s=0.002)

    # warm every bucket the clients will hit
    for b in (1, 2, 4, 8, 16, 32, args.max_batch):
        engine.infer({"x": np.zeros((b, 256), np.float32)})

    errors = []

    def client(seed):
        rng = np.random.RandomState(seed)
        for _ in range(args.requests):
            n = int(rng.choice([1, 1, 1, 2, 4]))  # mostly single-sample
            try:
                out = batcher.infer({"x": rng.randn(n, 256).astype(np.float32)})
                assert out.shape == (n, 16)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=client, args=(s,))
               for s in range(args.clients)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    dt = time.perf_counter() - t0
    total = args.clients * args.requests
    stats = batcher.latency_stats()
    batcher.close()
    if errors:
        print(f"FAILED: {errors[0]}")
        sys.exit(1)
    print(f"requests: {total}  wall: {dt:.2f}s  "
          f"throughput: {total / dt:.0f} req/s  "
          f"batches: {batcher.batches_run} "
          f"(avg {stats.get('n', 0) and total / batcher.batches_run:.1f} req/batch)")
    print(f"latency ms: p50={stats.get('p50_ms')} p95={stats.get('p95_ms')} "
          f"p99={stats.get('p99_ms')} mean={stats.get('mean_ms')}")


if __name__ == "__main__":
    main()
