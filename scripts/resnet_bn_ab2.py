"""On-chip probe #6: whole-model A/B of BN restructurings.

Trace probe #5 showed the step's time sunk in backward mega-fusions that
RECOMPUTE the BN-apply chain inside every consumer (wgrad / dgrad / BN
reduce), running at 290-520 GB/s vs the 819 peak.  Variants:

  base     — current code (XLA recomputes xhat per consumer)
  barrier  — optimization_barrier on BN forward output: forces the
             normalized tensor to materialize once, consumers read it
  cvjp     — custom_vjp BN(+relu): saves xhat + invstd; backward is the
             classic two-pass formula over saved tensors (no recompute,
             no conv inside reduce fusions)
"""
import sys
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

dev = jax.devices()[0]
print("device:", dev, flush=True)

import bench
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel
from flexflow_tpu.ops import norm as norm_mod
from flexflow_tpu.ops.norm import BatchNormParams

leg = bench.MANIFEST["legs"]["resnet50"]
sys.path.insert(0, "/root/repo/examples/python/pytorch")
from resnet50_search import ResNet50
B, px = leg["batch"], leg["px"]


def build():
    cfg = FFConfig(batch_size=B, num_devices=1, compute_dtype="bfloat16")
    ff = FFModel(cfg)
    x = ff.create_tensor([B, 3, px, px], name="input")
    (out,) = PyTorchModel(ResNet50(classes=leg["classes"])).torch_to_ff(ff, [x])
    ff.softmax(out)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    r = np.random.RandomState(0)
    xs = jax.device_put(r.randn(B, 3, px, px).astype(np.float32),
                        ff.executor.input_shardings()["input"])
    ys = jax.device_put(r.randint(0, leg["classes"], B).astype(np.int32),
                        ff.executor.label_sharding())
    for _ in range(3):
        m = ff.train_step({"input": xs}, ys)
    loss = float(m["loss"])
    dt = bench._steady_state(ff, {"input": xs}, ys, 40)
    return dt, loss


orig_forward = norm_mod.BatchNorm.forward


def barrier_forward(self, inputs, weights, *, training=False, rng=None):
    y, rm, rv = orig_forward(self, inputs, weights, training=training, rng=rng)
    return [lax.optimization_barrier(y), rm, rv]


# ---- custom_vjp BN(+relu) training path -------------------------------
import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _bn_train(x, gamma, beta, axes, bshape, eps, relu):
    y, *_ = _bn_fwd_core(x, gamma, beta, axes, bshape, eps, relu)
    return y


def _bn_fwd_core(x, gamma, beta, axes, bshape, eps, relu):
    mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
    var = jnp.maximum(
        jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes) - jnp.square(mean),
        0.0)
    invstd = lax.rsqrt(var + eps)
    xhat = ((x.astype(jnp.float32) - mean.reshape(bshape))
            * invstd.reshape(bshape)).astype(x.dtype)
    y = xhat * gamma.reshape(bshape).astype(x.dtype) \
        + beta.reshape(bshape).astype(x.dtype)
    if relu:
        y = jax.nn.relu(y)
    return y, xhat, invstd, mean, var


def _bn_fwd(x, gamma, beta, axes, bshape, eps, relu):
    y, xhat, invstd, _, _ = _bn_fwd_core(x, gamma, beta, axes, bshape, eps, relu)
    return y, (xhat, invstd, gamma, y if relu else None)


def _bn_bwd(axes, bshape, eps, relu, res, dy):
    xhat, invstd, gamma, y = res
    if relu:
        dy = jnp.where(y > 0, dy, jnp.zeros_like(dy))
    n = 1
    for a in axes:
        n *= xhat.shape[a]
    dyf = dy.astype(jnp.float32)
    xf = xhat.astype(jnp.float32)
    dbeta = jnp.sum(dyf, axis=axes)
    dgamma = jnp.sum(dyf * xf, axis=axes)
    g = gamma.astype(jnp.float32) * invstd
    dx = (g.reshape(bshape) * (dyf - (dbeta / n).reshape(bshape)
                               - xf * (dgamma / n).reshape(bshape))).astype(xhat.dtype)
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


_bn_train.defvjp(_bn_fwd, _bn_bwd)


def cvjp_forward(self, inputs, weights, *, training=False, rng=None):
    (x,) = inputs
    p: BatchNormParams = self.params
    gamma, beta, rmean, rvar = weights
    nhwc = getattr(self, "_data_layout", "nchw") == "nhwc"
    axes = (0, 1, 2) if nhwc else (0, 2, 3)
    bshape = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
    if not training:
        return orig_forward(self, inputs, weights, training=training, rng=rng)
    mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
    var = jnp.maximum(
        jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes) - jnp.square(mean),
        0.0)
    new_rmean = p.momentum * rmean + (1 - p.momentum) * mean.astype(rmean.dtype)
    new_rvar = p.momentum * rvar + (1 - p.momentum) * var.astype(rvar.dtype)
    y = _bn_train(x, gamma, beta, axes, bshape, p.eps, p.relu)
    return [y, new_rmean, new_rvar]


variants = [("base", orig_forward), ("barrier", barrier_forward),
            ("cvjp", cvjp_forward)]
for name, fwd in variants:
    norm_mod.BatchNorm.forward = fwd
    try:
        dt, loss = build()
        print(f"{name:8s}: {dt*1e3:7.2f} ms/step  ({B/dt:6.0f} img/s)  loss={loss:.4f}",
              flush=True)
    except Exception as e:
        print(f"{name:8s}: FAILED {type(e).__name__}: {e}", flush=True)
norm_mod.BatchNorm.forward = orig_forward
