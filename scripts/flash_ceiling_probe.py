#!/usr/bin/env python
"""Flash-attention ceiling campaign kit (VERDICT r4 #5).

Per-kernel timing for the Pallas flash kernels (fwd, and the bwd pair
with independent dq/dkv tiles) plus two calibration probes: a large
plain matmul (the chip's practical MXU rate through this harness) and
XLA's unfused attention at the same shape (the do-nothing alternative).

Timing discipline: `iters` kernel invocations are CHAINED inside one
jitted lax.scan with real dataflow (carry + 0.0*result — floats are
never constant-folded), so one device program runs the whole window and
the axon tunnel's per-call dispatch appears once, not per iteration.
Even so the tunnel wobbles individual readings by up to ~30%; treat
single cells as ±30% and rely on repeated orderings (the r5 sweep ran
every cell 2-3x across sessions before picking _PREFERRED).

Prints one JSON line; run on the bench chip.

Usage: python scripts/flash_ceiling_probe.py [--bh 96] [--d 64]
       [--seqs 2048,4096,8192] [--iters 15] [--windows 3] [--causal]
"""
import argparse
import functools
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bh", type=int, default=96)  # bench leg: b8 x 12 heads
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--seqs", type=str, default="2048,4096,8192")
    ap.add_argument("--iters", type=int, default=15)
    ap.add_argument("--windows", type=int, default=3)
    ap.add_argument("--causal", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from flexflow_tpu.ops.pallas import flash_attention as fa
    from flexflow_tpu.sim.machine_model import detect_device_spec

    spec = detect_device_spec()
    peak, hbm = spec.peak_flops, spec.hbm_bandwidth
    scale = 1.0 / np.sqrt(args.d)
    causal = args.causal

    def timed(fn, carrier):
        def body(c, _):
            r = fn(c)
            return c + 0.0 * r.astype(c.dtype), None

        f = jax.jit(lambda c: lax.scan(body, c, None,
                                       length=args.iters)[0])
        jax.block_until_ready(f(carrier))
        best = float("inf")
        for _ in range(args.windows):
            t0 = time.perf_counter()
            jax.block_until_ready(f(carrier))
            best = min(best, (time.perf_counter() - t0) / args.iters)
        return best

    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(8192, 8192), jnp.bfloat16)
    b = jnp.asarray(rng.randn(8192, 8192), jnp.bfloat16)
    dt = timed(lambda c: c @ b, a)
    matmul_tfs = 2 * 8192**3 / dt / 1e12
    print(f"calibration matmul 8192^3: {dt*1e3:.3f} ms "
          f"-> {matmul_tfs:.1f} TF/s", file=sys.stderr)

    results = {}
    for s in (int(x) for x in args.seqs.split(",")):
        # hold total tokens ~constant across seq lengths (the bench
        # leg shape): bh 96 @2048 -> 48 @4096 -> 24 @8192
        bh, d = max(12, args.bh * 2048 // s), args.d
        q = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        out, lse = jax.jit(functools.partial(
            fa._flash_fwd, scale=scale, causal=causal))(q, k, v)
        jax.block_until_ready((out, lse))

        mm = 2.0 * bh * s * s * d  # dense FLOPs of one score-sized matmul

        def xla_attn(qc):
            sc = jnp.einsum("bqd,bkd->bqk", qc, k).astype(jnp.float32) \
                * scale
            return jnp.einsum("bqk,bkd->bqd",
                              jax.nn.softmax(sc, -1).astype(v.dtype), v)

        def flash_fwd(qc):
            return fa.flash_attention(qc, k, v, scale, causal)

        def flash_loss(qc, kc, vc):
            return jnp.sum(
                fa.flash_attention(qc, kc, vc, scale, causal)
                .astype(jnp.float32) ** 2)

        grad_all = jax.grad(flash_loss, argnums=(0, 1, 2))

        def flash_fwd_bwd(qc):
            # consume ALL THREE gradients: grad wrt q alone lets JAX
            # dead-code-eliminate the dkv pallas kernel entirely (it
            # did, inflating the r5 first-capture utilization ~1.7x)
            dq, dk, dv = grad_all(qc, k, v)
            return dq + 0.0 * (dk + dv).astype(dq.dtype)

        leg = {}
        score_bytes = bh * s * s * 4
        if score_bytes < spec.hbm_capacity // 4:
            dt = timed(xla_attn, q)
            leg["xla_attention"] = {
                "ms": round(dt * 1e3, 3),
                "dense_util": round(2 * mm / dt / peak, 4)}
        else:  # unfused scores would not even fit — flash's raison d'etre
            leg["xla_attention"] = {
                "error": f"scores {score_bytes/1e9:.1f} GB exceed HBM"}
        dt = timed(flash_fwd, q)
        leg["flash_fwd"] = {"ms": round(dt * 1e3, 3),
                            "dense_util": round(2 * mm / dt / peak, 4),
                            "blocks": fa._pick_blocks("fwd", s, s)}
        dt = timed(flash_fwd_bwd, q)
        leg["flash_fwd_bwd"] = {
            "ms": round(dt * 1e3, 3),
            "dense_util": round(9 * mm / dt / peak, 4),
            "dq_blocks": fa._pick_blocks("dq", s, s),
            "dkv_blocks": fa._pick_blocks("dkv", s, s),
        }
        results[str(s)] = leg
        print(f"seq{s}: {leg}", file=sys.stderr)

    print(json.dumps({
        "workload": f"flash kernels bh{args.bh} d{args.d} bf16 "
                    f"causal={causal} (dense-FLOP utilization vs "
                    f"nominal peak)",
        "peak_flops": peak, "hbm_bandwidth": hbm,
        "calibration_matmul_tfs": round(matmul_tfs, 1),
        "seqs": results,
    }))


if __name__ == "__main__":
    main()
