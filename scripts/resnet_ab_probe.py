"""On-chip probe #4: whole-model A/B of candidate ResNet-50 step
optimizations (microbenches are untrustworthy through the tunnel; the
steady-state step time with a fetched loss is the only reliable clock).

Variants (monkeypatched, no repo change until a win is measured):
  base     — current code
  dot1x1   — 1x1 convs as lax.dot_general (XLA can epilogue-fuse into a
             dot; it cannot fuse into a conv custom-call); stride-2
             downsample 1x1 convs slice first (reads 1/4 of x)
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

dev = jax.devices()[0]
print("device:", dev, flush=True)

import bench
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel
from flexflow_tpu.ops import dense as dense_mod
from flexflow_tpu.ops.dense import Conv2DParams, apply_activation

leg = bench.MANIFEST["legs"]["resnet50"]
sys.path.insert(0, "/root/repo/examples/python/pytorch")
from resnet50_search import ResNet50

B, px = leg["batch"], leg["px"]


def build():
    cfg = FFConfig(batch_size=B, num_devices=1, compute_dtype="bfloat16")
    ff = FFModel(cfg)
    x = ff.create_tensor([B, 3, px, px], name="input")
    (out,) = PyTorchModel(ResNet50(classes=leg["classes"])).torch_to_ff(ff, [x])
    ff.softmax(out)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    r = np.random.RandomState(0)
    xs = jax.device_put(r.randn(B, 3, px, px).astype(np.float32),
                        ff.executor.input_shardings()["input"])
    ys = jax.device_put(r.randint(0, leg["classes"], B).astype(np.int32),
                        ff.executor.label_sharding())
    for _ in range(3):
        m = ff.train_step({"input": xs}, ys)
    loss = float(m["loss"])
    dt = bench._steady_state(ff, {"input": xs}, ys, 40)
    return dt, loss


orig_forward = dense_mod.Conv2D.forward


def dot1x1_forward(self, inputs, weights, *, training=False, rng=None):
    (x,) = inputs
    p: Conv2DParams = self.params
    nhwc = getattr(self, "_data_layout", "nchw") == "nhwc"
    if (nhwc and tuple(p.kernel) == (1, 1) and tuple(p.padding) == (0, 0)
            and p.groups == 1):
        w = weights[0]
        wt = jnp.transpose(w.reshape(w.shape[0], w.shape[1]), (1, 0)).astype(x.dtype)
        xs = x if tuple(p.stride) == (1, 1) else x[:, ::p.stride[0], ::p.stride[1], :]
        y = lax.dot_general(xs, wt, (((3,), (0,)), ((), ())))
        if p.use_bias:
            y = y + weights[1][None, None, None, :]
        return [apply_activation(y, p.activation)]
    return orig_forward(self, inputs, weights, training=training, rng=rng)


for name, fwd in [("base", orig_forward), ("dot1x1", dot1x1_forward)]:
    dense_mod.Conv2D.forward = fwd
    dt, loss = build()
    print(f"{name:8s}: {dt*1e3:7.2f} ms/step  ({B/dt:6.0f} img/s)  loss={loss:.4f}",
          flush=True)
dense_mod.Conv2D.forward = orig_forward
