"""On-chip probe #2: per-fusion byte accounting of the ResNet-50 bench
step.  Dumps the optimized HLO's largest fusions/ops by bytes-accessed
so the margin work targets the real HBM consumers (probe #1 showed the
step at 94.5% of HBM peak: only removing passes can help).
"""
import sys
import collections

sys.path.insert(0, "/root/repo")
import numpy as np
import jax

dev = jax.devices()[0]
print("device:", dev, flush=True)

import bench
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel

leg = bench.MANIFEST["legs"]["resnet50"]
sys.path.insert(0, "/root/repo/examples/python/pytorch")
from resnet50_search import ResNet50

B, px = leg["batch"], leg["px"]
cfg = FFConfig(batch_size=B, num_devices=1, compute_dtype="bfloat16")
ff = FFModel(cfg)
x = ff.create_tensor([B, 3, px, px], name="input")
(out,) = PyTorchModel(ResNet50(classes=leg["classes"])).torch_to_ff(ff, [x])
ff.softmax(out)
ff.compile(optimizer=SGDOptimizer(lr=0.1),
           loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
           devices=[dev])
r = np.random.RandomState(0)
xs = jax.device_put(r.randn(B, 3, px, px).astype(np.float32),
                    ff.executor.input_shardings()["input"])
ys = jax.device_put(r.randint(0, leg["classes"], B).astype(np.int32),
                    ff.executor.label_sharding())

import jax.random as jr
step = ff.executor._step_fn
lowered = step.lower(ff._weights, ff._opt_state, ff._state,
                     {"input": xs}, ys, jr.key(0))
compiled = lowered.compile()
an = compiled.cost_analysis()
print("total bytes accessed:", an.get("bytes accessed"), flush=True)
print("total flops:", an.get("flops"), flush=True)

# Optimized HLO: bucket instructions by opcode, estimate bytes from
# operand + output shapes (static shapes, so exact).
mod = compiled.runtime_executable().hlo_modules()[0]
txt = mod.to_string()
with open("/tmp/resnet_step_hlo.txt", "w") as f:
    f.write(txt)
print("HLO dumped to /tmp/resnet_step_hlo.txt,", len(txt), "chars", flush=True)

# crude per-opcode census of the entry computation's top-level ops
import re
DTYPE_BYTES = {"f32": 4, "bf16": 2, "s32": 4, "u32": 4, "s8": 1, "u8": 1,
               "pred": 1, "f16": 2, "s64": 8, "u64": 8, "f64": 8}


def shape_bytes(s):
    total = 0
    for m in re.finditer(r"(\w+)\[([\d,]*)\]", s):
        dt, dims = m.group(1), m.group(2)
        if dt not in DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * DTYPE_BYTES[dt]
    return total


# find ENTRY computation block
entry = re.search(r"ENTRY [^{]+\{(.*)", txt, re.S)
body = entry.group(1) if entry else txt
body = body[: body.index("\n}")] if "\n}" in body else body
ops = collections.Counter()
byts = collections.Counter()
shapes = {}
rows = []
for line in body.splitlines():
    line = line.strip()
    # optimized HLO carries layout/tiling annotations and tuple result
    # types: "%name = (bf16[..]{..}, f32[..]{..}) fusion(%a, %b), ..."
    m = re.match(r"(%[\w.\-]+) = (\(?.*?\)?) ([\w\-]+)\((.*)", line)
    if not m:
        continue
    name, ty, opname, rest = m.groups()
    out_b = shape_bytes(ty)
    shapes[name] = out_b
    in_b = sum(
        shapes.get(o, 0)
        for o in re.findall(r"%[\w.\-]+",
                            rest.split(", calls=")[0].split(", metadata=")[0])
    )
    ops[opname] += 1
    byts[opname] += out_b + in_b
    rows.append((out_b + in_b, opname, name, line[:140]))

print("\n-- opcode census (entry, output bytes) --", flush=True)
for op, b in byts.most_common(15):
    print(f"{op:20s} n={ops[op]:4d}  out_bytes={b/1e9:8.3f} GB", flush=True)

print("\n-- top 25 single instructions by output bytes --", flush=True)
rows.sort(reverse=True)
for b, opname, name, line in rows[:25]:
    print(f"{b/1e9:7.3f} GB  {line}", flush=True)

# count transposes/copies — layout sanity
n_tr = len(re.findall(r" transpose\(", txt))
n_cp = len(re.findall(r" copy\(", txt))
print(f"\ntransposes in module: {n_tr}, copies: {n_cp}", flush=True)
