"""Shared measurement harness for the scripts/ A/B kits
(inception_taso_ab.py, catalog_mlp_ab.py): warmup + device-resident
batch + INTERLEAVED best-of-N windows, so the tunnel's time-correlated
throughput wobble hits every variant equally."""
from __future__ import annotations

import sys
import time
from typing import Callable, Dict, List, Sequence, Tuple


def make_train_window(ff, inputs, labels, iters: int) -> Callable[[], float]:
    """Device-put the batch, warm up, and return a window() closure
    measuring seconds/step over `iters` serial steps with ONE hard
    sync (fetching the loss drains the donated-weight chain)."""
    import jax

    put = {
        k: jax.device_put(v, ff.executor.input_shardings()[k])
        for k, v in inputs.items()
    }
    ys = jax.device_put(labels, ff.executor.label_sharding())
    for _ in range(3):
        m = ff.train_step(put, ys)
    _ = float(m["loss"])

    def window() -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            m = ff.train_step(put, ys)
        _ = float(m["loss"])
        return (time.perf_counter() - t0) / iters

    return window


def interleaved_best(windows: Dict[str, Callable[[], float]],
                     rounds: int) -> Dict[str, List[float]]:
    """Run each variant's window once per round, A/B/A/B...; returns
    per-variant per-round seconds/step."""
    samples: Dict[str, List[float]] = {tag: [] for tag in windows}
    for r in range(rounds):
        for tag, win in windows.items():
            samples[tag].append(win())
        print(f"window {r}: " + " ".join(
            f"{tag}={samples[tag][-1]*1e3:.2f}ms" for tag in windows),
            file=sys.stderr)
    return samples


def summarize(samples: Dict[str, List[float]]) -> Dict[str, Dict]:
    return {
        tag: {
            "step_ms": round(min(s) * 1e3, 3),
            "window_ms": [round(x * 1e3, 3) for x in s],
        }
        for tag, s in samples.items()
    }
