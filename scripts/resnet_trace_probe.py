"""On-chip probe #5: jax profiler trace of the resnet bench step; parse
the device trace for the top ops by self time (replaces byte-model
guesswork with measured per-op time)."""
import sys, glob, gzip, json, collections
sys.path.insert(0, "/root/repo")
import numpy as np
import jax

dev = jax.devices()[0]
print("device:", dev, flush=True)

import bench
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel

leg = bench.MANIFEST["legs"]["resnet50"]
sys.path.insert(0, "/root/repo/examples/python/pytorch")
from resnet50_search import ResNet50
B, px = leg["batch"], leg["px"]

cfg = FFConfig(batch_size=B, num_devices=1, compute_dtype="bfloat16")
ff = FFModel(cfg)
x = ff.create_tensor([B, 3, px, px], name="input")
(out,) = PyTorchModel(ResNet50(classes=leg["classes"])).torch_to_ff(ff, [x])
ff.softmax(out)
ff.compile(optimizer=SGDOptimizer(lr=0.1),
           loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
           devices=[dev])
r = np.random.RandomState(0)
xs = jax.device_put(r.randn(B, 3, px, px).astype(np.float32),
                    ff.executor.input_shardings()["input"])
ys = jax.device_put(r.randint(0, leg["classes"], B).astype(np.int32),
                    ff.executor.label_sharding())
for _ in range(5):
    m = ff.train_step({"input": xs}, ys)
print("warm, loss", float(m["loss"]), flush=True)

import shutil
shutil.rmtree("/tmp/restrace", ignore_errors=True)
with jax.profiler.trace("/tmp/restrace"):
    for _ in range(3):
        m = ff.train_step({"input": xs}, ys)
    _ = float(m["loss"])
print("trace captured", flush=True)

# parse the trace proto (xplane) via tensorflow-free reader if available,
# else the trace.json.gz event file
files = glob.glob("/tmp/restrace/**/*.trace.json.gz", recursive=True)
print("trace files:", files, flush=True)
if files:
    ev = json.load(gzip.open(files[0]))
    events = ev.get("traceEvents", [])
    # restrict to the device "XLA Ops" lane (thread_name metadata) —
    # summing every pid/tid would mix host TraceMe spans (which cover
    # whole steps) with device self-time and double-count derived lanes
    op_lanes = set()
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "thread_name"
                and (e.get("args") or {}).get("name") == "XLA Ops"):
            op_lanes.add((e.get("pid"), e.get("tid")))
    agg = collections.Counter()
    cnt = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in op_lanes:
            continue
        base = e.get("name", "").rstrip("0123456789").rstrip(".")
        agg[base] += e.get("dur", 0)  # us
        cnt[base] += 1
    tot = sum(agg.values())
    print(f"\ndevice op time: {tot/1e3:.1f} ms over 3 steps "
          f"= {tot/3e3:.2f} ms/step", flush=True)
    print("\n-- top device op groups (us over 3 steps) --", flush=True)
    for name, d in agg.most_common(40):
        print(f"{d:10.0f} us  n={cnt[name]:4d}  {name[:90]}", flush=True)
else:
    xp = glob.glob("/tmp/restrace/**/*.xplane.pb", recursive=True)
    print("no trace.json.gz; xplane files:", xp, flush=True)
