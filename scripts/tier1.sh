#!/usr/bin/env bash
# Tier-1 test suite, split into three deterministic tranches.
#
# The single-shot tier-1 run outgrew its 870 s wall-clock budget, so
# this script sorts tests/test_*.py lexically and deals the list
# round-robin into three tranches, running each under its own 870 s
# timeout with the exact flags from ROADMAP.md.  Round-robin (not a
# contiguous split) matters: the expensive serving tests cluster
# alphabetically, and a contiguous split piles them all into one
# tranche that then blows the budget on its own.  The deal is purely
# lexical — no timing data, no randomness — so any test lands in the
# same tranche on every machine.
#
# Output contract (matches the old one-shot verify line):
#   DOTS_PASSED=<total>   merged passed-dot count across tranches
#   exit 0 iff ALL tranches exit 0.
#
# Usage: scripts/tier1.sh [extra pytest args...]
set -u -o pipefail

cd "$(dirname "$0")/.."

mapfile -t FILES < <(ls tests/test_*.py | LC_ALL=C sort)
n=${#FILES[@]}
if [ "$n" -eq 0 ]; then
    echo "tier1.sh: no test files found" >&2
    exit 2
fi

T1=() T2=() T3=()
for i in "${!FILES[@]}"; do
    case $(( i % 3 )) in
        0) T1+=("${FILES[$i]}") ;;
        1) T2+=("${FILES[$i]}") ;;
        2) T3+=("${FILES[$i]}") ;;
    esac
done

run_tranche() {
    local idx="$1"; shift
    local log="/tmp/_t1_tranche${idx}.log"
    rm -f "$log"
    echo "== tier-1 tranche ${idx}: $# file(s) =="
    timeout -k 10 870 env JAX_PLATFORMS=cpu \
        python -m pytest "$@" -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly 2>&1 | tee "$log"
    local rc=${PIPESTATUS[0]}
    local dots
    dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$log" | tr -cd . | wc -c)
    echo "TRANCHE${idx}_RC=${rc} TRANCHE${idx}_DOTS=${dots}"
    TOTAL_DOTS=$(( TOTAL_DOTS + dots ))
    return "$rc"
}

TOTAL_DOTS=0
FINAL_RC=0
run_tranche 1 "${T1[@]}" || FINAL_RC=$?
run_tranche 2 "${T2[@]}" || rc2=$?
run_tranche 3 "${T3[@]}" || rc3=$?
[ "${rc2:-0}" -ne 0 ] && [ "$FINAL_RC" -eq 0 ] && FINAL_RC=$rc2
[ "${rc3:-0}" -ne 0 ] && [ "$FINAL_RC" -eq 0 ] && FINAL_RC=$rc3

echo "DOTS_PASSED=${TOTAL_DOTS}"
exit "$FINAL_RC"
