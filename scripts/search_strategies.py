"""Pre-search strategies for the north-star models and ship them as JSON
artifacts (reference parity: examples/cpp/DLRM/strategies/*.pb — the
reference distributes pre-searched strategy files so runs can skip the
search; here `--import-strategy` loads them).

Usage (hermetic CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python scripts/search_strategies.py --out examples/strategies -n 8

Each JSON round-trips through Strategy.load + FFModel.compile(strategy=...)
and records the graph rewrites the search applied.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer  # noqa: E402


def _searched(build, n, batch, loss=None, **cfg_kw):
    cfg = FFConfig(batch_size=batch, num_devices=n, search_budget=500,
                   **cfg_kw)
    ff = FFModel(cfg)
    build(ff, cfg)
    import jax

    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=loss or LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=jax.devices()[:n])
    return ff


def bert(ff, cfg):
    from flexflow_tpu.models.transformer import build_bert

    build_bert(ff, batch_size=cfg.batch_size, seq_length=64, hidden_size=256,
               num_layers=4, num_heads=8, intermediate_size=1024)


def inception(ff, cfg):
    from flexflow_tpu.models.inception import build_inception_v3

    build_inception_v3(ff, batch_size=cfg.batch_size, image_size=75,
                       channel_scale=0.25)


def dlrm(ff, cfg):
    from flexflow_tpu.models.dlrm import build_dlrm

    build_dlrm(ff, batch_size=cfg.batch_size)


#: (artifact name, builder, batch, FFConfig overrides) — the single
#: source of truth; tests/test_strategy_artifacts.py imports this so the
#: shipped strategies and the graphs they apply to cannot drift apart
JOBS = [
    ("bert_encoder", "bert", 16, {"enable_parameter_parallel": True}),
    ("inception_v3", "inception", 16, {}),
    ("dlrm", "dlrm", 16, {"enable_attribute_parallel": True}),
]


# -- v5p-32 target-scale artifacts (VERDICT r03 Missing #2) ---------------
#
# All five BASELINE configs searched at 16 chips under the v5p-32
# 3D-torus machine file (examples/machines/v5p32.json).  The search is
# purely analytic, so the graphs are built at the BASELINE's REAL
# workload scale (searching a toy batch at 16 chips degenerates: grad
# sync dominates tiny compute and "replicate everything" wins).
# tests/test_strategy_artifacts.py re-applies each artifact to a
# structurally identical reduced-size graph on a hermetic 16-device CPU
# mesh and trains one step.  `search` builds the search-scale graph;
# `validate` the CPU-sized one — SAME layer names, different shapes.

V5P32_MACHINE = os.path.join(os.path.dirname(__file__), "..",
                             "examples", "machines", "v5p32.json")


def _v5p32_models():
    from flexflow_tpu.models.alexnet import build_alexnet
    from flexflow_tpu.models.dlrm import build_dlrm
    from flexflow_tpu.models.inception import build_inception_v3
    from flexflow_tpu.models.resnet import build_resnet50
    from flexflow_tpu.models.transformer import build_bert

    return {
        "alexnet": dict(
            search=lambda ff: build_alexnet(ff, batch_size=1024,
                                            image_size=229,
                                            num_classes=1000),
            validate=lambda ff: build_alexnet(ff, batch_size=32,
                                              image_size=64,
                                              num_classes=100),
            cfg={},
            loss=None,
        ),
        "resnet50": dict(
            search=lambda ff: build_resnet50(ff, batch_size=512,
                                             image_size=224,
                                             num_classes=1000),
            validate=lambda ff: build_resnet50(ff, batch_size=32,
                                               image_size=64,
                                               num_classes=100),
            cfg={},
            loss=None,
        ),
        "bert_base": dict(
            search=lambda ff: build_bert(ff, batch_size=256, seq_length=128,
                                         hidden_size=768, num_layers=12,
                                         num_heads=12,
                                         intermediate_size=3072),
            # batch must satisfy the artifact's pipeline payload
            # (dp=4 x 64 microbatches searched at b256): keep b256,
            # shrink seq/hidden instead
            validate=lambda ff: build_bert(ff, batch_size=256, seq_length=16,
                                           hidden_size=96, num_layers=12,
                                           num_heads=12,
                                           intermediate_size=384),
            cfg={"enable_parameter_parallel": True},
            loss=None,
        ),
        "dlrm": dict(
            search=lambda ff: build_dlrm(ff, batch_size=4096,
                                         embedding_size=[1000000] * 4),
            validate=lambda ff: build_dlrm(ff, batch_size=64,
                                           embedding_size=[10000] * 4),
            cfg={"enable_attribute_parallel": True},
            loss=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        ),
        "inception_v3": dict(
            search=lambda ff: build_inception_v3(ff, batch_size=128,
                                                 image_size=299,
                                                 num_classes=1000),
            # b128 = the searched batch (pipeline payload dp=8 x 16
            # microbatches); 75px/0.25-scale keeps the CPU step small
            validate=lambda ff: build_inception_v3(ff, batch_size=128,
                                                   image_size=75,
                                                   channel_scale=0.25),
            cfg={},
            loss=None,
        ),
    }


def search_v5p32_strategy(name: str, job: dict):
    """Search one BASELINE config at full workload scale on the v5p-32
    machine model, WITHOUT compiling an executor (the searched shapes
    exceed a CPU host; only the analytic search sees them)."""
    from flexflow_tpu.pcg.search import unity_search

    cfg = FFConfig(batch_size=64, num_devices=16, search_budget=500,
                   machine_model_file=V5P32_MACHINE, **job["cfg"])
    ff = FFModel(cfg)
    job["search"](ff)
    return unity_search(ff, 16)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="examples/strategies")
    p.add_argument("-n", "--num-devices", type=int, default=8)
    p.add_argument("--jobs", choices=["default", "v5p32"], default="default")
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)

    if args.jobs == "v5p32":
        for name, job in _v5p32_models().items():
            strategy = search_v5p32_strategy(name, job)
            path = os.path.join(args.out, f"{name}.json")
            strategy.save(path)
            print(f"{name}: mesh={strategy.mesh_axes} "
                  f"shards={len(strategy.shard_configs)} "
                  f"rewrites={strategy.rewrites} -> {path}")
        return

    for name, build, batch, kw in JOBS:
        ff = _searched(globals()[build], args.num_devices, batch, **kw)
        path = os.path.join(args.out, f"{name}.json")
        ff.strategy.save(path)
        print(f"{name}: mesh={ff.strategy.mesh_axes} "
              f"shards={len(ff.strategy.shard_configs)} "
              f"rewrites={ff.strategy.rewrites} -> {path}")


if __name__ == "__main__":
    main()
