"""Pre-search strategies for the north-star models and ship them as JSON
artifacts (reference parity: examples/cpp/DLRM/strategies/*.pb — the
reference distributes pre-searched strategy files so runs can skip the
search; here `--import-strategy` loads them).

Usage (hermetic CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python scripts/search_strategies.py --out examples/strategies -n 8

Each JSON round-trips through Strategy.load + FFModel.compile(strategy=...)
and records the graph rewrites the search applied.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer  # noqa: E402


def _searched(build, n, batch, **cfg_kw):
    cfg = FFConfig(batch_size=batch, num_devices=n, search_budget=500,
                   **cfg_kw)
    ff = FFModel(cfg)
    build(ff, cfg)
    import jax

    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=jax.devices()[:n])
    return ff


def bert(ff, cfg):
    from flexflow_tpu.models.transformer import build_bert

    build_bert(ff, batch_size=cfg.batch_size, seq_length=64, hidden_size=256,
               num_layers=4, num_heads=8, intermediate_size=1024)


def inception(ff, cfg):
    from flexflow_tpu.models.inception import build_inception_v3

    build_inception_v3(ff, batch_size=cfg.batch_size, image_size=75,
                       channel_scale=0.25)


def dlrm(ff, cfg):
    from flexflow_tpu.models.dlrm import build_dlrm

    build_dlrm(ff, batch_size=cfg.batch_size)


#: (artifact name, builder, batch, FFConfig overrides) — the single
#: source of truth; tests/test_strategy_artifacts.py imports this so the
#: shipped strategies and the graphs they apply to cannot drift apart
JOBS = [
    ("bert_encoder", "bert", 16, {"enable_parameter_parallel": True}),
    ("inception_v3", "inception", 16, {}),
    ("dlrm", "dlrm", 16, {"enable_attribute_parallel": True}),
]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="examples/strategies")
    p.add_argument("-n", "--num-devices", type=int, default=8)
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)

    for name, build, batch, kw in JOBS:
        ff = _searched(globals()[build], args.num_devices, batch, **kw)
        path = os.path.join(args.out, f"{name}.json")
        ff.strategy.save(path)
        print(f"{name}: mesh={ff.strategy.mesh_axes} "
              f"shards={len(ff.strategy.shard_configs)} "
              f"rewrites={ff.strategy.rewrites} -> {path}")


if __name__ == "__main__":
    main()
