"""On-chip probe: KV-cache decoding throughput — O(T^2) re-forward vs
host-loop cached decode vs whole-generation-as-one-program lax.scan
(GPT-2-small shape).  Through the axon tunnel the scan path also shows
the RTT x T -> RTT x 1 host-round-trip win."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax

dev = jax.devices()[0]
print("device:", dev, flush=True)

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.decoding import (
    gpt_generate_cached, gpt_generate_scan, make_gpt_decoder,
)
from flexflow_tpu.models.transformer import build_gpt, gpt_generate

B, S, NEW = 8, 256, 128
ff = FFModel(FFConfig(batch_size=B, num_devices=1, compute_dtype="bfloat16"))
build_gpt(ff, batch_size=B, seq_length=S, hidden_size=768, num_layers=12,
          num_heads=12, intermediate_size=3072, vocab_size=50257)
ff.compile(optimizer=SGDOptimizer(lr=0.01),
           loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
           devices=[dev])
rng = np.random.RandomState(0)
prompt = rng.randint(1, 50257, size=(B, 64)).astype(np.int32)

print("building decoder twin...", flush=True)
ffd = make_gpt_decoder(ff, devices=[dev])

# warm each path once on a short run, then time one full generation
for name, fn in [
    ("full-O(T^2)", lambda n: gpt_generate(ff, prompt, n)),
    ("cached-host", lambda n: gpt_generate_cached(ffd, prompt, n)),
    ("cached-scan", lambda n: gpt_generate_scan(ffd, prompt, n)),
]:
    _ = fn(2)
    t0 = time.perf_counter()
    out = fn(NEW)
    dt = time.perf_counter() - t0
    tok = B * NEW / dt
    print(f"{name:12s}: {dt:7.2f}s for {NEW} new tokens x b{B} "
          f"({tok:8.0f} tok/s)  tail={out[0, -4:].tolist()}", flush=True)
