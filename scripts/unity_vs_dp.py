"""Unity-vs-data-parallel comparison (reference scripts/osdi22ae/*.sh:
each AE workload runs the Unity search and reports its strategy's
speedup over the pure data-parallel baseline).

Per workload: run the Unity search, rank BOTH strategies with the
simulator (the search's own judge), and — with --run — execute both on
the available devices and print measured throughputs.

  PYTHONPATH=. python scripts/unity_vs_dp.py --workload mlp -n 8
  PYTHONPATH=. python scripts/unity_vs_dp.py --workload bert -n 8 --run
"""
import argparse
import sys
import time

import numpy as np


def build(workload: str, batch: int, substitution_json=None):
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.models import build_mlp_unify
    from flexflow_tpu.models.transformer import build_transformer

    # substitution_json must reach FFConfig too: compile-time replay of
    # a recorded catalog rewrite builds its rule list from the config
    ff = FFModel(FFConfig(batch_size=batch,
                          substitution_json=substitution_json))
    if workload == "mlp":
        build_mlp_unify(ff, batch_size=batch, input_dim=256,
                        hidden_dims=[2048] * 4 + [16])
        data = {
            "input1": np.random.randn(batch, 256).astype(np.float32),
            "input2": np.random.randn(batch, 256).astype(np.float32),
        }
        labels = np.random.randint(0, 16, batch).astype(np.int32)
    elif workload == "bert":
        build_transformer(ff, batch_size=batch, seq_length=128,
                          hidden_size=256, num_layers=4, num_heads=8)
        data = {"input": np.random.randn(batch, 128, 256).astype(np.float32)}
        labels = np.random.rand(batch, 128, 1).astype(np.float32)
    else:
        raise SystemExit(f"unknown workload {workload}")
    return ff, data, labels


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--workload", default="mlp", choices=["mlp", "bert"])
    p.add_argument("-n", "--num-devices", type=int, default=8)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--run", action="store_true",
                   help="also execute both strategies and time them")
    p.add_argument("--substitution-json", default=None,
                   help="TASO RuleCollection file (e.g. the reference's "
                        "graph_subst_3_v2.json): its verified rules join "
                        "the rewrite enumeration")
    args = p.parse_args()

    from flexflow_tpu.pcg.rewrite import (CATALOG_DEGREES,
                                          generate_rewrite_rules,
                                          load_rewrite_rules)
    from flexflow_tpu.pcg.unity import UnitySearch
    from flexflow_tpu.sim.machine_model import TpuPodModel
    from flexflow_tpu.sim.simulator import OpCostModel, Simulator
    from flexflow_tpu.strategy import (
        apply_strategy,
        assign_views,
        data_parallel_strategy,
    )

    ff, _, _ = build(args.workload, args.batch_size,
                     args.substitution_json)
    machine = TpuPodModel()
    cm = OpCostModel(machine)
    sim = Simulator(machine, cm)

    def ranked(strategy):
        g = apply_strategy(ff.layers, strategy)
        assign_views(g, strategy.mesh_axes)
        return sim.simulate(g, strategy.mesh_axes).total_time

    dp = data_parallel_strategy(args.num_devices)
    t0 = time.perf_counter()
    unity = UnitySearch(
        ff.layers, args.num_devices, machine, cm,
        # same rule list + degrees the compile-time replay builds
        # (rules_for_config / CATALOG_DEGREES) so recorded rewrite
        # traces stay replayable; depth/variant overrides only apply
        # when the catalog widens the rule pool
        rewrite_rules=(
            generate_rewrite_rules()
            + load_rewrite_rules(args.substitution_json,
                                 degrees=CATALOG_DEGREES)
            if args.substitution_json else None
        ),
        **({"rewrite_depth": 3, "rewrite_max_variants": 24}
           if args.substitution_json else {}),
    ).optimize()
    search_s = time.perf_counter() - t0
    if unity is None:
        print(f"workload={args.workload} n={args.num_devices}: no valid "
              f"Unity strategy found; data-parallel simulated "
              f"{ranked(dp) * 1e3:.3f} ms/iter")
        sys.exit(0)
    t_dp, t_unity = ranked(dp), ranked(unity)
    print(f"workload={args.workload} n={args.num_devices} "
          f"(search took {search_s:.1f}s)")
    print(f"  data-parallel   : mesh={dp.mesh_axes}  simulated "
          f"{t_dp * 1e3:.3f} ms/iter")
    print(f"  unity strategy  : mesh={unity.mesh_axes}  simulated "
          f"{t_unity * 1e3:.3f} ms/iter  "
          f"({t_dp / t_unity:.2f}x vs DP)")

    if not args.run:
        return
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer

    for name, strategy in [("data-parallel", dp), ("unity", unity)]:
        m, d, l = build(args.workload, args.batch_size,
                        args.substitution_json)
        loss = (LossType.SPARSE_CATEGORICAL_CROSSENTROPY
                if args.workload == "mlp"
                else LossType.MEAN_SQUARED_ERROR_AVG_REDUCE)
        m.compile(optimizer=SGDOptimizer(lr=0.01), loss_type=loss,
                  strategy=strategy)
        for _ in range(3):
            res = m.train_step(d, l)
        _ = float(res["loss"])
        iters = 10
        t0 = time.perf_counter()
        for _ in range(iters):
            res = m.train_step(d, l)
        _ = float(res["loss"])
        dt = (time.perf_counter() - t0) / iters
        print(f"  measured {name:<14}: {dt * 1e3:.1f} ms/iter "
              f"({args.batch_size / dt:.0f} samples/s)")


if __name__ == "__main__":
    main()
