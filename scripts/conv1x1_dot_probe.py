"""On-chip probe #3: is a 1x1 conv faster as lax.dot_general, and does
XLA fuse a BN-stats reduction into the dot's epilogue (it cannot fuse
into a conv custom-call)?  ResNet-50 b256 shapes, bf16, NHWC."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

dev = jax.devices()[0]
print("device:", dev, flush=True)


def timeit(fn, *args, iters=20, windows=3):
    f = jax.jit(fn)
    r = jax.block_until_ready(f(*args))
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(*args)
        jax.block_until_ready(r)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best, r


def conv_cc(x, w):  # custom-call path, NHWC/OIHW
    return lax.conv_general_dilated(x, w, (1, 1), [(0, 0), (0, 0)],
                                    dimension_numbers=("NHWC", "OIHW", "NHWC"))


def conv_dot(x, w):
    wt = jnp.transpose(w.reshape(w.shape[0], w.shape[1]), (1, 0))
    return lax.dot_general(x, wt, (((3,), (0,)), ((), ())))


def with_stats(conv):
    def f(x, w):
        y = conv(x, w)
        m = jnp.mean(y, axis=(0, 1, 2), dtype=jnp.float32)
        m2 = jnp.mean(jnp.square(y.astype(jnp.float32)), axis=(0, 1, 2))
        return y, m, m2
    return f


def with_apply(conv):  # stats + apply + relu: the full BN train forward
    def f(x, w, res):
        y = conv(x, w)
        m = jnp.mean(y, axis=(0, 1, 2), dtype=jnp.float32)
        m2 = jnp.mean(jnp.square(y.astype(jnp.float32)), axis=(0, 1, 2))
        v = jnp.maximum(m2 - jnp.square(m), 0.0)
        s = lax.rsqrt(v + 1e-5)
        z = jax.nn.relu((y - m.astype(y.dtype)) * s.astype(y.dtype) + res)
        return z, m, m2
    return f


rng = np.random.RandomState(0)
# (B,H,W,Cin,Cout): resnet 1x1 shapes (stage1 conv3, stage2 conv1, stage3 conv1, stage4 conv3)
cases = [(256, 56, 56, 64, 256), (256, 56, 56, 256, 64),
         (256, 28, 28, 512, 128), (256, 14, 14, 1024, 256),
         (256, 7, 7, 512, 2048)]
for (b, h, w_, ci, co) in cases:
    x = jax.device_put(jnp.asarray(rng.randn(b, h, w_, ci), jnp.bfloat16), dev)
    wgt = jax.device_put(jnp.asarray(rng.randn(co, ci, 1, 1) * 0.05, jnp.bfloat16), dev)
    res = jax.device_put(jnp.asarray(rng.randn(b, h, w_, co), jnp.bfloat16), dev)
    t_cc, r1 = timeit(conv_cc, x, wgt)
    t_dot, r2 = timeit(conv_dot, x, wgt)
    ok = np.allclose(np.asarray(r1, np.float32), np.asarray(r2, np.float32),
                     rtol=5e-2, atol=1e-1)
    t_ccs, _ = timeit(with_stats(conv_cc), x, wgt)
    t_dots, _ = timeit(with_stats(conv_dot), x, wgt)
    t_cca, _ = timeit(with_apply(conv_cc), x, wgt, res)
    t_dota, _ = timeit(with_apply(conv_dot), x, wgt, res)
    print(f"[{b}x{h}x{w_} {ci:4d}->{co:4d}] conv {t_cc*1e6:7.1f}us  dot {t_dot*1e6:7.1f}us"
          f" | +stats: conv {t_ccs*1e6:7.1f}  dot {t_dots*1e6:7.1f}"
          f" | +bn+relu+res: conv {t_cca*1e6:7.1f}  dot {t_dota*1e6:7.1f}  match={ok}",
          flush=True)

# stride-2 1x1 (downsample): conv reads full x; slice-then-dot reads 1/4
def conv_cc_s2(x, w):
    return lax.conv_general_dilated(x, w, (2, 2), [(0, 0), (0, 0)],
                                    dimension_numbers=("NHWC", "OIHW", "NHWC"))


def conv_dot_s2(x, w):
    xs = x[:, ::2, ::2, :]
    wt = jnp.transpose(w.reshape(w.shape[0], w.shape[1]), (1, 0))
    return lax.dot_general(xs, wt, (((3,), (0,)), ((), ())))


print("\n-- stride-2 downsample 1x1 --", flush=True)
for (b, h, w_, ci, co) in [(256, 56, 56, 256, 512), (256, 28, 28, 512, 1024),
                           (256, 14, 14, 1024, 2048)]:
    x = jax.device_put(jnp.asarray(rng.randn(b, h, w_, ci), jnp.bfloat16), dev)
    wgt = jax.device_put(jnp.asarray(rng.randn(co, ci, 1, 1) * 0.05, jnp.bfloat16), dev)
    t_cc, r1 = timeit(conv_cc_s2, x, wgt)
    t_dot, r2 = timeit(conv_dot_s2, x, wgt)
    ok = np.allclose(np.asarray(r1, np.float32), np.asarray(r2, np.float32),
                     rtol=5e-2, atol=1e-1)
    print(f"[{b}x{h}x{w_} {ci:4d}->{co:4d}/2] conv {t_cc*1e6:7.1f}us  "
          f"dot {t_dot*1e6:7.1f}us  match={ok}", flush=True)
