"""On-chip probe #7: cvjp2 — BN backward over raw-x reductions.

dgamma = s*sum(dy*x) + (-mean*s)*sum(dy);  dbeta = sum(dy)
dx = gamma*s*(dy - sum_dy/n - xhat*sum_dyxhat/n), xhat recomputed
     elementwise inside the dx pass (x is read there anyway).

Forward identical to base (precomputed scale/shift, one fused pass, no
xhat materialization).  Backward: exactly two passes over (dy, x[, y]).
"""
import sys, functools
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

dev = jax.devices()[0]
print("device:", dev, flush=True)

import bench
from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel
from flexflow_tpu.ops import norm as norm_mod
from flexflow_tpu.ops.norm import BatchNormParams

leg = bench.MANIFEST["legs"]["resnet50"]
sys.path.insert(0, "/root/repo/examples/python/pytorch")
from resnet50_search import ResNet50
B, px = leg["batch"], leg["px"]


def build():
    cfg = FFConfig(batch_size=B, num_devices=1, compute_dtype="bfloat16")
    ff = FFModel(cfg)
    x = ff.create_tensor([B, 3, px, px], name="input")
    (out,) = PyTorchModel(ResNet50(classes=leg["classes"])).torch_to_ff(ff, [x])
    ff.softmax(out)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
               devices=[dev])
    r = np.random.RandomState(0)
    xs = jax.device_put(r.randn(B, 3, px, px).astype(np.float32),
                        ff.executor.input_shardings()["input"])
    ys = jax.device_put(r.randint(0, leg["classes"], B).astype(np.int32),
                        ff.executor.label_sharding())
    for _ in range(3):
        m = ff.train_step({"input": xs}, ys)
    loss = float(m["loss"])
    dt = bench._steady_state(ff, {"input": xs}, ys, 40)
    return dt, loss


orig_forward = norm_mod.BatchNorm.forward


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _bn_apply(x, gamma, beta, mean, invstd, axes, bshape, relu):
    scale = gamma.astype(jnp.float32) * invstd
    shift = beta.astype(jnp.float32) - mean * scale
    y = x * scale.reshape(bshape).astype(x.dtype) \
        + shift.reshape(bshape).astype(x.dtype)
    if relu:
        y = jax.nn.relu(y)
    return y


def _bn_apply_fwd(x, gamma, beta, mean, invstd, axes, bshape, relu):
    y = _bn_apply(x, gamma, beta, mean, invstd, axes, bshape, relu)
    return y, (x, gamma, mean, invstd, y if relu else None)


def _bn_apply_bwd(axes, bshape, relu, res, dy):
    x, gamma, mean, invstd, y = res
    if relu:
        dy = jnp.where(y > 0, dy, jnp.zeros_like(dy))
    n = 1
    for a in axes:
        n *= x.shape[a]
    dyf = dy.astype(jnp.float32)
    sum_dy = jnp.sum(dyf, axis=axes)
    sum_dyx = jnp.sum(dyf * x.astype(jnp.float32), axis=axes)
    s = invstd
    sum_dyxhat = s * sum_dyx - mean * s * sum_dy
    dgamma = sum_dyxhat
    dbeta = sum_dy
    gs = (gamma.astype(jnp.float32) * s).reshape(bshape)
    c1 = (sum_dy / n).reshape(bshape)
    c2 = (sum_dyxhat / n).reshape(bshape)
    ms = (mean * s).reshape(bshape)
    sb = s.reshape(bshape)
    # xhat recomputed inline: x*sb - ms
    dx = (gs * (dyf - c1 - (x.astype(jnp.float32) * sb - ms) * c2)).astype(x.dtype)
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype),
            jnp.zeros_like(mean), jnp.zeros_like(invstd))


_bn_apply.defvjp(_bn_apply_fwd, _bn_apply_bwd)


def cvjp2_forward(self, inputs, weights, *, training=False, rng=None):
    (x,) = inputs
    p: BatchNormParams = self.params
    gamma, beta, rmean, rvar = weights
    nhwc = getattr(self, "_data_layout", "nchw") == "nhwc"
    axes = (0, 1, 2) if nhwc else (0, 2, 3)
    bshape = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
    if not training:
        return orig_forward(self, inputs, weights, training=training, rng=rng)
    mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
    var = jnp.maximum(
        jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes) - jnp.square(mean),
        0.0)
    invstd = lax.rsqrt(var + p.eps)
    new_rmean = p.momentum * rmean + (1 - p.momentum) * mean.astype(rmean.dtype)
    new_rvar = p.momentum * rvar + (1 - p.momentum) * var.astype(rvar.dtype)
    y = _bn_apply(x, gamma, beta, lax.stop_gradient(mean),
                  lax.stop_gradient(invstd), axes, bshape, p.relu)
    return [y, new_rmean, new_rvar]


for name, fwd in [("base", orig_forward), ("cvjp2", cvjp2_forward)]:
    norm_mod.BatchNorm.forward = fwd
    try:
        dt, loss = build()
        print(f"{name:8s}: {dt*1e3:7.2f} ms/step  ({B/dt:6.0f} img/s)  loss={loss:.4f}",
              flush=True)
    except Exception as e:
        print(f"{name:8s}: FAILED {type(e).__name__}: {e}", flush=True)
norm_mod.BatchNorm.forward = orig_forward
