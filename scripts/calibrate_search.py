"""Fit the search's overlap constants from measured step times.

Runs a fixed MLP under dp / dp x tp / tp strategies on the live
backend, measures real steady-state step times, and least-squares fits
`overlap_fraction` / `sync_overlap_fraction` (sim/calibrate.py).  The
fitted constants persist beside the op-cost cache
(~/.cache/flexflow_tpu/overlap_constants.json) and are picked up by
both search entry points on the next run.

On this build's hardware only the hermetic CPU mesh has >1 device (the
tunnel exposes a single chip), so chip runs fit against CPU-mesh
collectives; on a real multi-chip slice the same command refits against
ICI.  Usage:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python scripts/calibrate_search.py [--out PATH]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None, help="constants JSON path")
    p.add_argument("-n", "--num-devices", type=int, default=8)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--hidden", type=int, default=1024)
    args = p.parse_args()

    import jax

    # the axon sitecustomize registers the TPU backend regardless of
    # JAX_PLATFORMS (see .claude/skills/verify/SKILL.md); honor the env
    # var through jax.config BEFORE any device query so a CPU-mesh
    # calibration can never touch the single-tenant chip
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.fftype import ActiMode
    from flexflow_tpu.ops.op import ShardConfig
    from flexflow_tpu.sim.calibrate import (calibrate_overlap,
                                            save_overlap_constants)
    from flexflow_tpu.sim.machine_model import SimpleMachineModel
    from flexflow_tpu.sim.simulator import make_cost_model
    from flexflow_tpu.strategy import Strategy, data_parallel_strategy

    n = args.num_devices
    devices = jax.devices()[:n]
    batch, hidden = args.batch, args.hidden

    def build():
        ff = FFModel(FFConfig(batch_size=batch, num_devices=n))
        x = ff.create_tensor([batch, hidden], name="x")
        t = x
        for i in range(4):
            t = ff.dense(t, hidden, activation=ActiMode.RELU, name=f"fc{i}")
        ff.dense(t, 8, name="head")
        return ff

    def make_inputs(ff):
        rs = np.random.RandomState(0)
        xs = jax.device_put(rs.randn(batch, hidden).astype(np.float32),
                            ff.executor.input_shardings()["x"])
        ys = jax.device_put(rs.randint(0, 8, batch).astype(np.int32),
                            ff.executor.label_sharding())
        return {"x": xs}, ys

    def megatron(tp_degree, dp_degree):
        axes = {}
        if dp_degree > 1:
            axes["data"] = dp_degree
        axes["model"] = tp_degree
        s = Strategy(mesh_axes=axes)
        if dp_degree > 1:
            s.edge_ops["__inputs__"] = [
                ("repartition", {"dim": 0, "degree": dp_degree})]
        for i in range(4):
            s.shard_configs[f"fc{i}"] = ShardConfig(
                channel=tp_degree if i % 2 == 0 else 1,
                reduction=1 if i % 2 == 0 else tp_degree,
            )
        return s

    half = max(2, n // 2)
    strategies = [
        (data_parallel_strategy(1), 1),  # anchors the compute scale
        (data_parallel_strategy(n), n),
        (megatron(half, n // half), n),
        (megatron(n, 1), n),
    ]

    machine = SimpleMachineModel(num_nodes=1, devices_per_node=n)
    cost_model = make_cost_model(FFConfig(num_devices=n), machine)
    fit = calibrate_overlap(build, strategies, devices, machine,
                            cost_model, make_inputs)
    path = save_overlap_constants(fit, args.out)
    print(f"fitted: {fit} -> {path}")


if __name__ == "__main__":
    main()
