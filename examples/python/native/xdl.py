"""XDL recommender demo (reference examples/cpp/XDL, osdi22ae/xdl.sh)."""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_xdl

EMB = (100000, 100000, 100000, 100000)


def main():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    build_xdl(ff, batch_size=cfg.batch_size, embedding_size=EMB,
              sparse_feature_size=64)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 8
    xs = {f"sparse_input_{i}": rng.randint(0, v, size=(n, 1)).astype(np.int32)
          for i, v in enumerate(EMB)}
    ys = rng.rand(n, 2).astype(np.float32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
