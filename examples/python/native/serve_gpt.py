"""Generation serving end to end: train a tiny GPT, build its KV-cache
decode twin, serve /v2/generate over HTTP, and fire concurrent
requests (docs/SERVING.md; the scope the reference's triton/ prototype
never reached).

--serving-mode continuous (the default) runs the iteration-level
scheduler on the paged KV-cache pool (serving/scheduler.py);
--serving-mode static falls back to the whole-scan GenerationBatcher.
Continuous mode always serves through a ServingFront
(serving/front.py) — even --serving-replicas 1 gains the decode-step
watchdog (--serving-step-timeout) and budget-capped restart
supervision; N >= 2 adds queue handoff on replica death (requeues
onto survivors) and /v2/health per-replica liveness aggregation.

Run: python serve_gpt.py [-e STEPS] [-b BATCH]
                         [--serving-mode continuous|static]
                         [--kv-page-size N] [--serving-slots N]
                         [--serving-replicas N]
                         [--serving-step-timeout S]
                         [--serving-roles prefill=1,decode=1]
"""
import argparse
import json
import signal
import threading
import urllib.request

import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.transformer import build_gpt
from flexflow_tpu.serving import GenerationBatcher, GenerationEngine
from flexflow_tpu.serving.server import serve_http

V, S = 64, 24


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--steps", type=int, default=30)
    p.add_argument("-b", "--batch-size", type=int, default=8)
    args, _ = p.parse_known_args()
    serving_cfg = FFConfig.from_args()  # --serving-mode/--kv-page-size/
    b = args.batch_size                 # --serving-slots/--kv-pool-blocks

    # --strategy-store/--compilation-cache flow into the replica's
    # compiles (docs/STORE.md "Replica cold start"): a second process
    # serving the same model restores instead of re-searching
    ff = FFModel(FFConfig(batch_size=b, num_devices=1,
                          strategy_store=serving_cfg.strategy_store,
                          compilation_cache=serving_cfg.compilation_cache))
    build_gpt(ff, batch_size=b, seq_length=S, hidden_size=32,
              num_layers=2, num_heads=4, intermediate_size=64,
              vocab_size=V)
    ff.compile(optimizer=SGDOptimizer(lr=0.5),
               loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY)
    rng = np.random.RandomState(0)
    seq = (rng.randint(0, V, (b, 1))
           + rng.randint(1, 5, (b, 1)) * np.arange(S + 1)) % V
    ids, labels = seq[:, :-1].astype(np.int32), seq[:, 1:].astype(np.int32)
    pos = np.broadcast_to(np.arange(S, dtype=np.int32), (b, S)).copy()
    for i in range(args.steps):
        m = ff.train_step({"input": ids, "positions": pos}, labels)
    print(f"trained {args.steps} steps, loss={float(m['loss']):.3f}")

    grace_displaced = {}
    if serving_cfg.serving_mode == "continuous":
        page = serving_cfg.kv_page_size
        if S % page:  # the demo model's position table is small
            page = 4
        # the front supervises even a SINGLE replica (watchdog +
        # budget-capped restarts — the config.py contract for
        # --serving-step-timeout at replicas=1), so continuous mode
        # always serves through it; --serving-roles upgrades it to a
        # disaggregated prefill/decode fleet (docs/SERVING.md
        # "Disaggregated fleet")
        from flexflow_tpu.serving import build_front

        ff.config.serving_replicas = serving_cfg.serving_replicas
        ff.config.serving_slots = serving_cfg.serving_slots
        ff.config.kv_page_size = page
        ff.config.kv_pool_blocks = serving_cfg.kv_pool_blocks
        # prefix cache + chunked prefill ride into every replica's
        # engine (--prefill-chunk / --no-prefix-cache)
        ff.config.prefill_chunk = serving_cfg.prefill_chunk
        ff.config.prefix_cache = serving_cfg.prefix_cache
        # --paged-kernel {gather,pallas}: which paged-attention
        # formulation every replica's decode step runs (validated +
        # logged at engine build, docs/SERVING.md "Fused paged
        # attention")
        ff.config.paged_kernel = serving_cfg.paged_kernel
        ff.config.serving_step_timeout = \
            serving_cfg.serving_step_timeout
        ff.config.serving_max_restarts = \
            serving_cfg.serving_max_restarts
        ff.config.request_retry_limit = \
            serving_cfg.request_retry_limit
        ff.config.serving_roles = serving_cfg.serving_roles
        ff.config.kv_transfer = serving_cfg.kv_transfer
        ff.config.migration_cost_cap = serving_cfg.migration_cost_cap
        batcher = build_front(ff, serving_cfg)
        # SIGTERM/SIGINT drain instead of kill for ANY front — the
        # grace machinery lives in ServingFront, not the autoscaler
        grace_displaced = batcher.install_grace_handlers(
            deadline_s=serving_cfg.serving_drain_timeout)
        if serving_cfg.serving_max_replicas > 0:
            # --serving-max-replicas N turns the fleet size into a
            # controlled variable (docs/SERVING.md "Autoscaling &
            # drain lifecycle"): scale-up on load, graceful drain
            # when calm
            from flexflow_tpu.serving import ServingAutoscaler

            ServingAutoscaler.from_config(
                batcher, serving_cfg).start()
    else:
        engine = GenerationEngine(ff, batch_size=b)
        batcher = GenerationBatcher(engine, flush_timeout_s=0.02)
    server = serve_http(generator=batcher, port=0, block=False)
    port = server.server_address[1]
    print(f"serving /v2/generate on :{port} "
          f"({serving_cfg.serving_mode} mode)")

    def client(i, out):
        payload = {"prompt": ids[i % b, :4].tolist(), "max_new_tokens": 8}
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v2/generate",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out[i] = json.loads(r.read())["tokens"][0]

    results = {}
    threads = [threading.Thread(target=client, args=(i, results))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert len(results) == 6 and all(len(v) == 12 for v in results.values())
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/v2/stats",
                                timeout=10) as r:
        stats = json.loads(r.read())
    print(f"6 concurrent generations OK; batches_run="
          f"{stats['batches_run']} p95={stats['latency']['p95_ms']}ms")
    server.shutdown()
    batcher.close()
    for signum, handler in grace_displaced.items():
        if handler is not None:  # Ctrl-C kills again post-close
            signal.signal(signum, handler)


if __name__ == "__main__":
    main()
