"""CANDLE-Uno demo (reference examples/cpp/candle_uno,
osdi22ae/candle_uno.sh): multi-tower drug-response regression."""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_candle_uno

INPUT_DIMS = (942, 5270, 2048)


def main():
    import argparse

    # model-size knobs on top of the FFConfig flag set (reference
    # candle_uno.cc defaults are 4192-wide stacks — ~485M params, too
    # big for the CPU smoke tier)
    mp = argparse.ArgumentParser(add_help=False)
    mp.add_argument("--width", type=int, default=4192)
    mp.add_argument("--feature-depth", type=int, default=8)
    margs, rest = mp.parse_known_args()
    cfg = FFConfig.from_args(rest)
    ff = FFModel(cfg)
    build_candle_uno(ff, batch_size=cfg.batch_size,
                     input_dims=list(INPUT_DIMS),
                     dense_layers=[margs.width] * 4,
                     dense_feature_layers=[margs.width] * margs.feature_depth)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 4
    xs = {f"input_{i}": rng.randn(n, d).astype(np.float32)
          for i, d in enumerate(INPUT_DIMS)}
    ys = rng.rand(n, 1).astype(np.float32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
