"""Decoder-only causal LM training demo (GPT-2 shape, next-token loss).

A model family beyond the reference zoo: causal Pallas flash attention
on chip (seq >= 2048), causal ring attention across chips under an sp
strategy.  Trains on a synthetic integer-sequence task (predict the
next token of a modular progression) so the loss decreasing is
meaningful without downloaded data.
"""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.models.transformer import build_gpt


def main():
    cfg = FFConfig.from_args()
    batch, seq, vocab = cfg.batch_size, 128, 256
    ff = FFModel(cfg)
    build_gpt(ff, batch_size=batch, seq_length=seq, hidden_size=128,
              num_layers=2, num_heads=4, intermediate_size=256,
              vocab_size=vocab)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    )
    print(f"mesh: {ff.mesh}")

    rng = np.random.RandomState(0)
    n = batch * 4
    # modular progressions: token[t+1] = token[t] + step (mod vocab)
    start = rng.randint(0, vocab, (n, 1))
    step = rng.randint(1, 8, (n, 1))
    seq_ids = (start + step * np.arange(seq + 1)) % vocab
    ids = seq_ids[:, :-1].astype(np.int32)
    labels = seq_ids[:, 1:].astype(np.int32)  # next token
    positions = np.broadcast_to(np.arange(seq, dtype=np.int32),
                                (n, seq)).copy()
    ff.fit({"input": ids, "positions": positions}, labels,
           epochs=cfg.epochs)

    # generation: the trained model continues the modular progressions
    # (greedy argmax; gpt_generate re-runs the fixed-shape graph per
    # emitted token under the causal mask)
    from flexflow_tpu.models.transformer import gpt_generate

    # prompt batch must match the compiled (dp-sharded) batch
    prompt = ids[:batch, : seq // 2]
    out = gpt_generate(ff, prompt, max_new_tokens=seq // 2)
    want = seq_ids[:batch, : out.shape[1]]
    acc = float(np.mean(out[:, seq // 2:] == want[:, seq // 2:]))
    print(f"generate: continued {out.shape[1] - seq // 2} tokens, "
          f"progression accuracy {acc:.2f}")


if __name__ == "__main__":
    main()
