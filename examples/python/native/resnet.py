"""ResNet-50 training demo (reference examples/cpp/ResNet/resnet.cc).

Synthetic CIFAR-style data; pass --search-budget to let the Unity
search pick a hybrid strategy instead of pure DP.
"""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_resnet50


def main():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    build_resnet50(ff, batch_size=cfg.batch_size, num_classes=10, image_size=64)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 8
    xs = rng.randn(n, 3, 64, 64).astype(np.float32)
    ys = rng.randint(0, 10, size=n).astype(np.int32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
