"""DLRM training demo (reference examples/cpp/DLRM/dlrm.cc).

Synthetic click-through data; the big embedding tables are the
attribute-parallel showcase (vocab-dim sharding -> ICI all-to-all).
"""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_dlrm

EMB = (100000, 100000, 100000, 100000)


def main():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    build_dlrm(ff, batch_size=cfg.batch_size, embedding_size=EMB,
               sparse_feature_size=64, dense_feature_dim=64,
               mlp_bot=[64, 64], mlp_top=[64, 64, 2])
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 8
    xs = {f"sparse_input_{i}": rng.randint(0, v, size=(n, 1)).astype(np.int32)
          for i, v in enumerate(EMB)}
    xs["dense_input"] = rng.randn(n, 64).astype(np.float32)
    ys = rng.rand(n, 2).astype(np.float32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
