"""BERT/Transformer training demo (reference
examples/cpp/Transformer/transformer.cc: 12L/1024h/16heads/seq512 at
b=8 in the Unity AE, scripts/osdi22ae/bert.sh).

`--budget N` lets the search pick a hybrid dp x tp / dp x sp strategy.
"""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_transformer


def main():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    build_transformer(ff, batch_size=cfg.batch_size, seq_length=512,
                      hidden_size=1024, num_layers=12, num_heads=16)
    # per-token scalar head (dense -> 1), MSE — the reference example's
    # synthetic objective shape
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    print(f"strategy: {ff.strategy.mesh_axes}")
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 8
    xs = rng.randn(n, 512, 1024).astype(np.float32)
    ys = rng.rand(n, 512, 1).astype(np.float32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
