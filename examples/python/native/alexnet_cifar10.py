"""BASELINE minimum slice: AlexNet on CIFAR-10, pure data parallel
(reference bootcamp_demo/ff_alexnet_cifar10.py; BASELINE.md row 3).
Uses the keras cifar10 loader (cached real data or synthetic blobs)."""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.keras import datasets
from flexflow_tpu.models import build_alexnet


def main():
    cfg = FFConfig.from_args()
    cfg.only_data_parallel = True
    ff = FFModel(cfg)
    # AlexNet's stride-4 stem needs >=64px inputs; CIFAR is upsampled 2x
    build_alexnet(ff, batch_size=cfg.batch_size, num_classes=10, image_size=64)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    (x_train, y_train), _ = datasets.cifar10.load_data(num_samples=1024)
    xs = x_train.astype(np.float32) / 255.0
    xs = xs.repeat(2, axis=2).repeat(2, axis=3)  # 32 -> 64 px
    ys = y_train.reshape(-1).astype(np.int32)
    ff.fit(xs, ys, epochs=cfg.epochs, shuffle=True)


if __name__ == "__main__":
    main()
