"""InceptionV3 training demo (reference examples/cpp/InceptionV3,
Unity AE scripts/osdi22ae/inception.sh: b=64 budget=10)."""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_inception_v3


def main():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    build_inception_v3(ff, batch_size=cfg.batch_size, num_classes=10,
                       image_size=299)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 4
    xs = rng.randn(n, 3, 299, 299).astype(np.float32)
    ys = rng.randint(0, 10, n).astype(np.int32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
