"""ResNeXt-50 (32x4d) training demo (reference examples/cpp/resnext50,
Unity AE scripts/osdi22ae/resnext-50.sh: b=16 budget=20)."""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_resnext50


def main():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    build_resnext50(ff, batch_size=cfg.batch_size, num_classes=1000,
                    image_size=224)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 4
    xs = rng.randn(n, 3, 224, 224).astype(np.float32)
    ys = rng.randint(0, 1000, n).astype(np.int32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
