"""Mixture-of-Experts demo (reference examples/cpp/mixture_of_experts/moe.cc).

MNIST-shaped synthetic data through the MoE classifier; expert
parallelism shards the stacked expert FFN over the mesh 'ep' axis.
"""
import numpy as np

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models import build_moe_mlp


def main():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    build_moe_mlp(ff, batch_size=cfg.batch_size, input_dim=784,
                  num_classes=10, num_exp=5, num_select=2, hidden_size=64)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 8
    xs = rng.randn(n, 784).astype(np.float32)
    ys = rng.randint(0, 10, size=n).astype(np.int32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
