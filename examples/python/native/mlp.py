"""MLP training demo — user-style script through the public API.

Mirrors the reference's examples/python/native/mnist_mlp.py shape:
build layers, compile (strategy + jitted step), fit, print throughput.
"""
import numpy as np

from flexflow_tpu import (
    ActiMode,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)


def main():
    cfg = FFConfig.from_args()
    cfg.batch_size = 64
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 64], name="x")
    t = ff.dense(x, 256, activation=ActiMode.RELU)
    t = ff.dense(t, 256, activation=ActiMode.RELU)
    t = ff.dense(t, 10)
    t = ff.softmax(t)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    import jax

    print(f"devices: {jax.devices()}")
    print(f"mesh: {ff.mesh}")
    print(f"strategy: {ff.strategy.mesh_axes}")

    rng = np.random.RandomState(42)
    n = 4096
    w_true = rng.randn(64, 10)
    xs = rng.randn(n, 64).astype(np.float32)
    ys = np.argmax(xs @ w_true + 0.1 * rng.randn(n, 10), axis=1).astype(np.int32)
    ff.fit(xs, ys, epochs=5)


if __name__ == "__main__":
    main()
