"""BASELINE north-star config 1: ResNet-50 imported via torch.fx,
strategy discovered by search (reference: fx.torch_to_flexflow +
--budget; BASELINE.md row 1).

torchvision isn't in this image, so the standard bottleneck ResNet-50
is defined inline in plain torch and symbolically traced; run with
`--budget 1000 --search-algo mcmc` to reproduce the north-star setup.
"""
import numpy as np
import torch
import torch.nn as nn

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.torch_frontend.model import PyTorchModel


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * self.expansion
        self.conv1 = nn.Conv2d(cin, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, cout, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU()
        self.down = (
            nn.Sequential(nn.Conv2d(cin, cout, 1, stride, bias=False),
                          nn.BatchNorm2d(cout))
            if stride != 1 or cin != cout else None
        )

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return self.relu(y + idt)


class ResNet50(nn.Module):
    def __init__(self, classes=1000):
        super().__init__()
        self.stem = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn = nn.BatchNorm2d(64)
        self.pool = nn.MaxPool2d(3, 2, 1)
        self.relu = nn.ReLU()
        layers = []
        cin = 64
        for width, blocks, stride in [(64, 3, 1), (128, 4, 2),
                                      (256, 6, 2), (512, 3, 2)]:
            for i in range(blocks):
                layers.append(Bottleneck(cin, width, stride if i == 0 else 1))
                cin = width * Bottleneck.expansion
        self.layers = nn.Sequential(*layers)
        self.avg = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(cin, classes)

    def forward(self, x):
        x = self.pool(self.relu(self.bn(self.stem(x))))
        x = self.layers(x)
        x = self.avg(x)
        x = torch.flatten(x, 1)
        return self.fc(x)


def main():
    cfg = FFConfig.from_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 3, 224, 224], name="input")
    pt = PyTorchModel(ResNet50(classes=1000))
    (out,) = pt.torch_to_ff(ff, [x])
    out = ff.softmax(out)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    print(f"strategy: mesh={ff.strategy.mesh_axes}")
    rng = np.random.RandomState(0)
    n = cfg.batch_size * 4
    xs = rng.randn(n, 3, 224, 224).astype(np.float32)
    ys = rng.randint(0, 1000, n).astype(np.int32)
    ff.fit(xs, ys, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
