"""Sequential Keras MNIST CNN (reference examples/python/keras/
seq_mnist_cnn.py shape): Conv-Conv-Pool -> Dense head.

Run: python seq_mnist_cnn.py [-e EPOCHS] [-b BATCH] [--num-samples N]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import (
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    MaxPooling2D,
    Sequential,
    datasets,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=3)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=2048)
    args, _ = p.parse_known_args()

    (x_train, y_train), _ = datasets.mnist.load_data(args.num_samples)
    x_train = x_train.reshape(len(x_train), 1, 28, 28)
    x_train = x_train.astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32)

    model = Sequential([
        Conv2D(32, (3, 3), strides=(1, 1), padding="same",
               activation="relu"),
        Conv2D(64, (3, 3), strides=(1, 1), padding="same",
               activation="relu"),
        MaxPooling2D((2, 2), strides=(2, 2)),
        Flatten(),
        Dense(128, activation="relu"),
        Dropout(0.25),
        Dense(10, activation="softmax"),
    ], input_shape=(1, 28, 28))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=args.batch_size)
    model.fit(x_train, y_train, epochs=args.epochs)


if __name__ == "__main__":
    main()
