"""Sequential Keras MNIST MLP (reference examples/python/keras/
seq_mnist_mlp.py shape): Dense stack with dropout, Adam optimizer.

Run: python seq_mnist_mlp.py [-e EPOCHS] [-b BATCH] [--num-samples N]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import Dense, Dropout, Sequential, datasets


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=3)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=4096)
    args, _ = p.parse_known_args()

    (x_train, y_train), _ = datasets.mnist.load_data(args.num_samples)
    x_train = x_train.reshape(len(x_train), 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32)

    model = Sequential([
        Dense(256, activation="relu"),
        Dropout(0.2),
        Dense(128, activation="relu"),
        Dense(10, activation="softmax"),
    ], input_shape=(784,))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=args.batch_size)
    model.fit(x_train, y_train, epochs=args.epochs)


if __name__ == "__main__":
    main()
