"""Functional CIFAR-10 CNN with a concat branch join (reference
examples/python/keras/func_cifar10_cnn_concat.py shape): two conv
branches concatenated on the channel dim before the head — exercises
Concatenate through the NHWC layout path.

Run: python func_cifar10_cnn_concat.py [-e EPOCHS] [-b BATCH]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import (
    Concatenate,
    Conv2D,
    Dense,
    Flatten,
    Input,
    MaxPooling2D,
    Model,
    datasets,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=3)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=2048)
    args, _ = p.parse_known_args()

    (x_train, y_train), _ = datasets.cifar10.load_data(args.num_samples)
    x_train = x_train.astype(np.float32) / 255.0
    y_train = y_train.ravel().astype(np.int32)

    inp = Input(shape=(3, 32, 32))
    a = Conv2D(32, (3, 3), padding="same", activation="relu")(inp)
    b = Conv2D(32, (5, 5), padding="same", activation="relu")(inp)
    t = Concatenate(axis=1)([a, b])
    t = MaxPooling2D((2, 2), strides=(2, 2))(t)
    t = Conv2D(64, (3, 3), padding="same", activation="relu")(t)
    t = MaxPooling2D((2, 2), strides=(2, 2))(t)
    t = Flatten()(t)
    t = Dense(128, activation="relu")(t)
    out = Dense(10, activation="softmax")(t)

    model = Model(inp, out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=args.batch_size)
    model.fit(x_train, y_train, epochs=args.epochs)


if __name__ == "__main__":
    main()
