"""Callback tier demo (reference examples/python/keras/callback.py):
LearningRateScheduler + EarlyStopping + ProgbarLogger + VerifyMetrics
on an MNIST MLP.

Run: python callback_demo.py [-e EPOCHS] [-b BATCH]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import (
    Dense,
    EarlyStopping,
    LearningRateScheduler,
    ProgbarLogger,
    Sequential,
    VerifyMetrics,
    datasets,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=6)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=4096)
    p.add_argument("--floor", type=float, default=0.5)
    args, _ = p.parse_known_args()

    (x_train, y_train), _ = datasets.mnist.load_data(args.num_samples)
    x_train = x_train.reshape(len(x_train), 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32)

    model = Sequential([
        Dense(256, activation="relu"),
        Dense(10, activation="softmax"),
    ], input_shape=(784,))
    model.compile(optimizer="sgd",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=args.batch_size)
    model.fit(
        x_train, y_train, epochs=args.epochs, verbose=False,
        callbacks=[
            ProgbarLogger(),
            LearningRateScheduler(lambda epoch, lr: lr * 0.9),
            EarlyStopping(monitor="accuracy", patience=3),
            VerifyMetrics(monitor="accuracy", floor=args.floor,
                          each_epoch=True),
        ],
    )


if __name__ == "__main__":
    main()
