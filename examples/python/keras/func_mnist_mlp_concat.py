"""Functional Keras MNIST MLP with branch concat (reference
examples/python/keras/func_mnist_mlp_concat.py shape): two Dense
branches off one input, concatenated into the head — the branchy graph
the merge rewrites and the strategy search care about.

Run: python func_mnist_mlp_concat.py [-e EPOCHS] [-b BATCH]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import (
    Concatenate,
    Dense,
    Input,
    Model,
    datasets,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=3)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=4096)
    args, _ = p.parse_known_args()

    (x_train, y_train), _ = datasets.mnist.load_data(args.num_samples)
    x_train = x_train.reshape(len(x_train), 784).astype(np.float32) / 255.0
    y_train = y_train.astype(np.int32)

    inp = Input(shape=(784,))
    a = Dense(128, activation="relu")(inp)
    b = Dense(128, activation="sigmoid")(inp)
    t = Concatenate(axis=1)([a, b])
    t = Dense(64, activation="relu")(t)
    out = Dense(10, activation="softmax")(t)

    model = Model(inp, out)
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=args.batch_size)
    model.fit(x_train, y_train, epochs=args.epochs)


if __name__ == "__main__":
    main()
