"""Reuters topic MLP over bag-of-words features (reference
examples/python/keras/seq_reuters_mlp.py shape) — exercises the
dependency-free keras.preprocessing pipeline: Tokenizer-style
sequences -> pad_sequences -> binary term matrix.

Run: python seq_reuters_mlp.py [-e EPOCHS] [-b BATCH]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import Dense, Dropout, Sequential, datasets
from flexflow_tpu.keras.preprocessing import pad_sequences

NUM_WORDS = 2000


def to_binary_matrix(seqs: np.ndarray, n: int) -> np.ndarray:
    m = np.zeros((len(seqs), n), np.float32)
    for i, row in enumerate(seqs):
        m[i, row[row > 0]] = 1.0
    return m


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=4)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=2048)
    args, _ = p.parse_known_args()

    (x_train, y_train), _ = datasets.reuters.load_data(
        num_words=NUM_WORDS, maxlen=200, num_samples=args.num_samples)
    # normalize ragged/over-length rows through the preprocessing API
    x_train = pad_sequences(list(x_train), maxlen=200, padding="post",
                            truncating="post")
    x_train = to_binary_matrix(x_train, NUM_WORDS)
    y_train = y_train.astype(np.int32)

    model = Sequential([
        Dense(512, activation="relu"),
        Dropout(0.5),
        Dense(datasets.reuters.num_classes, activation="softmax"),
    ], input_shape=(NUM_WORDS,))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=args.batch_size)
    model.fit(x_train, y_train, epochs=args.epochs)


if __name__ == "__main__":
    main()
