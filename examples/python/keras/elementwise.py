"""Elementwise-merge layers end to end (the reference's
examples/python/keras/elementwise_*.py + unary.py tier, folded into
one runnable script): Add / Subtract / Multiply branches training on a
synthetic regression target.

Run: python elementwise.py [-e EPOCHS] [-b BATCH]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import (
    Add,
    Concatenate,
    Dense,
    Input,
    Model,
    Multiply,
    Subtract,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=4)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=4096)
    args, _ = p.parse_known_args()

    rng = np.random.RandomState(0)
    x = rng.randn(args.num_samples, 32).astype(np.float32)
    y = (np.sin(x[:, :1]) + x[:, 1:2] * x[:, 2:3]).astype(np.float32)

    inp = Input(shape=(32,))
    a = Dense(64, activation="relu")(inp)
    b = Dense(64, activation="tanh")(inp)
    merged = Concatenate(axis=1)([
        Add()([a, b]), Subtract()([a, b]), Multiply()([a, b]),
    ])
    t = Dense(32, activation="relu")(merged)
    out = Dense(1)(t)

    model = Model(inp, out)
    model.compile(optimizer="adam", loss="mean_squared_error",
                  metrics=["mean_squared_error"],
                  batch_size=args.batch_size)
    model.fit(x, y, epochs=args.epochs)


if __name__ == "__main__":
    main()
