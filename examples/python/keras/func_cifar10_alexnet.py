"""Functional Keras CIFAR-10 AlexNet (reference examples/python/keras/
func_cifar10_alexnet.py shape, scaled to 32px inputs).

Run: python func_cifar10_alexnet.py [-e EPOCHS] [-b BATCH]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import (
    Conv2D,
    Dense,
    Dropout,
    Flatten,
    Input,
    MaxPooling2D,
    Model,
    datasets,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=4)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=2048)
    args, _ = p.parse_known_args()

    (x_train, y_train), _ = datasets.cifar10.load_data(args.num_samples)
    x_train = x_train.astype(np.float32) / 255.0
    y_train = y_train.ravel().astype(np.int32)

    inp = Input(shape=(3, 32, 32))
    t = Conv2D(64, (5, 5), strides=(1, 1), padding="same",
               activation="relu")(inp)
    t = MaxPooling2D((3, 3), strides=(2, 2))(t)
    t = Conv2D(192, (5, 5), strides=(1, 1), padding="same",
               activation="relu")(t)
    t = MaxPooling2D((3, 3), strides=(2, 2))(t)
    t = Conv2D(384, (3, 3), padding="same", activation="relu")(t)
    t = Conv2D(256, (3, 3), padding="same", activation="relu")(t)
    t = Conv2D(256, (3, 3), padding="same", activation="relu")(t)
    t = MaxPooling2D((3, 3), strides=(2, 2))(t)
    t = Flatten()(t)
    t = Dense(1024, activation="relu")(t)
    t = Dropout(0.5)(t)
    t = Dense(1024, activation="relu")(t)
    out = Dense(10, activation="softmax")(t)

    model = Model(inp, out)
    model.compile(optimizer="sgd",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=args.batch_size)
    model.fit(x_train, y_train, epochs=args.epochs)


if __name__ == "__main__":
    main()
