"""Functional Keras CIFAR-10 CNN with callbacks.

Mirrors the reference's examples/python/keras/func_cifar10_cnn.py
(Conv-Conv-Pool x2 -> Dense head trained with SGD) plus the callback
tier (LearningRateScheduler + EarlyStopping).  The dataset loader
serves the real CIFAR-10 when a cache is present and class-structured
synthetic images otherwise (no-egress images).

Run: python func_cifar10_cnn.py [-e EPOCHS] [-b BATCH] [--num-samples N]
"""
import argparse

import numpy as np

from flexflow_tpu.keras import (
    Conv2D,
    Dense,
    EarlyStopping,
    Flatten,
    Input,
    LearningRateScheduler,
    MaxPooling2D,
    Model,
    datasets,
)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=4)
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--num-samples", type=int, default=2048)
    args, _ = p.parse_known_args()

    (x_train, y_train), _ = datasets.cifar10.load_data(args.num_samples)
    x_train = x_train.astype(np.float32) / 255.0
    y_train = y_train.ravel().astype(np.int32)

    inp = Input(shape=(3, 32, 32))
    t = Conv2D(32, (3, 3), padding="same", activation="relu")(inp)
    t = Conv2D(32, (3, 3), padding="same", activation="relu")(t)
    t = MaxPooling2D((2, 2), strides=(2, 2))(t)
    t = Conv2D(64, (3, 3), padding="same", activation="relu")(t)
    t = Conv2D(64, (3, 3), padding="same", activation="relu")(t)
    t = MaxPooling2D((2, 2), strides=(2, 2))(t)
    t = Flatten()(t)
    t = Dense(256, activation="relu")(t)
    out = Dense(10, activation="softmax")(t)

    model = Model(inp, out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], batch_size=args.batch_size)

    callbacks = [
        LearningRateScheduler(lambda epoch, lr: lr * (0.9 ** epoch)),
        EarlyStopping(monitor="accuracy", patience=3),
    ]
    model.fit(x_train, y_train, epochs=args.epochs, callbacks=callbacks)


if __name__ == "__main__":
    main()
