"""Smoke tier: train every example on the hermetic CPU mesh.

Mirrors the role of the reference's tests/multi_gpu_tests.sh (train ~40
example models end-to-end in CI, DP-only, small budgets): each script
runs in its own process on an 8-device virtual CPU mesh with tiny
epochs/batch so the whole tier finishes in minutes, and a non-zero exit
from any script fails the tier.

Run: python examples/run_all.py [--only SUBSTR] [--timeout SECONDS]
"""
import argparse
import os
import re
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# The JAX_PLATFORMS env var alone is not enough on hosts whose
# sitecustomize registers a TPU backend at interpreter startup
# (tests/conftest.py documents the trap); force the config before the
# script's first jax use, then hand over argv.
_BOOTSTRAP = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
path = sys.argv[1]
sys.argv = sys.argv[1:]
with open(path) as fh:
    code = fh.read()
exec(compile(code, path, "exec"), {"__name__": "__main__"})
"""

# script (relative to examples/) -> extra args tuned for a CPU smoke
# run.  Native scripts run DP-only, mirroring multi_gpu_tests.sh's
# batch=64*GPUs DP-only convention (an unbounded Unity search on the
# wide multi-tower models runs for tens of minutes on CPU); the
# pytorch leg keeps a small MCMC budget so the search path stays
# exercised end-to-end.
_DP = ["--only-data-parallel"]
SCRIPTS = {
    "python/native/mlp.py": ["-e", "2", *_DP],
    "python/native/alexnet_cifar10.py": ["-e", "1", "-b", "32", *_DP],
    "python/native/resnet.py": ["-e", "1", "-b", "8", *_DP],
    "python/native/inception.py": ["-e", "1", "-b", "8", *_DP],
    "python/native/resnext.py": ["-e", "1", "-b", "8", *_DP],
    "python/native/dlrm.py": ["-e", "1", "-b", "32", *_DP],
    "python/native/xdl.py": ["-e", "1", "-b", "32", *_DP],
    "python/native/candle_uno.py": [
        "-e", "1", "-b", "16", "--width", "512", "--feature-depth", "4", *_DP,
    ],
    "python/native/moe.py": ["-e", "1", "-b", "32", *_DP],
    "python/native/transformer.py": ["-e", "1", "-b", "8", *_DP],
    "python/native/gpt.py": ["-e", "1", "-b", "8", *_DP],
    "python/native/serve_gpt.py": ["-e", "5", "-b", "4", *_DP],
    "python/keras/seq_mnist_mlp.py": ["-e", "1", "--num-samples", "512"],
    "python/keras/func_cifar10_cnn.py": [
        "-e", "1", "-b", "32", "--num-samples", "256",
    ],
    "python/keras/func_cifar10_cnn_concat.py": [
        "-e", "1", "-b", "32", "--num-samples", "256",
    ],
    "python/keras/seq_mnist_cnn.py": [
        "-e", "1", "-b", "32", "--num-samples", "256",
    ],
    "python/keras/func_mnist_mlp_concat.py": [
        "-e", "1", "--num-samples", "512",
    ],
    "python/keras/func_cifar10_alexnet.py": [
        "-e", "1", "-b", "32", "--num-samples", "256",
    ],
    "python/keras/seq_reuters_mlp.py": [
        "-e", "1", "-b", "32", "--num-samples", "256",
    ],
    "python/keras/callback_demo.py": [
        "-e", "2", "--num-samples", "512", "--floor", "0.05",
    ],
    "python/keras/elementwise.py": [
        "-e", "1", "-b", "32", "--num-samples", "512",
    ],
    "python/pytorch/resnet50_search.py": [
        "-e", "1", "-b", "4", "--budget", "4",
    ],
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--only", default="", help="substring filter")
    p.add_argument("--timeout", type=int, default=900)
    args = p.parse_args()

    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    failed = []
    for rel, extra in SCRIPTS.items():
        if args.only and args.only not in rel:
            continue
        script = os.path.join(HERE, rel)
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _BOOTSTRAP, script, *extra],
                env=env, capture_output=True, text=True,
                timeout=args.timeout,
            )
            rc, err = proc.returncode, proc.stderr
        except subprocess.TimeoutExpired:
            rc, err = -1, f"timed out after {args.timeout}s"
        dt = time.perf_counter() - t0
        status = "ok" if rc == 0 else f"FAIL rc={rc}"
        print(f"{rel:45s} {dt:7.1f}s  {status}", flush=True)
        if rc != 0:
            failed.append(rel)
            sys.stderr.write((err or "")[-2000:] + "\n")
    if failed:
        print(f"\n{len(failed)} failed: {failed}")
        sys.exit(1)
    print("\nall examples passed")


if __name__ == "__main__":
    main()
