#!/usr/bin/env python
"""Offline two-tier checkpoint verifier (docs/RESILIENCE.md "Durable
offload & host-loss recovery").

Walks the LOCAL checkpoint directory and (with --remote) the REMOTE
mirror tier, re-checks every per-leaf crc32 manifest, validates the
`LATEST` / `REMOTE_LATEST` pointers, and reports local/remote
divergence (a step present in both tiers whose manifests disagree —
the mirror must be byte-identical to the verified local publish).

Exit status is CI-friendly:

    0  every checkpoint verified, pointers intact, tiers agree
    1  corruption, a dangling pointer, or tier divergence was found
    2  usage / I/O error (directory missing, bad URI)

Usage:
    python tools/checkpoint_fsck.py CKPT_DIR [--remote URI] [--json]
"""
from __future__ import annotations

import argparse
import io
import json
import os
import sys
from typing import Dict, List, Optional

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as a plain script from anywhere
    sys.path.insert(0, _REPO)

from flexflow_tpu.checkpoint import _STEP_DIR_RE, _leaf_crc  # noqa: E402
from flexflow_tpu.resilience.offload import (  # noqa: E402
    RemoteCheckpointStore,
)
from flexflow_tpu.store.blobstore import (  # noqa: E402
    BlobStoreError,
    blobstore_from_uri,
)


def _verify_leaves(state_bytes: bytes, manifest: Dict) -> List[str]:
    """crc-check every manifest leaf against npz bytes; returns the
    list of problems (empty == verified)."""
    problems: List[str] = []
    leaves = manifest.get("leaves")
    if not isinstance(leaves, dict):
        return ["manifest has no leaves table"]
    try:
        with np.load(io.BytesIO(state_bytes)) as data:
            names = set(data.files)
            for key, spec in leaves.items():
                if key not in names:
                    problems.append(f"leaf {key!r} in manifest but not in "
                                    "state.npz")
                    continue
                crc = _leaf_crc(data[key])
                if crc != spec.get("crc32"):
                    problems.append(
                        f"leaf {key!r} crc32 {crc:#010x} != manifest "
                        f"{spec.get('crc32')}"
                    )
            for extra in sorted(names - set(leaves)):
                problems.append(f"leaf {extra!r} in state.npz but not in "
                                "manifest")
    except Exception as e:  # torn zip/npz
        problems.append(f"state.npz undecodable: {type(e).__name__}: {e}")
    return problems


def fsck_local(directory: str) -> Dict:
    """Verify every local step dir + the LATEST pointer."""
    report: Dict = {"tier": "local", "directory": directory, "steps": {},
                    "latest": None, "problems": []}
    if not os.path.isdir(directory):
        report["problems"].append(f"directory {directory} does not exist")
        return report
    steps = []
    for name in sorted(os.listdir(directory)):
        m = _STEP_DIR_RE.fullmatch(name)
        if not m:
            continue
        step = int(m.group(1))
        steps.append(step)
        path = os.path.join(directory, name)
        problems: List[str] = []
        try:
            with open(os.path.join(path, "meta.json")) as f:
                json.load(f)
        except Exception as e:
            problems.append(f"meta.json unreadable: {e}")
        manifest = None
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            problems.append("manifest.json missing (pre-manifest "
                            "checkpoint: integrity unverifiable)")
        except Exception as e:
            problems.append(f"manifest.json unreadable: {e}")
        if manifest is not None:
            try:
                with open(os.path.join(path, "state.npz"), "rb") as f:
                    state = f.read()
            except OSError as e:
                problems.append(f"state.npz unreadable: {e}")
            else:
                problems += _verify_leaves(state, manifest)
        report["steps"][step] = {"ok": not problems, "problems": problems}
    latest_path = os.path.join(directory, "LATEST")
    try:
        with open(latest_path) as f:
            latest = int(f.read().strip())
        report["latest"] = latest
        entry = report["steps"].get(latest)
        if entry is None:
            report["problems"].append(
                f"LATEST pointer dangles: names step {latest} but no such "
                "step dir exists"
            )
        elif not entry["ok"]:
            report["problems"].append(
                f"LATEST pointer names step {latest}, which failed "
                "verification"
            )
    except FileNotFoundError:
        if steps:
            report["problems"].append(
                "LATEST pointer missing (directory written by pre-pointer "
                "code?)"
            )
    except ValueError as e:
        report["problems"].append(f"LATEST pointer unparseable: {e}")
    return report


def fsck_remote(uri: str) -> Dict:
    """Verify every remote mirrored step + the REMOTE_LATEST pointer."""
    report: Dict = {"tier": "remote", "uri": uri, "steps": {},
                    "latest": None, "problems": [], "manifests": {}}
    remote = RemoteCheckpointStore(blobstore_from_uri(uri))
    try:
        steps = remote.list_steps()
    except BlobStoreError as e:
        report["problems"].append(f"remote tier unlistable: {e}")
        return report
    for step in steps:
        try:
            manifest = remote.verify_step(step)
            report["steps"][step] = {"ok": True, "problems": []}
            report["manifests"][step] = manifest
        except Exception as e:
            report["steps"][step] = {"ok": False,
                                     "problems": [str(e)]}
    latest = remote.read_latest()
    report["latest"] = latest
    if latest is not None:
        entry = report["steps"].get(latest)
        if entry is None:
            report["problems"].append(
                f"REMOTE_LATEST pointer dangles: names step {latest} but "
                "no such mirrored step exists"
            )
        elif not entry["ok"]:
            report["problems"].append(
                f"REMOTE_LATEST pointer names step {latest}, which failed "
                "verification"
            )
    elif steps:
        report["problems"].append(
            "REMOTE_LATEST pointer missing/unreadable while mirrored "
            "steps exist"
        )
    return report


def diff_tiers(local_dir: str, local_rep: Dict, remote_rep: Dict
               ) -> List[str]:
    """Steps present in BOTH tiers must carry identical manifests (the
    mirror uploads the exact verified local bytes); any disagreement is
    divergence — somebody wrote one tier without the other."""
    problems: List[str] = []
    for step, remote_manifest in sorted(remote_rep["manifests"].items()):
        local_entry = local_rep["steps"].get(step)
        if local_entry is None or not local_entry["ok"]:
            continue  # nothing verified to compare against
        path = os.path.join(local_dir, f"step_{step:08d}", "manifest.json")
        try:
            with open(path) as f:
                local_manifest = json.load(f)
        except Exception:
            continue
        l_leaves = local_manifest.get("leaves", {})
        r_leaves = remote_manifest.get("leaves", {})
        if set(l_leaves) != set(r_leaves):
            problems.append(
                f"step {step}: local and remote manifests list different "
                "leaves"
            )
            continue
        for key in sorted(l_leaves):
            if l_leaves[key].get("crc32") != r_leaves[key].get("crc32"):
                problems.append(
                    f"step {step}: leaf {key!r} diverges (local crc "
                    f"{l_leaves[key].get('crc32')} != remote "
                    f"{r_leaves[key].get('crc32')})"
                )
    return problems


def _render(report: Dict) -> str:
    lines = []
    for tier in report["tiers"]:
        name = tier["tier"]
        where = tier.get("directory") or tier.get("uri")
        lines.append(f"[{name}] {where}")
        for step, entry in sorted(tier["steps"].items()):
            mark = "ok" if entry["ok"] else "CORRUPT"
            lines.append(f"  step {step:>8}  {mark}")
            for p in entry["problems"]:
                lines.append(f"      - {p}")
        pointer = "LATEST" if name == "local" else "REMOTE_LATEST"
        lines.append(f"  {pointer} = {tier['latest']}")
        for p in tier["problems"]:
            lines.append(f"  ! {p}")
    for p in report["divergence"]:
        lines.append(f"! divergence: {p}")
    lines.append("clean" if report["clean"] else "PROBLEMS FOUND")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("directory", help="local checkpoint directory")
    p.add_argument("--remote", default=None,
                   help="remote tier URI (file:///path or a bare path)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the machine-readable report")
    args = p.parse_args(argv)

    local_rep = fsck_local(args.directory)
    if (not os.path.isdir(args.directory)) and args.remote is None:
        print(f"error: {args.directory} is not a directory",
              file=sys.stderr)
        return 2
    tiers = [local_rep]
    divergence: List[str] = []
    if args.remote is not None:
        try:
            remote_rep = fsck_remote(args.remote)
        except (ValueError, NotImplementedError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        tiers.append(remote_rep)
        divergence = diff_tiers(args.directory, local_rep, remote_rep)
        remote_rep.pop("manifests", None)  # internal to the diff

    clean = (
        not divergence
        and all(not t["problems"] for t in tiers)
        and all(e["ok"] for t in tiers for e in t["steps"].values())
    )
    report = {"tiers": tiers, "divergence": divergence, "clean": clean}
    if args.as_json:
        json.dump(report, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(_render(report))
    return 0 if clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
