#!/usr/bin/env python
"""Promote shipped strategy artifacts (examples/strategies/*.json) into
a StrategyStore so runs hit the store instead of needing
--import-strategy plumbing.

The store is content-addressed by (graph signature, mesh fingerprint,
simulator version), so an import must rebuild the FRONTEND graph the
artifact was searched for and recompute the key under the SAME config
the consuming run will compile with.  The builder registry is
scripts/search_strategies.JOBS — the repo's single source of truth for
shipped artifacts — so the promoted keys match what
`FFModel.compile` computes for those models.

Usage (hermetic CPU mesh, matching the artifacts' 8-device search):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/strategy_store_import.py --store /path/to/store [-n 8]

`Strategy.load` / --import-strategy keep working unchanged — the store
entry is an additional, verified, key-addressed copy.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, ".."))
sys.path.insert(0, os.path.join(_HERE, "..", "scripts"))


def import_default_jobs(store_root: str, strategies_dir: str,
                        num_devices: int, overwrite: bool = False):
    """Promote each JOBS artifact; returns [(name, digest, written)]."""
    import search_strategies as ss  # scripts/ single source of truth

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.store import StrategyStore, store_key_for

    store = StrategyStore(store_root)
    results = []
    for name, build, batch, cfg_kw in ss.JOBS:
        path = os.path.join(strategies_dir, f"{name}.json")
        if not os.path.exists(path):
            print(f"skip {name}: no artifact at {path}")
            continue
        # the cfg the artifact was searched under (search_strategies
        # _searched): budget 500 + the job's flags — the key must match
        # what a consuming compile with that cfg computes
        cfg = FFConfig(batch_size=batch, num_devices=num_devices,
                       search_budget=500, **cfg_kw)
        ff = FFModel(cfg)
        getattr(ss, build)(ff, cfg)  # frontend graph only — no compile
        key = store_key_for(cfg, ff.layers, num_devices)
        written = store.import_strategy(
            key, path, created_at=time.time(), overwrite=overwrite,
            search_stats={"imported_job": name},
        )
        results.append((name, key.digest, written))
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--store", required=True,
                   help="store root (FLEXFLOW_TPU_STORE_DIR of the fleet)")
    p.add_argument("--strategies",
                   default=os.path.join(_HERE, "..", "examples",
                                        "strategies"),
                   help="directory of shipped *.json artifacts")
    p.add_argument("-n", "--num-devices", type=int, default=8,
                   help="device count the artifacts were searched for")
    p.add_argument("--overwrite", action="store_true",
                   help="replace existing entries for matching keys")
    args = p.parse_args(argv)

    results = import_default_jobs(
        args.store, os.path.abspath(args.strategies), args.num_devices,
        overwrite=args.overwrite,
    )
    for name, digest, written in results:
        state = "imported" if written else "kept existing"
        print(f"{name}: {state} -> {digest[:16]}")
    if not results:
        print("nothing imported", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
