#!/usr/bin/env python
"""Per-request critical-path breakdown from run_telemetry.jsonl span
records (obs/reqtrace.py; docs/OBSERVABILITY.md "Request tracing").

Usage:
    python tools/trace_analyze.py <run_telemetry.jsonl | trace-dir>
        [--slowest N] [--check]

Groups `"kind":"span"` records into per-request trace trees, buckets
each tree's time into the serving phases (queue / dispatch / prefill /
migration / kv_adopt / decode / spec_verify — the last from the shared
verify-round batch spans the per-request decode span references), and
prints p50/p99 per phase plus the N slowest requests with their phase
split.  --check exits non-zero when any tree is disconnected (orphan
spans / missing root) — the serving_trace bench leg's assertion runs
through the same functions.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

#: phase bucket order for reports (spec_verify is informational — it
#: overlaps the decode phase rather than extending the critical path)
PHASES = ("queue", "dispatch", "prefill", "migration", "kv_adopt",
          "decode", "spec_verify")


def load_records(path: str) -> List[Dict]:
    """Parse a telemetry JSONL (or the trace dir holding one).  Bad
    lines are skipped here — telemetry_summary.py owns strict torn-
    line reporting; this tool only needs the span records."""
    if os.path.isdir(path):
        path = os.path.join(path, "run_telemetry.jsonl")
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def build_traces(records: List[Dict]
                 ) -> Tuple[Dict[str, List[Dict]], Dict[int, Dict]]:
    """(traces, batch_spans): spans grouped by trace_id, plus the
    shared batch spans (trace_id None — prefill_chunk / decode_step /
    spec_verify dispatches) indexed by span_id for ref resolution."""
    traces: Dict[str, List[Dict]] = {}
    batch: Dict[int, Dict] = {}
    for rec in records:
        if rec.get("kind") != "span":
            continue
        tid = rec.get("trace_id")
        if tid is None:
            batch[rec["span_id"]] = rec
        else:
            traces.setdefault(tid, []).append(rec)
    return traces, batch


def check_connected(spans: List[Dict]) -> Tuple[bool, List[Dict]]:
    """One tree per trace: exactly one root (parent_id None) and every
    other span's parent present IN this trace.  Returns (ok, orphans)
    — cross-replica spans (kv_adopt arriving via the FFKV frame
    header's wire dict) must resolve like any local child."""
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans if s.get("parent_id") is None]
    orphans = [s for s in spans
               if s.get("parent_id") is not None
               and s["parent_id"] not in ids]
    return len(roots) == 1 and not orphans, orphans


def phase_breakdown(spans: List[Dict], batch: Dict[int, Dict]
                    ) -> Dict[str, float]:
    """Phase -> microseconds for one trace.  Direct phase spans sum
    by name (a requeued request owns several queue spans); the
    spec_verify bucket sums the shared verify-round batch spans this
    trace's phase spans reference by span id."""
    out: Dict[str, float] = {}
    for s in spans:
        name = s["name"]
        if name in PHASES:
            out[name] = out.get(name, 0.0) + float(s.get("dur_us", 0.0))
        for ref in (s.get("args") or {}).get("batch_spans") or ():
            b = batch.get(ref)
            if b is not None and b["name"] == "spec_verify":
                out["spec_verify"] = (out.get("spec_verify", 0.0)
                                      + float(b.get("dur_us", 0.0)))
    return out


def trace_total_us(spans: List[Dict]) -> float:
    roots = [s for s in spans if s.get("parent_id") is None]
    if roots:
        return float(roots[0].get("dur_us", 0.0))
    return sum(float(s.get("dur_us", 0.0)) for s in spans
               if s["name"] in PHASES)


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def analyze(records: List[Dict]) -> Dict:
    """The report data main() renders (and tests/bench assert on):
    per-phase percentiles, per-trace totals + breakdowns, and the
    connectivity verdicts."""
    traces, batch = build_traces(records)
    per_phase: Dict[str, List[float]] = {p: [] for p in PHASES}
    rows = []
    disconnected = []
    for tid, spans in traces.items():
        ok, orphans = check_connected(spans)
        if not ok:
            disconnected.append((tid, orphans))
        phases = phase_breakdown(spans, batch)
        for p, us in phases.items():
            per_phase[p].append(us)
        root = next((s for s in spans if s.get("parent_id") is None),
                    None)
        rows.append({
            "trace_id": tid,
            "total_us": trace_total_us(spans),
            "spans": len(spans),
            "phases": phases,
            "args": dict((root or {}).get("args") or {}),
            "connected": ok,
        })
    rows.sort(key=lambda r: -r["total_us"])
    n_spans = sum(r["spans"] for r in rows)
    summary = {}
    for p in PHASES:
        vals = sorted(per_phase[p])
        if vals:
            summary[p] = {
                "traces": len(vals),
                "p50_us": _pct(vals, 0.50),
                "p99_us": _pct(vals, 0.99),
                "total_us": sum(vals),
            }
    return {
        "traces": len(rows),
        "spans": n_spans,
        "batch_spans": len(batch),
        "phases": summary,
        "requests": rows,
        "disconnected": disconnected,
    }


def _ms(us: float) -> str:
    return f"{us / 1e3:.2f}"


def render(report: Dict, slowest: int = 3) -> str:
    lines = []
    n = report["traces"]
    spans_per = report["spans"] / n if n else 0.0
    lines.append(
        f"Request traces: {n}  (spans {report['spans']}, "
        f"{spans_per:.1f}/trace; shared batch spans "
        f"{report['batch_spans']})")
    if report["disconnected"]:
        lines.append(
            f"DISCONNECTED traces: "
            f"{[tid for tid, _ in report['disconnected']]}")
    if report["phases"]:
        lines.append("")
        lines.append(f"{'phase':<12}{'traces':>8}{'p50 ms':>10}"
                     f"{'p99 ms':>10}{'total ms':>11}")
        for p in PHASES:
            st = report["phases"].get(p)
            if not st:
                continue
            lines.append(
                f"{p:<12}{st['traces']:>8}{_ms(st['p50_us']):>10}"
                f"{_ms(st['p99_us']):>10}{_ms(st['total_us']):>11}")
    top = report["requests"][:max(0, slowest)]
    if top:
        lines.append("")
        lines.append(f"Slowest {len(top)}:")
        for r in top:
            args = r["args"]
            ok = args.get("ok")
            head = (f"  {r['trace_id']}  total {_ms(r['total_us'])} ms"
                    f"  spans={r['spans']}")
            if ok is not None:
                head += f"  ok={ok}"
            if not r["connected"]:
                head += "  DISCONNECTED"
            lines.append(head)
            split = "  |  ".join(
                f"{p} {_ms(r['phases'][p])}"
                for p in PHASES if p in r["phases"])
            if split:
                lines.append(f"    {split}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="run_telemetry.jsonl or the trace dir")
    p.add_argument("--slowest", type=int, default=3, metavar="N",
                   help="show the N slowest requests (default 3)")
    p.add_argument("--check", action="store_true",
                   help="exit non-zero if any trace tree is "
                        "disconnected (orphan spans / missing root)")
    args = p.parse_args(argv)
    try:
        records = load_records(args.path)
    except FileNotFoundError as e:
        print(f"error: no telemetry file at {e}", file=sys.stderr)
        return 1
    report = analyze(records)
    if report["traces"] == 0:
        print("no span records found (tracing off, or sampled out "
              "via --trace-sample)")
        return 0
    sys.stdout.write(render(report, slowest=args.slowest))
    if args.check and report["disconnected"]:
        print(f"error: {len(report['disconnected'])} disconnected "
              "trace tree(s)", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
