"""Render the substitution-rule catalog to Graphviz dot (reference
tools/substitutions_to_dot: rule-file visualization).

Each rule renders as source-pattern -> target-pattern: an op of its
type rewritten into the sharded form with the parallel ops the kind
implies (channel -> Repartition/Combine on the channel dim,
reduction -> Replicate/Reduce, attribute/expert -> attribute-dim
Repartition + AllToAll boundaries).

  PYTHONPATH=. python tools/substitutions_to_dot.py [rules.json] > subs.dot
"""
import sys

KIND_DECOR = {
    "channel": ("Repartition[out-ch]", "Combine[out-ch]"),
    "reduction": ("Replicate", "Reduce"),
    "attribute": ("Repartition[attr]", "AllToAll"),
    "expert": ("Repartition[expert]", "AllToAll"),
}


def to_dot(xfers) -> str:
    lines = [
        "digraph substitutions {",
        "  rankdir=LR;",
        '  node [shape=box, fontname="monospace"];',
    ]
    for i, x in enumerate(xfers):
        pre, post = KIND_DECOR[x.kind]
        src = f"s{i}"
        lines += [
            f'  subgraph cluster_{i} {{ label="{x.name}";',
            f'    {src}_in  [label="{x.op_type.value}"];',
            f'    {src}_pre  [label="{pre}", style=dashed];',
            f'    {src}_op   [label="{x.op_type.value} (sharded: {x.kind})"];',
            f'    {src}_post [label="{post}", style=dashed];',
            f"    {src}_in -> {src}_pre -> {src}_op -> {src}_post;",
            "  }",
        ]
    lines.append("}")
    return "\n".join(lines)


def main():
    from flexflow_tpu.pcg.substitution import (
        generate_all_pcg_xfers,
        load_substitution_rules,
    )

    if len(sys.argv) > 1:
        xfers = load_substitution_rules(sys.argv[1])
    else:
        xfers = generate_all_pcg_xfers()
    print(to_dot(xfers))


if __name__ == "__main__":
    main()
