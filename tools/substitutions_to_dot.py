"""Render the substitution-rule catalog to Graphviz dot (reference
tools/substitutions_to_dot: rule-file visualization).

Each rule renders as source-pattern -> target-pattern: an op of its
type rewritten into the sharded form with the parallel ops the kind
implies (channel -> Repartition/Combine on the channel dim,
reduction -> Replicate/Reduce, attribute/expert -> attribute-dim
Repartition + AllToAll boundaries).

  PYTHONPATH=. python tools/substitutions_to_dot.py [rules.json] > subs.dot
"""
import sys

KIND_DECOR = {
    "channel": ("Repartition[out-ch]", "Combine[out-ch]"),
    "reduction": ("Replicate", "Reduce"),
    "attribute": ("Repartition[attr]", "AllToAll"),
    "expert": ("Repartition[expert]", "AllToAll"),
}


def to_dot(xfers) -> str:
    lines = [
        "digraph substitutions {",
        "  rankdir=LR;",
        '  node [shape=box, fontname="monospace"];',
    ]
    for i, x in enumerate(xfers):
        pre, post = KIND_DECOR[x.kind]
        src = f"s{i}"
        lines += [
            f'  subgraph cluster_{i} {{ label="{x.name}";',
            f'    {src}_in  [label="{x.op_type.value}"];',
            f'    {src}_pre  [label="{pre}", style=dashed];',
            f'    {src}_op   [label="{x.op_type.value} (sharded: {x.kind})"];',
            f'    {src}_post [label="{post}", style=dashed];',
            f"    {src}_in -> {src}_pre -> {src}_op -> {src}_post;",
            "  }",
        ]
    lines.append("}")
    return "\n".join(lines)


def taso_to_dot(rules, limit=None) -> str:
    """Render parsed TASO pattern rules (pcg/taso.py) — srcOp and dstOp
    subgraphs side by side, externals as ellipses (reference
    tools/substitutions_to_dot over substitutions/graph_subst_3_v2.json)."""
    lines = [
        "digraph taso_rules {",
        "  rankdir=LR;",
        '  node [shape=box, fontname="monospace"];',
    ]
    for i, r in enumerate(rules if limit is None else rules[:limit]):
        lines.append(f'  subgraph cluster_{i} {{ label="{r.name}";')
        for side, ops in (("s", r.src_ops), ("d", r.dst_ops)):
            ext_seen = set()
            for j, op in enumerate(ops):
                params = ",".join(f"{k[3:]}={v}" for k, v in op.params)
                lines.append(
                    f'    r{i}{side}{j} [label="{op.type[3:]}'
                    + (f'\\n{params}' if params else "")
                    + ('"];' if side == "s" else '", style=filled, '
                       'fillcolor=lightgrey];')
                )
                for ref in op.inputs:
                    if ref.op_id < 0:
                        ext = f"r{i}{side}x{-ref.op_id}"
                        if ref.op_id not in ext_seen:
                            ext_seen.add(ref.op_id)
                            lines.append(
                                f'    {ext} [label="in{-ref.op_id}", '
                                "shape=ellipse];")
                        lines.append(f"    {ext} -> r{i}{side}{j};")
                    else:
                        lines.append(
                            f"    r{i}{side}{ref.op_id} -> r{i}{side}{j};")
        for m in r.mapped_outputs:
            lines.append(
                f"    r{i}s{m.src_op_id} -> r{i}d{m.dst_op_id} "
                "[style=dotted, constraint=false];")
        lines.append("  }")
    lines.append("}")
    return "\n".join(lines)


def main():
    from flexflow_tpu.pcg.substitution import (
        generate_all_pcg_xfers,
        load_substitution_rules,
    )

    if len(sys.argv) > 1:
        path = sys.argv[1]
        from flexflow_tpu.pcg.taso import (is_taso_rule_file,
                                           parse_rule_collection)

        if is_taso_rule_file(path):
            limit = int(sys.argv[2]) if len(sys.argv) > 2 else None
            print(taso_to_dot(parse_rule_collection(path), limit))
            return
        xfers = load_substitution_rules(path)
    else:
        xfers = generate_all_pcg_xfers()
    print(to_dot(xfers))


if __name__ == "__main__":
    main()
