#!/usr/bin/env python
"""Convert a binary TASO substitution catalog (.pb) to its JSON twin.

Drop-in for the reference's tools/protobuf_to_json converter
(protobuf_to_json.cc) with no protobuf dependency: the wire bytes are
decoded by flexflow_tpu/pcg/taso_pb.py and written in the exact same
JSON schema (rules named taso_rule_{i}, 2-space indent).

Usage: python tools/pb_to_json.py <input.pb> <output.json>
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv):
    if len(argv) != 3:
        print(f"Usage: {argv[0]} <input-file> <output-file>",
              file=sys.stderr)
        return 1
    from flexflow_tpu.pcg.taso_pb import pb_to_dict

    d = pb_to_dict(argv[1])
    print(f"Loaded {len(d['rule'])} rules.")
    with open(argv[2], "w") as f:
        json.dump(d, f, indent=2)
        f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
