#!/usr/bin/env python
"""Static drift check: every metric the code can emit under the
`serving/`, `resilience/`, `store/`, or `comm/` groups must be named
in docs/OBSERVABILITY.md.

Scans flexflow_tpu/ for registry call sites — `counter("...")` /
`gauge("...")` / `histogram("...")` literals (f-strings included) plus
the per-module `_count("...")` / `_observe_ms("...")` helpers whose
group prefix the module fixes — and fails listing every name the doc
does not mention.  Dynamic name segments (`{...}` in an f-string)
match the docs' `<i>`-placeholder convention
(`serving/replica/<i>/queue_depth`) or a documented wildcard family
(`serving/autoscaler_*`).  Wired in as a tier-1 test
(tests/test_metric_docs.py) so the metric table cannot drift.

Usage: python tools/check_metric_docs.py [--root REPO]   (exit 0/1)
"""
from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List, Set, Tuple

GROUPS = ("serving/", "resilience/", "store/", "comm/")

#: direct registry call sites; \s* spans the line break of a wrapped
#: call like registry.gauge(\n    f"serving/replica/{id}/queue_depth"
_CALL = re.compile(
    r'\.(?:counter|gauge|histogram)\(\s*(f?)"([^"\n]+)"')

#: module-fixed helper prefix, e.g. `self.registry.counter(
#: f"store/{name}")` inside `def _count` — calls `self._count("hits")`
#: then emit store/hits
_HELPER_DEF = re.compile(
    r'\.(?:counter|histogram)\(\s*f"('
    + "|".join(g.rstrip("/") for g in GROUPS)
    + r')/\{name\}"')

_HELPER_CALL = re.compile(
    r'self\.(_count|_observe_ms)\(\s*"([^"\n]+)"')

#: a dynamic f-string segment
_DYN = re.compile(r"\{[^}]*\}")


def emitted_names(root: str) -> Dict[str, List[str]]:
    """name -> [files emitting it] for every grouped metric name the
    package can emit.  Fully dynamic leaves (`serving/{name}`: the
    helper-def pattern itself) are excluded — their concrete names
    come in through the helper-call scan."""
    out: Dict[str, List[str]] = {}
    pkg = os.path.join(root, "flexflow_tpu")
    for dirpath, _, files in os.walk(pkg):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            with open(path) as f:
                text = f.read()
            for is_f, name in _CALL.findall(text):
                if not name.startswith(GROUPS):
                    continue
                if is_f and _DYN.sub("", name) in (
                        g for g in GROUPS):
                    continue  # helper def: serving/{name} itself
                out.setdefault(name, []).append(rel)
            prefixes = set(_HELPER_DEF.findall(text))
            if len(prefixes) == 1:
                prefix = next(iter(prefixes))
                for _, leaf in _HELPER_CALL.findall(text):
                    out.setdefault(f"{prefix}/{leaf}",
                                   []).append(rel)
    return out


def documented_forms(doc_text: str) -> Tuple[Set[str], List[str]]:
    """(exact names incl. <i>-placeholder forms, wildcard prefixes).
    A wildcard must extend past its group prefix — the group headers
    (`serving/*`) document the namespace, not any particular metric."""
    names = set(re.findall(
        r"((?:" + "|".join(g.rstrip("/") for g in GROUPS)
        + r")/[A-Za-z0-9_/<>.-]+)", doc_text))
    wild = []
    for m in re.findall(
            r"((?:" + "|".join(g.rstrip("/") for g in GROUPS)
            + r")/[A-Za-z0-9_/<>.-]*)\*", doc_text):
        if m not in GROUPS:  # bare group headers don't count
            wild.append(m)
    return names, wild


def is_documented(name: str, names: Set[str],
                  wild: List[str]) -> bool:
    norm = _DYN.sub("<i>", name)
    if name in names or norm in names:
        return True
    # the literal head of a templated name may fall in a documented
    # wildcard family (serving/autoscaler_{action} ~ autoscaler_*)
    head = name.split("{", 1)[0]
    return any(head.startswith(w) or (("{" in name) and w.startswith(head))
               for w in wild)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = p.parse_args(argv)
    doc_path = os.path.join(args.root, "docs", "OBSERVABILITY.md")
    with open(doc_path) as f:
        doc_text = f.read()
    emitted = emitted_names(args.root)
    names, wild = documented_forms(doc_text)
    missing = {n: files for n, files in sorted(emitted.items())
               if not is_documented(n, names, wild)}
    if missing:
        print(f"{len(missing)} emitted metric name(s) missing from "
              "docs/OBSERVABILITY.md:", file=sys.stderr)
        for n, files in missing.items():
            print(f"  {n}  (emitted by {', '.join(sorted(set(files)))})",
                  file=sys.stderr)
        return 1
    print(f"ok: {len(emitted)} emitted metric name(s) all documented "
          f"({len(names)} doc names, {len(wild)} wildcard families)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
