#!/usr/bin/env python
"""Render a run_telemetry.jsonl into a step-time / compile-time /
search / resilience / fidelity table.

Usage:
    python tools/telemetry_summary.py <run_telemetry.jsonl | trace-dir>
        [--allow-torn-tail]

Accepts either the JSONL itself or the --trace-dir directory containing
it.  Metrics are cumulative snapshots, so for re-drained runs the
latest record per name wins (ties broken by file order).  See
docs/OBSERVABILITY.md for the record schema.

Unreadable lines are an ERROR, not a silent skip: a summary that
quietly dropped records would misreport the run.  A killed run may
legitimately leave torn line(s) at the FILE TAIL — --allow-torn-tail
tolerates exactly those (reported to stderr with a count); corruption
anywhere else always exits non-zero.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Tuple


class TornTelemetryError(Exception):
    """Unparseable run_telemetry.jsonl line(s).  `bad` holds
    (lineno, detail) pairs; `tail_only` is True when every bad line
    sits after the last good record (a killed-run torn tail)."""

    def __init__(self, bad: List[Tuple[int, str]], tail_only: bool):
        self.bad = bad
        self.tail_only = tail_only
        where = "tail" if tail_only else "mid-file"
        super().__init__(
            f"{len(bad)} unreadable telemetry line(s) ({where}): "
            f"line(s) {[ln for ln, _ in bad]}")


def load_records(path: str, allow_torn_tail: bool = False
                 ) -> Tuple[List[Dict], List[Tuple[int, str]]]:
    """(records, torn_lines).  Raises TornTelemetryError on any
    unparseable line, unless every bad line is at the file tail AND
    `allow_torn_tail` is set — then the torn tail is returned for the
    caller to report."""
    if os.path.isdir(path):
        path = os.path.join(path, "run_telemetry.jsonl")
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    out: List[Dict] = []
    bad: List[Tuple[int, str]] = []
    last_good = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
                last_good = lineno
            except json.JSONDecodeError as e:
                bad.append((lineno, str(e)))
    tail_only = bool(bad) and all(ln > last_good for ln, _ in bad)
    if bad and not (allow_torn_tail and tail_only):
        raise TornTelemetryError(bad, tail_only)
    return out, bad


def latest_by_name(records: List[Dict], kinds) -> Dict[str, Dict]:
    """Last record per name among `kinds` (cumulative snapshots: the
    newest drain supersedes older ones)."""
    out: Dict[str, Dict] = {}
    for rec in records:
        if rec.get("kind") in kinds and "name" in rec:
            out[rec["name"]] = rec
    return out


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _section(title: str, rows: List[tuple]) -> str:
    if not rows:
        return ""
    w = max(len(k) for k, _ in rows) + 2
    lines = [title, "-" * len(title)]
    lines += [f"{k:<{w}}{_fmt(v)}" for k, v in rows]
    return "\n".join(lines) + "\n"


def summarize(records: List[Dict]) -> str:
    metrics = latest_by_name(records, {"counter", "gauge", "histogram"})
    fidelity = [r for r in records if r.get("kind") == "fidelity"]
    events = [r for r in records if r.get("kind") == "event"]
    out: List[str] = []

    step = metrics.get("fit/step_ms")
    rows = []
    if step:
        rows += [
            ("steps", step.get("count", 0)),
            ("dispatch ms mean", step.get("mean", 0.0)),
            ("dispatch ms min/max",
             f"{_fmt(step.get('min', 0.0))} / {_fmt(step.get('max', 0.0))}"),
        ]
    epoch = metrics.get("fit/epoch_s")
    if epoch:
        rows.append(("epoch s mean", epoch.get("mean", 0.0)))
    tput = metrics.get("fit/throughput_sps")
    if tput:
        rows.append(("throughput samples/s", tput.get("value", 0.0)))
    out.append(_section("Steps", rows))

    rows = [
        (name.split("/", 1)[1] if "/" in name else name,
         rec.get("value", 0.0))
        for name, rec in sorted(metrics.items())
        if name.startswith("compile/")
    ]
    out.append(_section("Compile (ms)", rows))

    rows = [
        (name.split("/", 2)[-1], rec.get("value", 0.0))
        for name, rec in sorted(metrics.items())
        if name.startswith("search/")
    ]
    out.append(_section("Search", rows))

    rows = []
    for name, rec in sorted(metrics.items()):
        if not name.startswith("store/") or name.startswith("store/remote_"):
            continue  # remote_* renders under Durability
        short = name.split("/", 1)[1]
        if rec.get("kind") == "histogram":
            # lookup latency: render the streaming summary
            rows.append((
                short,
                f"n={rec.get('count', 0)} mean={_fmt(rec.get('mean', 0.0))} "
                f"min={_fmt(rec.get('min', 0.0))} "
                f"max={_fmt(rec.get('max', 0.0))}",
            ))
        else:
            rows.append((short, rec.get("value", 0.0)))
    out.append(_section("Store", rows))

    rows = [
        (name.split("/", 1)[1], rec.get("value", 0.0))
        for name, rec in sorted(metrics.items())
        if name.startswith("resilience/")
        and not name.startswith("resilience/offload_")
    ]
    out.append(_section("Resilience", rows))

    # the durable offload tier (docs/RESILIENCE.md "Durable offload &
    # host-loss recovery"): upload/verify/degradation counters from the
    # checkpoint mirror plus the strategy store's fleet-mirror traffic
    rows = []
    for name, rec in sorted(metrics.items()):
        if not (name.startswith("resilience/offload_")
                or name.startswith("store/remote_")):
            continue
        short = name.split("/", 1)[1]
        if rec.get("kind") == "histogram":
            rows.append((
                short,
                f"n={rec.get('count', 0)} mean={_fmt(rec.get('mean', 0.0))} "
                f"min={_fmt(rec.get('min', 0.0))} "
                f"max={_fmt(rec.get('max', 0.0))}",
            ))
        else:
            rows.append((short, rec.get("value", 0.0)))
    out.append(_section("Durability", rows))

    # per-tier predicted comm split (topology subsystem,
    # docs/TOPOLOGY.md): ICI vs DCN bytes/time for the compiled
    # strategy's placement — zero DCN on single-slice runs
    rows = [
        (name.split("/", 1)[1], rec.get("value", 0.0))
        for name, rec in sorted(metrics.items())
        if name.startswith("comm/")
    ]
    out.append(_section("Comm", rows))

    # searched-remat memory split (docs/PERF.md "Searched
    # rematerialization"): per-run saved-activation bytes under the
    # compiled plan + the recompute seconds the plan pays
    rows = [
        (name.split("/", 1)[1], rec.get("value", 0.0))
        for name, rec in sorted(metrics.items())
        if name.startswith("mem/") or name == "compute/recompute_s"
    ]
    out.append(_section("Memory", rows))

    rows = []
    # prefix cache (docs/SERVING.md "Prefix cache & chunked prefill"):
    # one composite line ahead of the raw serving/* rows
    hits = metrics.get("serving/prefix_hits")
    hit_toks = metrics.get("serving/prefix_hit_tokens")
    if hits is not None or hit_toks is not None:
        shared = metrics.get("serving/kv_shared_blocks", {})
        evicted = metrics.get("serving/prefix_evictions", {})
        rows.append((
            "prefix cache",
            f"hits={int((hits or {}).get('value', 0))} "
            f"hit_tokens={int((hit_toks or {}).get('value', 0))} "
            f"shared_blocks={int(shared.get('value', 0))} "
            f"evictions={int(evicted.get('value', 0))}",
        ))
    # tensor-parallel replicas (docs/SERVING.md "Tensor-parallel
    # replicas"): one composite line when a multi-chip engine
    # registered its mesh geometry
    tp = metrics.get("serving/tp_degree")
    if tp is not None:
        chips = metrics.get("serving/tp_chips", {})
        per_blk = metrics.get("serving/tp_kv_block_bytes_per_chip", {})
        per_pool = metrics.get("serving/tp_kv_pool_bytes_per_chip", {})
        rows.append((
            "tensor parallel",
            f"degree={int(tp.get('value', 1))} "
            f"chips={int(chips.get('value', 1))} "
            f"kv_block_bytes_per_chip={int(per_blk.get('value', 0))} "
            f"kv_pool_bytes_per_chip={int(per_pool.get('value', 0))}",
        ))
    # disaggregated fleet (docs/SERVING.md "Disaggregated fleet"):
    # one composite line when the dispatcher ever costed a handoff —
    # migrate/re-prefill decisions plus the KV stream counters
    mig = metrics.get("serving/disagg_migrate_decisions")
    rep = metrics.get("serving/disagg_reprefill_decisions")
    if mig is not None or rep is not None:
        done = metrics.get("serving/kv_migration_done", {})
        failed = metrics.get("serving/kv_migration_failed", {})
        mig_bytes = metrics.get("serving/kv_migration_bytes", {})
        mig_blocks = metrics.get("serving/kv_migration_blocks", {})
        rows.append((
            "disaggregated fleet",
            f"migrate={int((mig or {}).get('value', 0))} "
            f"reprefill={int((rep or {}).get('value', 0))} "
            f"migrations_done={int(done.get('value', 0))} "
            f"failed={int(failed.get('value', 0))} "
            f"bytes={int(mig_bytes.get('value', 0))} "
            f"blocks={int(mig_blocks.get('value', 0))}",
        ))
    # fused paged kernel (docs/SERVING.md "Fused paged attention"):
    # one composite read-traffic line when the kernel formulation ran
    blocks = metrics.get("serving/paged_kernel_blocks_read")
    if blocks is not None:
        read = metrics.get("serving/paged_kernel_bytes_read", {})
        avoided = metrics.get("serving/paged_dense_bytes_avoided", {})
        rows.append((
            "paged kernel",
            f"blocks_read={int(blocks.get('value', 0))} "
            f"bytes_read={int(read.get('value', 0))} "
            f"dense_bytes_avoided={int(avoided.get('value', 0))}",
        ))
    # speculative decoding (docs/SERVING.md "Speculative decoding"):
    # accept rate + tokens/round + verify-round rate in one line
    prop = metrics.get("serving/spec_proposed")
    if prop is not None:
        acc = metrics.get("serving/spec_accepted", {})
        rounds = metrics.get("serving/spec_rounds", {})
        per_round = metrics.get("serving/spec_accepted_per_round", {})
        rps = metrics.get("serving/spec_rounds_per_s", {})
        n_prop = int(prop.get("value", 0))
        n_acc = int(acc.get("value", 0))
        rate = n_acc / n_prop if n_prop else 0.0
        rows.append((
            "speculative",
            f"accept_rate={rate:.3f} ({n_acc}/{n_prop}) "
            f"tokens/round={_fmt(per_round.get('mean', 0.0))} "
            f"rounds={int(rounds.get('value', 0))} "
            f"rounds/s={_fmt(rps.get('value', 0.0))}",
        ))
    for name, rec in sorted(metrics.items()):
        if not name.startswith("serving/"):
            continue
        short = name.split("/", 1)[1]
        if rec.get("kind") == "histogram":
            # SLO histograms (ttft_ms, per_token_ms, kv occupancy):
            # render the streaming summary, not a bare value
            rows.append((
                short,
                f"n={rec.get('count', 0)} mean={_fmt(rec.get('mean', 0.0))} "
                f"min={_fmt(rec.get('min', 0.0))} "
                f"max={_fmt(rec.get('max', 0.0))}",
            ))
        else:
            rows.append((short, rec.get("value", 0.0)))
    out.append(_section("Serving", rows))

    # request traces (obs/reqtrace.py, docs/OBSERVABILITY.md "Request
    # tracing"): span counts plus the top-3 slowest requests with
    # their per-phase split — the full report is trace_analyze.py
    try:
        from . import trace_analyze as _ta
    except ImportError:  # run as a script: tools/ itself is on sys.path
        import trace_analyze as _ta
    treport = _ta.analyze(records)
    rows = []
    if treport["traces"]:
        rows += [
            ("traces recorded", treport["traces"]),
            ("spans", treport["spans"]),
            ("spans/trace",
             round(treport["spans"] / treport["traces"], 1)),
            ("shared batch spans", treport["batch_spans"]),
        ]
        if treport["disconnected"]:
            rows.append(("DISCONNECTED trees",
                         len(treport["disconnected"])))
        for r in treport["requests"][:3]:
            split = " ".join(
                f"{p}={r['phases'][p] / 1e3:.2f}ms"
                for p in _ta.PHASES if p in r["phases"])
            rows.append((
                f"slowest {r['trace_id']}",
                f"total={r['total_us'] / 1e3:.2f}ms {split}"))
    out.append(_section("Tracing", rows))

    rows = []
    for rec in fidelity:
        rows += [
            ("source", rec.get("source", "?")),
            ("predicted step ms", rec.get("predicted_step_ms")),
            ("measured step ms", rec.get("measured_step_ms")),
            ("predicted / measured", rec.get("predicted_vs_measured")),
            ("mesh", json.dumps(rec.get("mesh_axes", {}))),
            ("calibrated", rec.get("calibrated", False)),
        ]
    out.append(_section("Fidelity", rows))

    logs = [r for r in events if r.get("name") == "log"]
    if logs:
        lines = ["Log events", "----------"]
        for r in logs[-20:]:
            f = r.get("fields", {})
            lines.append(
                f"[{f.get('level', '?')}] {f.get('logger', '?')}: "
                f"{f.get('message', '')}"
            )
        out.append("\n".join(lines) + "\n")

    body = "\n".join(s for s in out if s)
    return body if body.strip() else "no telemetry records found\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="run_telemetry.jsonl or the trace dir")
    p.add_argument("--allow-torn-tail", action="store_true",
                   help="tolerate unreadable line(s) at the FILE TAIL "
                        "(a killed run's torn write); mid-file "
                        "corruption still exits non-zero")
    args = p.parse_args(argv)
    try:
        records, torn = load_records(
            args.path, allow_torn_tail=args.allow_torn_tail)
    except FileNotFoundError as e:
        print(f"error: no telemetry file at {e}", file=sys.stderr)
        return 1
    except TornTelemetryError as e:
        hint = (" (re-run with --allow-torn-tail to tolerate a "
                "killed run's torn tail)" if e.tail_only else "")
        print(f"error: {e}{hint}", file=sys.stderr)
        return 1
    if torn:
        print(f"warning: skipped {len(torn)} torn tail line(s): "
              f"{[ln for ln, _ in torn]}", file=sys.stderr)
    sys.stdout.write(summarize(records))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
