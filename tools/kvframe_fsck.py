#!/usr/bin/env python
"""Offline FFKV frame verifier (docs/SERVING.md "KV block streaming").

Walks a directory of dumped FFKV wire frames (or explicit frame
files) — the block streams a KVMigrator ships between replicas for
prefix migration and mid-decode handoff — and audits each one without
any engine:

  * magic / header-length / JSON header decodable, version supported;
  * schema sane: every block payload's length matches the schema's
    array shapes x dtypes;
  * per-block crc32 re-checked against the raw payload bytes;
  * token-page boundary chain: every page except the last holds
    exactly page_size tokens (only the handoff tail may be partial),
    and the declared payload lengths tile the frame exactly — no
    trailing or missing bytes.

Exit status is CI-friendly (tools/checkpoint_fsck.py convention):

    0  every frame verified
    1  a torn, truncated, or inconsistent frame was found
    2  usage / I/O error (path missing, no frames)

Usage:
    python tools/kvframe_fsck.py PATH [PATH ...] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import zlib
from typing import Dict, List, Optional

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as a plain script from anywhere
    sys.path.insert(0, _REPO)

from flexflow_tpu.serving.kv_transfer import (  # noqa: E402
    _MAGIC,
    _VERSION,
)


def fsck_frame(data: bytes) -> List[str]:
    """Audit one FFKV frame's bytes; returns the list of problems
    (empty == verified).  Mirrors unpack_kv_blocks' trust boundary
    but keeps walking past the first torn block so a report names
    EVERY problem, and additionally enforces the boundary chain the
    adopting pool relies on (full pages except an optional tail)."""
    problems: List[str] = []
    if len(data) < 8:
        return [f"frame too short for magic+header length "
                f"({len(data)} bytes)"]
    if data[:4] != _MAGIC:
        return [f"bad magic {data[:4]!r} (want {_MAGIC!r})"]
    (hlen,) = struct.unpack("<I", data[4:8])
    if len(data) < 8 + hlen:
        return [f"truncated header: {hlen} declared, "
                f"{len(data) - 8} present"]
    try:
        hdr = json.loads(data[8:8 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        return [f"mangled header: {type(e).__name__}: {e}"]
    if hdr.get("v") != _VERSION:
        problems.append(f"version {hdr.get('v')} != {_VERSION}")
    page = int(hdr.get("page_size", 0) or 0)
    if page < 1:
        problems.append(f"page_size {hdr.get('page_size')!r} invalid")
        return problems
    pages = hdr.get("pages")
    crcs = hdr.get("crcs")
    sizes = hdr.get("block_bytes")
    schema = hdr.get("schema")
    if not (isinstance(pages, list) and isinstance(crcs, list)
            and isinstance(sizes, list) and isinstance(schema, list)):
        problems.append("header missing pages/crcs/block_bytes/schema")
        return problems
    if not len(pages) == len(crcs) == len(sizes):
        problems.append(
            f"header tables disagree: {len(pages)} pages, "
            f"{len(crcs)} crcs, {len(sizes)} block_bytes")
        return problems
    # schema-implied payload size: each block carries every schema
    # array once, concatenated in schema order
    want_bytes = None
    try:
        want_bytes = sum(
            int(np.prod(s["shape"])) * np.dtype(s["dtype"]).itemsize
            for s in schema)
    except Exception as e:  # noqa: BLE001 — unresolvable schema
        problems.append(f"schema undecodable: {type(e).__name__}: {e}")
    # boundary chain: only the LAST page may be partial (the handoff
    # tail); an interior short page would desynchronize adoption
    for j, toks in enumerate(pages):
        if not isinstance(toks, list) or not toks:
            problems.append(f"block {j}: empty/invalid token page")
        elif len(toks) > page:
            problems.append(
                f"block {j}: {len(toks)} tokens exceed page_size "
                f"{page}")
        elif len(toks) < page and j != len(pages) - 1:
            problems.append(
                f"block {j}: interior partial page ({len(toks)} of "
                f"{page} tokens) breaks the boundary chain")
    # payload walk: crc + declared length per block, exact tiling
    off = 8 + hlen
    for j, (crc, nbytes) in enumerate(zip(crcs, sizes)):
        raw = data[off:off + int(nbytes)]
        off += int(nbytes)
        if len(raw) != int(nbytes):
            problems.append(
                f"block {j}: payload truncated ({len(raw)} of "
                f"{nbytes} bytes)")
            continue
        if want_bytes is not None and int(nbytes) != want_bytes:
            problems.append(
                f"block {j}: payload {nbytes} bytes != schema-implied "
                f"{want_bytes}")
        if zlib.crc32(raw) != crc:
            problems.append(
                f"block {j}: crc32 {zlib.crc32(raw):#010x} != header "
                f"{int(crc) & 0xFFFFFFFF:#010x}")
    if off < len(data):
        problems.append(
            f"frame has {len(data) - off} trailing byte(s) past the "
            "declared payloads")
    return problems


def fsck_paths(paths: List[str]) -> Dict:
    """Audit every .ffkv frame under the given files/directories."""
    report: Dict = {"frames": {}, "problems": []}
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            found = sorted(
                os.path.join(path, n) for n in os.listdir(path)
                if n.endswith(".ffkv"))
            if not found:
                report["problems"].append(
                    f"directory {path} holds no .ffkv frames")
            files.extend(found)
        elif os.path.isfile(path):
            files.append(path)
        else:
            report["problems"].append(f"path {path} does not exist")
    for fp in files:
        try:
            with open(fp, "rb") as f:
                data = f.read()
        except OSError as e:
            report["frames"][fp] = {"ok": False,
                                    "problems": [f"unreadable: {e}"]}
            continue
        problems = fsck_frame(data)
        report["frames"][fp] = {"ok": not problems, "bytes": len(data),
                                "problems": problems}
    return report


def _render(report: Dict) -> str:
    lines = []
    for fp, entry in sorted(report["frames"].items()):
        mark = "ok" if entry["ok"] else "CORRUPT"
        lines.append(f"  {fp}  {mark}")
        for p in entry["problems"]:
            lines.append(f"      - {p}")
    for p in report["problems"]:
        lines.append(f"  ! {p}")
    lines.append("clean" if report["clean"] else "PROBLEMS FOUND")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+",
                   help=".ffkv frame files or directories of them")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the machine-readable report")
    args = p.parse_args(argv)

    if not any(os.path.exists(path) for path in args.paths):
        print(f"error: no such path(s): {', '.join(args.paths)}",
              file=sys.stderr)
        return 2
    report = fsck_paths(args.paths)
    if not report["frames"] and not report["problems"]:
        print("error: nothing to verify", file=sys.stderr)
        return 2
    report["clean"] = (
        not report["problems"]
        and bool(report["frames"])
        and all(e["ok"] for e in report["frames"].values())
    )
    if args.as_json:
        json.dump(report, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(_render(report))
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
